"""Remote league proxy: the League surface over HTTP.

Learners and actors on other hosts construct a RemoteLeague with the league
server's address and use it exactly like an in-process League (the subset of
methods the worker roles call). Retries ride the shared resilience fabric
(``resilience.retry_call`` with a per-proxy circuit breaker) instead of the
hand-rolled loop each transport used to carry — one observable policy for
every cross-process link (role of the reference's requests retry adapters,
reference: distar/ctools/worker/actor/actor_comm.py:59-60, adapter.py:56-63).
"""
from __future__ import annotations

from typing import Optional

from ..resilience import CircuitBreaker, CommError, FatalError, RetryPolicy, retry_call
from .api import league_request


class RemoteLeague:
    def __init__(self, host: str, port: int, retries: int = 5, backoff_s: float = 0.5,
                 timeout: float = 30.0, policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.host, self.port = host, port
        self._timeout = timeout
        self._policy = policy or RetryPolicy(
            max_attempts=retries, backoff_base_s=backoff_s, backoff_max_s=10.0
        )
        # breaker shared across routes: the peer is one process — if jobs
        # are unreachable, results are too
        self._breaker = breaker or CircuitBreaker(op="league")

    def _call_once(self, route: str, body: dict):
        out = league_request(self.host, self.port, route, body, timeout=self._timeout)
        if out.get("code") == 0:
            return out["info"]
        # the server answered: this is an application error, not peer death
        raise FatalError(f"league {route} error: {out}")

    def _call(self, route: str, body: dict):
        try:
            return retry_call(
                self._call_once, route, body,
                op=f"league:{route}", policy=self._policy, breaker=self._breaker,
            )
        except CommError as e:
            raise CommError(
                f"league {route} unreachable after {self._policy.max_attempts} tries",
                op=e.op, cause=e,
            ) from e

    # --- the League surface used by workers ---
    def register_learner(self, player_id: str, ip: str = "", port: int = 0, rank: int = 0,
                         world_size: int = 1) -> dict:
        return self._call(
            "register_learner",
            {"player_id": player_id, "ip": ip, "port": port, "rank": rank,
             "world_size": world_size},
        )

    def learner_send_train_info(self, player_id: str, train_steps: int,
                                checkpoint_path: Optional[str] = None) -> dict:
        return self._call(
            "learner_send_train_info",
            {"player_id": player_id, "train_steps": train_steps,
             **({"checkpoint_path": checkpoint_path} if checkpoint_path else {})},
        )

    def actor_ask_for_job(self, request: Optional[dict] = None) -> dict:
        return self._call("actor_ask_for_job", request or {"job_type": "train"})

    def actor_send_result(self, result: dict) -> bool:
        return bool(self._call("actor_send_result", result))


class RemoteLeagueService:
    """Proxy for the coordinator-hosted league runtime (league/runtime/).

    The runtime routes live on the COORDINATOR (so they ride its HA
    journal), not the standalone league server — this proxy therefore
    speaks ``comm.coordinator_request`` (leadership failover, epoch
    fencing, ambiguous-ack typing) rather than ``league_request``. The
    method surface mirrors :class:`~.runtime.service.LeagueService` one to
    one; bodies carry the idempotency handles (``learner_id``, ``seq``,
    match ``key``) that make retries safe on the journaled side.

    ``addr`` is a single ``host:port`` or an HA comma list
    (``"h1:p1,h2:p2"`` — requests follow leadership across failovers).
    """

    def __init__(self, addr: str, timeout: float = 30.0, policy=None):
        self.addr = addr
        self._timeout = timeout
        self._policy = policy

    def _call(self, route: str, body: dict):
        from ..comm.coordinator import coordinator_request

        out = coordinator_request(self.addr, None, route, body,
                                  timeout=self._timeout, policy=self._policy)
        if out.get("code") != 0:
            raise FatalError(f"league runtime {route} error: {out}")
        return out.get("info")

    # --- the LeagueService surface, one proxy per journaled route ---
    def register_learner(self, player_id: str, learner_id: str = "",
                         ip: str = "", port: int = 0, rank: int = 0,
                         world_size: int = 1) -> dict:
        return self._call("league_register", {
            "player_id": player_id,
            "learner_id": learner_id or player_id,
            "ip": ip, "port": port, "rank": rank, "world_size": world_size,
        })

    def ask_job(self, player_id: str, learner_id: str = "",
                actor: str = "") -> Optional[dict]:
        return self._call("league_ask", {
            "player_id": player_id,
            "learner_id": learner_id or player_id,
            "actor": actor,
        })

    def report(self, job_id: str, matches: list, learner_id: str = "") -> dict:
        return self._call("league_report", {
            "job_id": job_id, "learner_id": learner_id, "matches": matches,
        })

    def train_info(self, player_id: str, seq: int, train_steps: int = 0,
                   checkpoint_path: str = "", generation_path: str = "",
                   learner_id: str = "") -> dict:
        return self._call("league_train_info", {
            "player_id": player_id,
            "learner_id": learner_id or player_id,
            "seq": int(seq),
            "train_steps": int(train_steps),
            **({"checkpoint_path": checkpoint_path} if checkpoint_path else {}),
            **({"generation_path": generation_path} if generation_path else {}),
        })

    def status(self) -> dict:
        return self._call("league_status", {})
