"""Remote league proxy: the League surface over HTTP.

Learners and actors on other hosts construct a RemoteLeague with the league
server's address and use it exactly like an in-process League (the subset of
methods the worker roles call). Retries with backoff mirror the reference's
requests retry adapters (reference: distar/ctools/worker/actor/
actor_comm.py:59-60, adapter.py:56-63).
"""
from __future__ import annotations

import time
from typing import Optional

from .api import league_request


class RemoteLeague:
    def __init__(self, host: str, port: int, retries: int = 5, backoff_s: float = 0.5,
                 timeout: float = 30.0):
        self.host, self.port = host, port
        self._retries = retries
        self._backoff_s = backoff_s
        self._timeout = timeout

    def _call(self, route: str, body: dict):
        err: Optional[Exception] = None
        for attempt in range(self._retries):
            try:
                out = league_request(self.host, self.port, route, body, timeout=self._timeout)
                if out.get("code") == 0:
                    return out["info"]
                raise RuntimeError(f"league {route} error: {out}")
            except (OSError, ConnectionError) as e:
                err = e
                time.sleep(self._backoff_s * (2 ** attempt))
        raise ConnectionError(f"league {route} unreachable after {self._retries} tries") from err

    # --- the League surface used by workers ---
    def register_learner(self, player_id: str, ip: str = "", port: int = 0, rank: int = 0,
                         world_size: int = 1) -> dict:
        return self._call(
            "register_learner",
            {"player_id": player_id, "ip": ip, "port": port, "rank": rank,
             "world_size": world_size},
        )

    def learner_send_train_info(self, player_id: str, train_steps: int,
                                checkpoint_path: Optional[str] = None) -> dict:
        return self._call(
            "learner_send_train_info",
            {"player_id": player_id, "train_steps": train_steps,
             **({"checkpoint_path": checkpoint_path} if checkpoint_path else {})},
        )

    def actor_ask_for_job(self, request: Optional[dict] = None) -> dict:
        return self._call("actor_ask_for_job", request or {"job_type": "train"})

    def actor_send_result(self, result: dict) -> bool:
        return bool(self._call("actor_send_result", result))
