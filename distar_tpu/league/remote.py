"""Remote league proxy: the League surface over HTTP.

Learners and actors on other hosts construct a RemoteLeague with the league
server's address and use it exactly like an in-process League (the subset of
methods the worker roles call). Retries ride the shared resilience fabric
(``resilience.retry_call`` with a per-proxy circuit breaker) instead of the
hand-rolled loop each transport used to carry — one observable policy for
every cross-process link (role of the reference's requests retry adapters,
reference: distar/ctools/worker/actor/actor_comm.py:59-60, adapter.py:56-63).
"""
from __future__ import annotations

from typing import Optional

from ..resilience import CircuitBreaker, CommError, FatalError, RetryPolicy, retry_call
from .api import league_request


class RemoteLeague:
    def __init__(self, host: str, port: int, retries: int = 5, backoff_s: float = 0.5,
                 timeout: float = 30.0, policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.host, self.port = host, port
        self._timeout = timeout
        self._policy = policy or RetryPolicy(
            max_attempts=retries, backoff_base_s=backoff_s, backoff_max_s=10.0
        )
        # breaker shared across routes: the peer is one process — if jobs
        # are unreachable, results are too
        self._breaker = breaker or CircuitBreaker(op="league")

    def _call_once(self, route: str, body: dict):
        out = league_request(self.host, self.port, route, body, timeout=self._timeout)
        if out.get("code") == 0:
            return out["info"]
        # the server answered: this is an application error, not peer death
        raise FatalError(f"league {route} error: {out}")

    def _call(self, route: str, body: dict):
        try:
            return retry_call(
                self._call_once, route, body,
                op=f"league:{route}", policy=self._policy, breaker=self._breaker,
            )
        except CommError as e:
            raise CommError(
                f"league {route} unreachable after {self._policy.max_attempts} tries",
                op=e.op, cause=e,
            ) from e

    # --- the League surface used by workers ---
    def register_learner(self, player_id: str, ip: str = "", port: int = 0, rank: int = 0,
                         world_size: int = 1) -> dict:
        return self._call(
            "register_learner",
            {"player_id": player_id, "ip": ip, "port": port, "rank": rank,
             "world_size": world_size},
        )

    def learner_send_train_info(self, player_id: str, train_steps: int,
                                checkpoint_path: Optional[str] = None) -> dict:
        return self._call(
            "learner_send_train_info",
            {"player_id": player_id, "train_steps": train_steps,
             **({"checkpoint_path": checkpoint_path} if checkpoint_path else {})},
        )

    def actor_ask_for_job(self, request: Optional[dict] = None) -> dict:
        return self._call("actor_ask_for_job", request or {"job_type": "train"})

    def actor_send_result(self, result: dict) -> bool:
        return bool(self._call("actor_send_result", result))
