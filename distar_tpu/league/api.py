"""League HTTP API (stdlib http.server, JSON bodies).

Role parity with the reference Flask routes (reference: distar/ctools/worker/
league/league_api.py:14-249): the four core RPCs used by learners/actors plus
the live admin surface (show/save payoff + ELO, save/load resume, add/remove
player, reset stats). Flask isn't assumed in the image; a ThreadingHTTPServer
with a JSON dispatch table covers the same contract.

POST /league/<route> with a JSON body; responds {"code": 0, "info": ...}.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .league import League


def _routes(league: League):
    return {
        "register_learner": lambda b: league.register_learner(**b),
        "learner_send_train_info": lambda b: league.learner_send_train_info(**b),
        "actor_ask_for_job": lambda b: league.actor_ask_for_job(b),
        "actor_send_result": lambda b: league.actor_send_result(b),
        # admin
        "show_payoff": lambda b: {
            pid: p.payoff.get_text() for pid, p in league.all_players.items()
        },
        "show_elo": lambda b: league.elo.ratings(),
        "refit_elo": lambda b: league.elo.refit(),
        "show_players": lambda b: {
            "active": list(league.active_players.keys()),
            "historical": list(league.historical_players.keys()),
        },
        "add_player": lambda b: league.add_active_player(**b),
        "remove_player": lambda b: league.remove_player(b["player_id"]),
        "reset_player_stats": lambda b: league.all_players[b["player_id"]].reset_payoff(),
        "save_resume": lambda b: league.save_resume(b["path"]),
        "load_resume": lambda b: league.load_resume(b["path"]),
    }


class LeagueAPIServer:
    """Threaded HTTP wrapper around a League instance."""

    def __init__(self, league: League, host: str = "127.0.0.1", port: int = 0):
        routes = _routes(league)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_POST(self):
                name = self.path.strip("/").split("/")[-1]
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    fn = routes.get(name)
                    if fn is None:
                        payload = {"code": 404, "info": f"no route {name}"}
                    else:
                        payload = {"code": 0, "info": fn(body)}
                except Exception as e:  # surface errors to the caller
                    payload = {"code": 1, "info": repr(e)}
                data = json.dumps(payload, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        # reap the serve loop before closing its socket under it
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._server.server_close()


def league_request(host: str, port: int, route: str, body: Optional[dict] = None, timeout=10.0):
    """Client helper used by learner/actor comm. Raises the typed
    ``resilience.CommError`` on any transport fault (never a raw
    URLError/timeout); retries belong to the caller (``RemoteLeague``)."""
    import urllib.error
    import urllib.request

    from ..resilience import CommError

    req = urllib.request.Request(
        f"http://{host}:{port}/league/{route}",
        data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError,
            ValueError) as e:
        raise CommError(
            f"league:{route} @ {host}:{port} failed: {e!r}",
            op=f"league:{route}", cause=e,
        ) from e
