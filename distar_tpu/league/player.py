"""League player taxonomy.

Role parity with the reference players (reference: distar/ctools/worker/
league/player.py): Player / HistoricalPlayer / ActivePlayer plus the five
active types and their matchmaking branches:

  MainPlayer              sp 50% (weak-vs-main falls back to that main's
                          history via variance-pfsp) / pfsp 'squared' / eval
  ExploiterPlayer         pfsp 'normal' over all history; 25% random reset
  MainExploiterPlayer     vs_main (falls back to that main's history when
                          winrate < 0.2) / pfsp / eval; always resets
  ExpertPlayer            pfsp 'variance' over non-exploiter history
  ExpertExploiterPlayer   like exploiter but rotates a hand-picked Z list
  AdaptiveEvolutionaryExploiterPlayer
                          vs_main family; resets to the historical ckpt
                          best-matched (winrate in [0.2, 0.5]) vs main

Player ids follow the reference convention: MP* main, ME* main exploiter,
EP* exploiter, EX* expert(-exploiter), AE* adaptive, *H<n> historical
snapshots carrying parent_id.
"""
from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

import numpy as np

from .algorithms import pfsp
from .payoff import Payoff

FRAC_ID = {0: ["zerg", "terran", "protoss"], 1: ["zerg"], 2: ["terran"], 3: ["protoss"]}


class Player:
    name = "BasePlayer"

    def __init__(
        self,
        checkpoint_path: str,
        player_id: str,
        pipeline: str = "default",
        frac_id: int = 1,
        z_path: str = "3map.json",
        z_prob: float = 0.0,
        teacher_id: str = "none",
        teacher_checkpoint_path: str = "none",
        total_agent_step: int = 0,
        decay: float = 0.995,
        warm_up_size: int = 1000,
        min_win_rate_games: int = 200,
        total_game_count: int = 0,
    ):
        self.checkpoint_path = checkpoint_path
        self.player_id = player_id
        self.pipeline = pipeline
        self.frac_id = frac_id
        self.z_path = z_path
        self.z_prob = z_prob
        self.teacher_id = teacher_id
        self.teacher_checkpoint_path = teacher_checkpoint_path
        self.total_agent_step = total_agent_step
        self.decay = decay
        self.warm_up_size = warm_up_size
        self.min_win_rate_games = min_win_rate_games
        self.total_game_count = total_game_count
        self.payoff = Payoff(decay, warm_up_size, min_win_rate_games)

    def get_race(self) -> str:
        return random.choice(FRAC_ID[self.frac_id])

    def reset_payoff(self) -> None:
        self.payoff = Payoff(self.decay, self.warm_up_size, self.min_win_rate_games)

    def __repr__(self):
        return f"{type(self).__name__}({self.player_id}, ckpt={self.checkpoint_path})"


class HistoricalPlayer(Player):
    name = "HistoricalPlayer"

    def __init__(self, *args, parent_id: str = "none", **kwargs):
        super().__init__(*args, **kwargs)
        self.parent_id = parent_id


class ActivePlayer(Player):
    name = "ActivePlayer"

    def __init__(
        self,
        *args,
        chosen_weight: float = 1.0,
        one_phase_step: int = int(2e8),
        last_enough_step: int = 0,
        snapshot_times: int = 0,
        strong_win_rate: float = 0.7,
        successive_model_path: Optional[str] = None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.chosen_weight = chosen_weight
        self.one_phase_step = int(one_phase_step)
        self.last_enough_step = last_enough_step
        self.snapshot_times = snapshot_times
        self.strong_win_rate = strong_win_rate
        self.snapshot_flag = False
        self.reset_flag = False
        self.successive_model_path = successive_model_path or self.checkpoint_path
        self.last_successive_step = last_enough_step
        self.teammate_payoff = Payoff(self.decay, self.warm_up_size, self.min_win_rate_games)
        self.opponent_payoff = Payoff(self.decay, self.warm_up_size, self.min_win_rate_games)
        from .stat_meters import CumStat, DistStat, UnitNumStat

        self.dist_stat = DistStat(self.decay, self.warm_up_size)
        self.cum_stat = CumStat(self.decay, self.warm_up_size)
        self.unit_num_stat = UnitNumStat(self.decay, self.warm_up_size)

    # ------------------------------------------------------------- helpers
    def _non_bot_history(self, historical: Dict[str, HistoricalPlayer], include_bots: bool):
        if include_bots:
            return list(historical.keys())
        return [pid for pid, p in historical.items() if p.pipeline != "bot"]

    def _pfsp_pick(self, keys: List[str], weighting: str, default_wr: float = 0.5) -> str:
        weights = [self.payoff.pfsp_winrate_info_dict.get(pid, default_wr) for pid in keys]
        probs = pfsp(np.array(weights), weighting=weighting)
        return random.choices(keys, weights=probs, k=1)[0]

    def _phase_gate(self) -> Optional[bool]:
        """Common trained-enough preamble. Returns True/False when decided,
        None when the winrate sweep should decide."""
        if self.snapshot_flag:
            self.snapshot_flag = False
            self.last_enough_step = self.total_agent_step
            return True
        step_passed = self.total_agent_step - self.last_enough_step
        if step_passed >= self.one_phase_step:
            self.last_enough_step = self.total_agent_step
            return True
        return None

    def _winrate_sweep(self, opponent_keys: List[str]) -> bool:
        """True iff winrate vs every listed opponent exceeds strong_win_rate
        with enough games."""
        rec = self.payoff.stat_info_record
        for pid in opponent_keys:
            if pid not in rec:
                return False
            m = rec[pid]["winrate"]
            if not (m.val > self.strong_win_rate and m.count >= self.warm_up_size):
                return False
        self.last_enough_step = self.total_agent_step
        return True

    def is_save_successive_model(self) -> bool:
        if self.total_agent_step - self.last_successive_step > self.one_phase_step / 2:
            self.last_successive_step = self.total_agent_step
            return True
        return False

    def snapshot(self) -> HistoricalPlayer:
        self.snapshot_times += 1
        h_id = f"{self.player_id}H{self.snapshot_times}"
        base, _, _ = self.checkpoint_path.partition(".ckpt")
        h_path = f"{base}_{self.total_agent_step}.ckpt"
        return HistoricalPlayer(
            h_path,
            h_id,
            pipeline=self.pipeline,
            frac_id=self.frac_id,
            z_path=self.z_path,
            z_prob=self.z_prob,
            total_agent_step=self.total_agent_step,
            decay=self.decay,
            warm_up_size=self.warm_up_size,
            min_win_rate_games=self.min_win_rate_games,
            parent_id=self.player_id,
        )

    def is_reset(self) -> bool:
        return False

    def reset_checkpoint(self, active_players, historical_players, new_player_id) -> str:
        return self.teacher_checkpoint_path

    # ------------------------------------------------------------ abstract
    def get_branch_opponent(self, historical_players, active_players, branch_probs, pfsp_train_bot=False):
        raise NotImplementedError

    def is_trained_enough(self, historical_players, active_players, pfsp_train_bot=False) -> bool:
        raise NotImplementedError


def _choose_branch(branch_probs: Dict[str, float]) -> str:
    names = list(branch_probs.keys())
    return random.choices(names, weights=list(branch_probs.values()), k=1)[0]


class MainPlayer(ActivePlayer):
    name = "MainPlayer"

    def get_branch_opponent(self, historical_players, active_players, branch_probs, pfsp_train_bot=False):
        branch = _choose_branch(branch_probs[self.name])
        if branch == "sp":
            mains = [p for p in active_players.values() if isinstance(p, MainPlayer)]
            opponent = random.choice(mains)
            if (
                opponent is not self
                and self.payoff.pfsp_winrate_info_dict.get(opponent.player_id, 0.5) < 0.3
            ):
                keys = [
                    pid for pid, p in historical_players.items() if p.parent_id == opponent.player_id
                ] or self._non_bot_history(historical_players, False)
                opponent = historical_players[self._pfsp_pick(keys, "variance")]
        elif branch == "pfsp":
            keys = self._non_bot_history(historical_players, pfsp_train_bot)
            assert keys, "pfsp branch needs historical players"
            opponent = historical_players[self._pfsp_pick(keys, "squared")]
        elif branch == "eval":
            opponent = historical_players[random.choice(list(historical_players.keys()))]
        else:
            raise NotImplementedError(branch)
        return branch, [self], [opponent]

    def is_trained_enough(self, historical_players, active_players, pfsp_train_bot=False) -> bool:
        gate = self._phase_gate()
        if gate is not None:
            return gate
        if self.total_agent_step - self.last_enough_step < self.one_phase_step / 2:
            return False
        hist_keys = self._non_bot_history(historical_players, pfsp_train_bot)
        # strong sweep over history alone (with margin) is enough
        rec = self.payoff.stat_info_record
        if hist_keys and all(
            pid in rec
            and rec[pid]["winrate"].val > self.strong_win_rate + 0.1
            and rec[pid]["winrate"].count >= self.warm_up_size
            for pid in hist_keys
        ):
            return True
        others = [pid for pid in active_players if pid != self.player_id]
        return self._winrate_sweep(hist_keys + others)


class ExploiterPlayer(ActivePlayer):
    name = "ExploiterPlayer"
    reset_prob = 0.25

    def get_branch_opponent(self, historical_players, active_players, branch_probs, pfsp_train_bot=False):
        branch = _choose_branch(branch_probs[self.name])
        if branch == "pfsp":
            keys = self._non_bot_history(historical_players, pfsp_train_bot)
            opponent = historical_players[self._pfsp_pick(keys, "normal")]
        elif branch == "eval":
            opponent = historical_players[random.choice(list(historical_players.keys()))]
        else:
            raise NotImplementedError(branch)
        return branch, [self], [opponent]

    def is_trained_enough(self, historical_players, active_players, pfsp_train_bot=False) -> bool:
        gate = self._phase_gate()
        if gate is not None:
            return gate
        if self.total_agent_step - self.last_enough_step < self.one_phase_step / 2:
            return False
        return self._winrate_sweep(self._non_bot_history(historical_players, pfsp_train_bot))

    def is_reset(self) -> bool:
        if self.reset_flag:
            self.reset_flag = False
            return True
        return np.random.uniform() < self.reset_prob


class MainExploiterPlayer(ActivePlayer):
    name = "MainExploiterPlayer"

    def _main_id(self, active_players) -> str:
        # ME<suffix> pairs with MP<suffix> (multi-digit suffixes included);
        # fall back to any main when no exact pair exists
        candidate = f"MP{self.player_id[2:]}"
        if candidate in active_players:
            return candidate
        mains = [pid for pid in active_players if pid.startswith("MP")]
        assert mains, "MainExploiter needs at least one MainPlayer in the league"
        return mains[0]

    def get_branch_opponent(self, historical_players, active_players, branch_probs, pfsp_train_bot=False):
        main = active_players[self._main_id(active_players)]
        branch = _choose_branch(branch_probs[self.name])
        if branch == "vs_main":
            if self.payoff.pfsp_winrate_info_dict.get(main.player_id, 0.5) > 0.2:
                return branch, [self], [main]
            branch = "pfsp"
        elif branch == "eval":
            return "vs_main_eval", [self], [main]
        if branch == "pfsp":
            keys = [
                pid for pid, p in historical_players.items() if p.parent_id == main.player_id
            ]
            opponent = historical_players[self._pfsp_pick(keys, "variance")]
            return branch, [self], [opponent]
        raise NotImplementedError(branch)

    def is_trained_enough(self, historical_players, active_players, pfsp_train_bot=False) -> bool:
        gate = self._phase_gate()
        if gate is not None:
            return gate
        mains = [pid for pid in active_players if "MP" in pid]
        return self._winrate_sweep(mains)

    def is_reset(self) -> bool:
        return True


class ExpertPlayer(ActivePlayer):
    name = "ExpertPlayer"

    def get_branch_opponent(self, historical_players, active_players, branch_probs, pfsp_train_bot=False):
        branch = _choose_branch(branch_probs[self.name])
        if branch == "pfsp":
            keys = [pid for pid in historical_players if "EX" not in pid]
            assert keys
            opponent = historical_players[self._pfsp_pick(keys, "variance", default_wr=0.1)]
        elif branch == "eval":
            opponent = historical_players[random.choice(list(historical_players.keys()))]
        else:
            raise NotImplementedError(branch)
        return branch, [self], [opponent]

    def is_trained_enough(self, historical_players, active_players, pfsp_train_bot=False) -> bool:
        gate = self._phase_gate()
        if gate is not None:
            return gate
        return self._winrate_sweep(self._non_bot_history(historical_players, pfsp_train_bot))


class ExpertExploiterPlayer(ActivePlayer):
    """Exploiter rotating a hand-picked Z list on every reset
    (reference player.py:425-525)."""

    name = "ExpertExploiterPlayer"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        assert isinstance(self.z_path, (list, tuple)), "ExpertExploiter takes a z_path list"
        self.z_paths = list(self.z_path)
        self.z_path = random.choice(self.z_paths)

    def get_branch_opponent(self, historical_players, active_players, branch_probs, pfsp_train_bot=False):
        branch = _choose_branch(branch_probs[self.name])
        if branch == "pfsp":
            keys = self._non_bot_history(historical_players, pfsp_train_bot)
            opponent = historical_players[self._pfsp_pick(keys, "normal")]
        elif branch == "eval":
            opponent = historical_players[random.choice(list(historical_players.keys()))]
        else:
            raise NotImplementedError(branch)
        return branch, [self], [opponent]

    def is_trained_enough(self, historical_players, active_players, pfsp_train_bot=False) -> bool:
        gate = self._phase_gate()
        if gate is not None:
            return gate
        return self._winrate_sweep(self._non_bot_history(historical_players, pfsp_train_bot))

    def is_reset(self) -> bool:
        self.z_path = random.choice(self.z_paths)
        return True

    def snapshot(self) -> HistoricalPlayer:
        snap = super().snapshot()
        snap.player_id = f"{self.player_id}H{self.snapshot_times}_{str(self.z_path).split('.')[0]}"
        return snap

    def reset_checkpoint(self, active_players, historical_players, new_player_id) -> str:
        mains = sorted(
            [pid for pid in historical_players if "MP" in pid],
            key=lambda x: int(x.split("H")[-1].split("_")[0]),
        )
        return historical_players[mains[-1]].checkpoint_path


class AdaptiveEvolutionaryExploiterPlayer(ActivePlayer):
    """Resets to the historical checkpoint best-matched against the main
    player (winrate in [0.2, 0.5]) — evolutionary selection over its own
    lineage (reference player.py:640-760)."""

    name = "AdaptiveEvolutionaryExploiterPlayer"
    reset_prob = 0.25

    def __init__(self, *args, init_players: Optional[List[str]] = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.init_players: List[str] = list(init_players or [])

    def get_branch_opponent(self, historical_players, active_players, branch_probs, pfsp_train_bot=False):
        main_id = random.choice([pid for pid in active_players if "MP" in pid])
        main = active_players[main_id]
        branch = _choose_branch(branch_probs[self.name])
        if branch == "vs_main":
            if self.payoff.pfsp_winrate_info_dict.get(main.player_id, 0.5) > 0.2:
                return branch, [self], [main]
            branch = "pfsp"
        elif branch == "eval":
            return "vs_main_eval", [self], [main]
        if branch == "pfsp":
            keys = [pid for pid, p in historical_players.items() if p.parent_id == main_id]
            opponent = historical_players[self._pfsp_pick(keys, "variance")]
            return branch, [self], [opponent]
        raise NotImplementedError(branch)

    def is_trained_enough(self, historical_players, active_players, pfsp_train_bot=False) -> bool:
        gate = self._phase_gate()
        if gate is not None:
            return gate
        mains = [pid for pid in active_players if "MP" in pid]
        return self._winrate_sweep(mains)

    def is_reset(self) -> bool:
        return True

    def reset_checkpoint(self, active_players, historical_players, new_player_id) -> str:
        main_id = random.choice([pid for pid in active_players if "MP" in pid])
        if random.random() < self.reset_prob:
            if new_player_id is not None:
                self.init_players.append(new_player_id)
            return self.teacher_checkpoint_path
        # candidates: the fresh snapshot (best_idx -1) and this lineage's
        # previous init snapshots; pick the one with winrate-vs-main closest
        # from within [0.2, 0.5] (highest wins)
        best_id, best_wr, best_idx = None, 0.0, None
        wr = self.payoff.stat_info_record[main_id]["winrate"].val
        if 0.2 <= wr <= 0.5:
            best_id, best_wr, best_idx = new_player_id, wr, -1
        main_payoff = active_players[main_id].payoff.stat_info_record
        for idx, pid in enumerate(self.init_players):
            if pid not in main_payoff:
                continue
            wr = 1 - main_payoff[pid]["winrate"].val
            if 0.2 <= wr <= 0.5 and wr > best_wr:
                best_id, best_wr, best_idx = pid, wr, idx
        if best_idx is not None and best_idx != -1:
            # an older lineage member wins: rotate it out, track the snapshot
            del self.init_players[best_idx]
            if new_player_id is not None:
                self.init_players.append(new_player_id)
        if best_id is not None and best_id in historical_players:
            return historical_players[best_id].checkpoint_path
        return self.teacher_checkpoint_path


PLAYER_TYPES = {
    "MP": MainPlayer,
    "ME": MainExploiterPlayer,
    "EP": ExploiterPlayer,
    "EX": ExpertExploiterPlayer,
    "AE": AdaptiveEvolutionaryExploiterPlayer,
    "XP": ExpertPlayer,
}


def active_player_type(player_id: str):
    """Map a player id prefix to its class (reference league.py convention:
    MP main, ME main-exploiter, EP exploiter, EX expert-exploiter, AE
    adaptive-evolutionary, XP expert)."""
    return PLAYER_TYPES.get(player_id[:2])
