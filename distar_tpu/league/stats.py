"""Meters used by the league's per-opponent statistics.

WindowedMeter reproduces the reference MoveAverageMeter semantics
(reference: distar/ctools/utils/log_helper.py:483-520): a true moving average
over the last ``length`` values, with ``count`` tracking lifetime updates
(the payoff's warm-up gate keys off count, not window fill).
"""
from __future__ import annotations

from collections import deque


class WindowedMeter:
    def __init__(self, length: int = 1000):
        self.length = length
        self.reset()

    def reset(self) -> None:
        self._history: deque = deque(maxlen=self.length)
        self._val = 0.0
        self._count = 0

    def update(self, value) -> None:
        value = float(value)
        self._count += 1
        n = len(self._history)
        if n < self.length:
            self._val = (1 - 1.0 / (n + 1)) * self._val + value / (n + 1)
            self._history.append(value)
        else:
            left = self._history.popleft()
            self._val = self._val + (value - left) / self.length
            self._history.append(value)

    @property
    def val(self) -> float:
        return self._val

    @property
    def count(self) -> int:
        return self._count

    def state(self) -> dict:
        return {"length": self.length, "history": list(self._history), "count": self._count}

    @classmethod
    def from_state(cls, s: dict) -> "WindowedMeter":
        m = cls(s["length"])
        for v in s["history"]:
            m.update(v)
        m._count = s["count"]
        return m


class EmaMeter:
    """EMA with linear warm-up (reference log_helper.py:570+)."""

    def __init__(self, decay: float, warm_up_size: int):
        assert 0 <= decay <= 1 and warm_up_size > 0
        self._decay = decay
        self._warm_up_size = warm_up_size
        self.reset()

    def reset(self) -> None:
        self._count = 0
        self._val = 0.0

    def update(self, value) -> None:
        value = float(value)
        if self._count < self._warm_up_size:
            self._val = (self._val * self._count + value) / (self._count + 1)
        else:
            self._val = self._decay * self._val + (1 - self._decay) * value
        self._count += 1

    @property
    def val(self) -> float:
        return self._val

    @property
    def count(self) -> int:
        return self._count
