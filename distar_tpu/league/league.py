"""League manager: player registry, matchmaking, payoff/ELO bookkeeping,
snapshot/reset decisions, resume.

Role parity with the reference League (reference: distar/ctools/worker/
league/league.py:30-556): learners register and stream train-info (driving
snapshot/reset decisions, :259-297); actors ask for jobs (PFSP matchmaking,
:394-486) and send results (payoff + ELO ingestion, :313-384). The HTTP
surface lives in api.py; this class is transport-agnostic and fully
deterministic given its RNG, so league logic is unit-testable without any
game (the simulation tests the reference lacks).
"""
from __future__ import annotations

import itertools
import os
import pickle
import random
import threading
import time
from typing import Dict, List, Optional

from ..utils import Config, deep_merge_dicts
from .elo import ELORating
from .trueskill import TrueSkill
from .player import (
    ActivePlayer,
    HistoricalPlayer,
    MainPlayer,
    Player,
    active_player_type,
)

LEAGUE_DEFAULTS = Config(
    {
        "league": {
            "use_historical_players": True,
            "vs_bot": False,
            "pfsp_train_bot": False,
            "save_initial_snapshot": True,
            "bot_probs": [0, 0, 0, 0.2, 0.2, 0.3, 0.3],
            "branch_probs": {
                "MainPlayer": {"sp": 0.5, "pfsp": 0.5},
                "ExploiterPlayer": {"pfsp": 1.0},
                "MainExploiterPlayer": {"vs_main": 0.3, "pfsp": 0.5, "eval": 0.2},
                "ExpertPlayer": {"pfsp": 1.0},
                "ExpertExploiterPlayer": {"pfsp": 1.0},
                "AdaptiveEvolutionaryExploiterPlayer": {"vs_main": 0.5, "pfsp": 0.5},
            },
            "map_names": ["KairosJunction"],
            "map_id_weights": [1],
            "stat_decay": 0.995,
            "stat_warm_up_size": 1000,
            "payoff_min_win_rate_games": 1000,
            "print_freq": 100,
            "save_resume_freq_s": 3600,
            "active_players": {
                "player_id": ["MP0"],
                "checkpoint_path": ["pretrain.ckpt"],
                "pipeline": ["default"],
                "frac_id": [1],
                "z_path": ["3map.json"],
                "z_prob": [0.0],
                "teacher_id": ["teacher"],
                "teacher_path": ["pretrain.ckpt"],
                "one_phase_step": [1e9],
                "chosen_weight": [1.0],
            },
            "historical_players": {
                "player_id": ["HP0"],
                "checkpoint_path": ["pretrain.ckpt"],
                "pipeline": ["default"],
                "frac_id": [1],
                "z_path": ["3map.json"],
                "z_prob": [0.0],
            },
        }
    }
)


class League:
    def __init__(self, cfg: Optional[dict] = None, logger=None):
        whole = deep_merge_dicts(LEAGUE_DEFAULTS, cfg or {})
        self.cfg = whole.league
        self.logger = logger
        self.active_players: Dict[str, ActivePlayer] = {}
        self.historical_players: Dict[str, HistoricalPlayer] = {}
        self.elo = ELORating()
        self.trueskill = TrueSkill()
        self._lock = threading.RLock()
        self._learners: Dict[str, List[dict]] = {}
        # runtime attachment (league/runtime/service.py): when a
        # LeagueService hosts this league, its roster/assignment/mint state
        # rides save_resume/load_resume so one journal carries everything
        self._runtime_state_fn = None
        self._runtime_load_fn = None
        if self.cfg.get("resume_path") and os.path.isfile(self.cfg.resume_path):
            self.load_resume(self.cfg.resume_path)
        else:
            self._init_players()

    # ------------------------------------------------------------------ init
    def _log(self, msg: str) -> None:
        if self.logger is not None:
            self.logger.info(msg)

    def _init_players(self) -> None:
        ap = self.cfg.active_players
        n = len(ap.player_id)

        def col(name, default):
            vals = ap.get(name)
            return vals if vals is not None else [default] * n

        for i in range(n):
            self.add_active_player(
                player_id=ap.player_id[i],
                checkpoint_path=ap.checkpoint_path[i],
                pipeline=col("pipeline", "default")[i],
                frac_id=col("frac_id", 1)[i],
                z_path=col("z_path", "3map.json")[i],
                z_prob=col("z_prob", 0.0)[i],
                teacher_id=col("teacher_id", "none")[i],
                teacher_path=col("teacher_path", "none")[i],
                one_phase_step=col("one_phase_step", 1e9)[i],
                chosen_weight=col("chosen_weight", 1.0)[i],
            )
        if self.cfg.save_initial_snapshot:
            # seed history with a snapshot of every active player so
            # parent-matched pfsp branches (ME/AE) have opponents from the
            # start (reference league.py:162-189)
            for player in list(self.active_players.values()):
                snap = player.snapshot()
                self.historical_players[snap.player_id] = snap
        if self.cfg.use_historical_players:
            hp = self.cfg.historical_players
            ids = hp.get("player_id") or [f"HP{i}" for i in range(len(hp.checkpoint_path))]
            for i, pid in enumerate(ids):
                self.historical_players[pid] = HistoricalPlayer(
                    hp.checkpoint_path[i],
                    pid,
                    pipeline=hp.pipeline[i],
                    frac_id=hp.frac_id[i],
                    z_path=hp.z_path[i],
                    z_prob=hp.get("z_prob", [0.0] * len(ids))[i],
                    decay=self.cfg.stat_decay,
                    warm_up_size=self.cfg.stat_warm_up_size,
                    min_win_rate_games=self.cfg.payoff_min_win_rate_games,
                )

    def add_active_player(self, player_id: str, checkpoint_path: str, pipeline="default",
                          frac_id=1, z_path="3map.json", z_prob=0.0, teacher_id="none",
                          teacher_path="none", one_phase_step=1e9, chosen_weight=1.0) -> None:
        cls = active_player_type(player_id)
        if cls is None:
            raise ValueError(
                f"unknown active player type for id {player_id} "
                f"(expected prefix MP/ME/EP/EX/AE/XP)"
            )
        self.active_players[player_id] = cls(
            checkpoint_path,
            player_id,
            pipeline=pipeline,
            frac_id=frac_id,
            z_path=z_path,
            z_prob=z_prob,
            teacher_id=teacher_id,
            teacher_checkpoint_path=teacher_path,
            decay=self.cfg.stat_decay,
            warm_up_size=self.cfg.stat_warm_up_size,
            min_win_rate_games=self.cfg.payoff_min_win_rate_games,
            one_phase_step=int(float(one_phase_step)),
            chosen_weight=chosen_weight,
        )

    def remove_player(self, player_id: str) -> bool:
        with self._lock:
            return (
                self.active_players.pop(player_id, None) is not None
                or self.historical_players.pop(player_id, None) is not None
            )

    @property
    def all_players(self) -> Dict[str, Player]:
        return {**self.active_players, **self.historical_players}

    # --------------------------------------------------------------- learner
    def register_learner(self, player_id: str, ip: str = "", port: int = 0, rank: int = 0,
                         world_size: int = 1) -> dict:
        with self._lock:
            player = self.active_players[player_id]
            self._learners.setdefault(player_id, []).append(
                {"ip": ip, "port": port, "rank": rank, "world_size": world_size}
            )
            return {"checkpoint_path": player.checkpoint_path}

    def learner_send_train_info(self, player_id: str, train_steps: int,
                                checkpoint_path: Optional[str] = None) -> dict:
        """Ingest learner progress; decide snapshot and/or live reset
        (reference league.py:259-297). Returns {} or
        {'reset_checkpoint_path': path} which the learner applies in place."""
        with self._lock:
            player = self.active_players[player_id]
            player.total_agent_step += int(train_steps)
            if checkpoint_path:
                player.checkpoint_path = checkpoint_path
            reply: dict = {}
            if player.is_trained_enough(
                self.historical_players, self.active_players, self.cfg.pfsp_train_bot
            ):
                snap = player.snapshot()
                self.historical_players[snap.player_id] = snap
                self._log(f"snapshot: {snap.player_id} @ step {player.total_agent_step}")
                if player.is_reset():
                    reset_path = player.reset_checkpoint(
                        self.active_players, self.historical_players, snap.player_id
                    )
                    player.reset_payoff()
                    player.checkpoint_path = reset_path
                    reply["reset_checkpoint_path"] = reset_path
                    self._log(f"reset {player_id} -> {reset_path}")
            return reply

    # ----------------------------------------------------------------- actor
    def choose_active_player(self) -> ActivePlayer:
        ids = list(self.active_players.keys())
        weights = [self.active_players[i].chosen_weight for i in ids]
        return self.active_players[random.choices(ids, weights=weights, k=1)[0]]

    def actor_ask_for_job(self, request: Optional[dict] = None) -> dict:
        request = request or {"job_type": "train"}
        job_type = request.get("job_type", "train")
        with self._lock:
            if job_type == "eval":
                job = self._eval_job()
            elif self.cfg.vs_bot:
                job = self._vs_bot_job(self.choose_active_player())
            else:
                job = self._train_job(self.choose_active_player())
            job["env_info"]["map_name"] = random.choices(
                list(self.cfg.map_names), weights=list(self.cfg.map_id_weights), k=1
            )[0]
            return job

    def _job_template(self, players: List[Player], branch: str) -> dict:
        return {
            "branch": branch,
            "player_ids": [p.player_id for p in players],
            "side_ids": list(range(len(players))),
            "pipelines": [p.pipeline for p in players],
            "checkpoint_paths": [p.checkpoint_path for p in players],
            "successive_ids": [
                p.player_id if isinstance(p, MainPlayer) else "none" for p in players
            ],
            "z_path": [p.z_path for p in players],
            "z_prob": [p.z_prob for p in players],
            "teacher_player_ids": [p.teacher_id for p in players],
            "teacher_checkpoint_paths": [p.teacher_checkpoint_path for p in players],
            "send_data_players": sorted(
                {p.player_id for p in players if isinstance(p, ActivePlayer)}
            ),
            "update_players": sorted(
                {p.player_id for p in players if isinstance(p, ActivePlayer)}
            ),
            "frac_ids": [p.frac_id for p in players],
            "env_info": {
                "player_ids": [p.player_id for p in players],
                "side_id": list(range(len(players))),
            },
        }

    def _train_job(self, player: ActivePlayer) -> dict:
        branch, home, away = player.get_branch_opponent(
            self.historical_players, self.active_players, self.cfg.branch_probs,
            self.cfg.pfsp_train_bot,
        )
        players = list(itertools.chain.from_iterable(zip(home, away)))
        job = self._job_template(players, branch)
        if branch == "vs_main":
            # the main player is frozen opponent here: no teacher, no data
            for idx, p in enumerate(players):
                if isinstance(p, MainPlayer):
                    job["teacher_player_ids"][idx] = "none"
                    job["teacher_checkpoint_paths"][idx] = "none"
            job["send_data_players"] = sorted(
                {
                    p.player_id
                    for p in players
                    if isinstance(p, ActivePlayer) and not isinstance(p, MainPlayer)
                }
            )
        elif "eval" in branch:
            job["teacher_player_ids"] = ["none"] * len(players)
            job["teacher_checkpoint_paths"] = ["none"] * len(players)
            job["send_data_players"] = []
        return job

    def _vs_bot_job(self, player: ActivePlayer) -> dict:
        bot_probs = list(self.cfg.bot_probs)
        bot_level = random.choices(range(len(bot_probs)), weights=bot_probs, k=1)[0]
        job = self._job_template([player], "train_bot")
        job["bot_id"] = f"bot{bot_level}"
        job["env_info"]["player_ids"] = [player.player_id, f"bot{bot_level}"]
        job["env_info"]["side_id"] = [0, 1]
        return job

    def _eval_job(self) -> dict:
        """Ladder pairing: prefer pairs with fewer recorded games than
        ladder_min_games so the payoff/rating matrix fills evenly
        (reference _get_ladder_job_info, league.py:486+)."""
        hist = list(self.historical_players.values())
        if len(hist) < 2:
            pair = hist * 2
        else:
            min_games = int(self.cfg.get("ladder_min_games", 100))
            # .get-based reads: indexing the nested defaultdicts would
            # materialise zero entries for every pair on every eval job
            games = self.elo.games
            under = [
                (a, b)
                for a in hist
                for b in hist
                if a.player_id != b.player_id
                and games.get(a.player_id, {}).get(b.player_id, 0) < min_games
            ]
            pair = list(random.choice(under)) if under else random.sample(hist, 2)
        job = self._job_template(pair, "ladder")
        job["send_data_players"] = []
        job["update_players"] = []
        return job

    def actor_send_result(self, result: dict) -> bool:
        """Ingest one finished game. ``result`` layout (per reference
        _send_result_loop): game_steps/game_iters/game_duration, plus per
        side-id dicts {'player_id', 'opponent_id', 'winloss' in {-1,0,1}}."""
        game_stats = {
            "game_steps": result.get("game_steps", 0),
            "game_iters": result.get("game_iters", 0),
            "game_duration": result.get("game_duration", 0.0),
        }
        sides = {k: v for k, v in result.items() if isinstance(v, dict) and "player_id" in v}
        with self._lock:
            for side in sides.values():
                pid, opp = side["player_id"], side["opponent_id"]
                if pid not in self.all_players:
                    continue
                player = self.all_players[pid]
                if pid != opp:
                    player.payoff.update(
                        opp,
                        {"winrate": (1 + side["winloss"]) / 2, **game_stats},
                    )
                player.total_game_count += 1
                race = side.get("race", "unknown")
                if isinstance(player, ActivePlayer) and race != "unknown":
                    stats = {**side, "game_steps": game_stats["game_steps"]}
                    player.dist_stat.update_from_result(race, stats)
                    player.cum_stat.update_from_result(race, stats)
                    player.unit_num_stat.update_from_result(race, stats)
            first = sides.get("0") or next(iter(sides.values()), None)
            if first is not None and first["player_id"] != first["opponent_id"]:
                wl = int(first["winloss"])
                self.elo.update(first["player_id"], first["opponent_id"], wl)
                if wl > 0:
                    self.trueskill.update(first["player_id"], first["opponent_id"])
                elif wl < 0:
                    self.trueskill.update(first["opponent_id"], first["player_id"])
                else:
                    self.trueskill.update(first["player_id"], first["opponent_id"], draw=True)
        return True

    # ---------------------------------------------------------------- resume
    def attach_runtime(self, state_fn, load_fn) -> None:
        """Hook a league-runtime service into resume journaling: its state
        (learner roster, assignment map, snapshot lineage, RNG cursor) is
        embedded in ``save_resume`` blobs and handed back on load."""
        self._runtime_state_fn = state_fn
        self._runtime_load_fn = load_fn

    def save_resume(self, path: str) -> str:
        """Journal the full league state (players, payoff, ratings) to
        ``path``. Atomic via the storage layer (tmp+fsync+rename): a
        coordinator killed mid-journal leaves the previous journal intact —
        the durability contract the autosave loop depends on."""
        from ..utils import storage

        with self._lock:
            blob = pickle.dumps(
                {
                    "active_players": self.active_players,
                    "historical_players": self.historical_players,
                    "elo": self.elo,
                    "trueskill": self.trueskill,
                    "learners": {k: list(v) for k, v in self._learners.items()},
                    "runtime": (
                        self._runtime_state_fn()
                        if self._runtime_state_fn is not None else None
                    ),
                }
            )
        storage.write_bytes(path, blob)
        return path

    def load_resume(self, path: str) -> None:
        from ..utils import storage

        data = pickle.loads(storage.read_bytes(path))
        self.active_players = data["active_players"]
        self.historical_players = data["historical_players"]
        self.elo = data["elo"]
        self.trueskill = data.get("trueskill", TrueSkill())
        self._learners = {k: list(v) for k, v in (data.get("learners") or {}).items()}
        runtime = data.get("runtime")
        if runtime is not None and self._runtime_load_fn is not None:
            self._runtime_load_fn(runtime)
        # backfill attributes absent from older resume pickles (unpickling
        # skips __init__)
        from .stat_meters import CumStat, DistStat, UnitNumStat

        for player in self.active_players.values():
            if not hasattr(player, "dist_stat"):
                player.dist_stat = DistStat(player.decay, player.warm_up_size)
                player.cum_stat = CumStat(player.decay, player.warm_up_size)
                player.unit_num_stat = UnitNumStat(player.decay, player.warm_up_size)
        self._log(f"league resumed from {path}")

    # -------------------------------------------------------------- autosave
    def start_autosave(self, path: str, interval_s: Optional[float] = None) -> str:
        """Periodic ``save_resume`` journaling on a daemon thread — the
        coordinator-durability leg of the fault-tolerance layer: a broker
        restart with ``load_resume(path)`` picks the league up where the
        last journal left it instead of resetting all payoff/ELO state.
        Cadence defaults to ``league.save_resume_freq_s``. Returns ``path``."""
        interval_s = float(
            self.cfg.get("save_resume_freq_s", 3600) if interval_s is None else interval_s
        )
        assert interval_s > 0
        self.stop_autosave()
        self._autosave_stop = threading.Event()

        def run():
            from ..obs import get_registry

            saves = get_registry().counter(
                "distar_league_autosaves_total", "league resume journals written"
            )
            while not self._autosave_stop.wait(interval_s):
                try:
                    self.save_resume(path)
                    saves.inc()
                except Exception as e:  # journaling must never kill matchmaking
                    self._log(f"league autosave failed: {e!r}")

        self._autosave_thread = threading.Thread(
            target=run, daemon=True, name="league-autosave"
        )
        self._autosave_thread.start()
        return path

    def stop_autosave(self) -> None:
        stop = getattr(self, "_autosave_stop", None)
        thread = getattr(self, "_autosave_thread", None)
        if stop is not None:
            stop.set()
        if thread is not None:
            thread.join(timeout=10.0)
            self._autosave_thread = None
