"""Typed replay-plane errors.

Same contract as the serve plane's error taxonomy (serve/errors.py): every
failure a client can dispatch on maps to a stable wire dict
(``to_wire``/``error_from_wire``), so the framed-TCP data plane carries
typed answers instead of ambiguous empties. ``RateLimitTimeout``
additionally subclasses ``resilience.RetryableError``: a store that is
rate-limit-blocked is *pacing* the caller, not failing, so the retry
fabric backs off and re-offers instead of giving up or striking the peer.
"""
from __future__ import annotations

from ..resilience import RetryableError


class ReplayError(Exception):
    """Base replay-store failure. ``code`` is the stable wire identifier."""

    code = "replay_error"

    def to_wire(self) -> dict:
        return {"code": self.code, "error": str(self)}


class UnknownTableError(ReplayError):
    """Operation referenced a table the store doesn't hold (and the store
    was configured without an auto-create factory)."""

    code = "unknown_table"


class InvalidBatchError(ReplayError):
    """The requested sample batch can never be admitted under the table's
    rate-limiter configuration (``batch_size`` exceeds what the
    ``error_buffer`` slack allows even with inserters run to their bound).
    Deliberately NOT retryable: waiting cannot fix a config mismatch, and
    without this check both sides block forever trading timeouts."""

    code = "invalid_batch"


class RateLimitTimeout(ReplayError, RetryableError):
    """The samples-per-insert limiter kept the operation blocked past its
    timeout. Retryable by construction: no state was created, and the
    block is the rate control working — inserters wait for the learner,
    samplers wait for the actors (docs/data_plane.md)."""

    code = "rate_limited"

    def __init__(self, side: str, timeout_s: float, state: dict):
        super().__init__(
            f"{side} blocked > {timeout_s:.1f}s by the rate limiter ({state})"
        )
        self.side = side
        self.state = state


class ItemCorruptError(ReplayError):
    """A spilled item failed its CRC check on recovery."""

    code = "item_corrupt"


class StoreDrainingError(ReplayError):
    """The store is retiring gracefully: new inserts are refused while the
    resident tail drains out to samplers. Deliberately NOT retryable against
    the same shard — waiting cannot un-drain it; sharded clients route the
    key to a survivor instead (and the drained shard leaves the map at the
    next membership refresh)."""

    code = "draining"


class BadFrameError(ReplayError):
    """The peer sent an unparseable frame (garbage header/codec): the framed
    stream can no longer be trusted and the connection closes after the
    reply."""

    code = "bad_frame"


class BadRequestError(ReplayError):
    """The request was not a well-formed op dict, or named an op this store
    does not speak. Not retryable: re-sending the same request cannot fix
    it."""

    code = "bad_request"


class RingServiceError(ReplayError):
    """The shm ring pump answered for a dispatch bug (comm/shm_ring.py
    ``RingService``): the request reached the store but its handler raised
    something untyped."""

    code = "shm_error"


class BadHelloError(ReplayError):
    """The connection's ``hello`` offered preference lists with no
    recognized name at all (garbage codec/transport names — a hostile or
    desynced peer). Deliberately NOT retryable, and never silently
    degraded: a peer that can't even name a real codec must be told so."""

    code = "bad_hello"


_WIRE_CODES = {
    cls.code: cls
    for cls in (ReplayError, UnknownTableError, InvalidBatchError,
                ItemCorruptError, BadHelloError, StoreDrainingError,
                BadFrameError, BadRequestError, RingServiceError)
}


def error_from_wire(payload: dict) -> ReplayError:
    """Rehydrate a typed error from its wire dict. ``rate_limited`` needs
    its own path (the constructor signature differs); unknown codes degrade
    to the base ``ReplayError`` so old clients survive new server codes."""
    code = payload.get("code")
    if code == RateLimitTimeout.code:
        err = RateLimitTimeout(
            payload.get("side", "?"), float(payload.get("timeout_s", 0.0)),
            payload.get("state", {}),
        )
        return err
    cls = _WIRE_CODES.get(code, ReplayError)
    return cls(payload.get("error", ""))
