"""Zero-copy colocated fast path: the replay plane without the wire.

In the Sebulba layout (``rl_train --type all``) actor, store and learner
share one process, yet the PR 5 smoke path still round-tripped every
trajectory through pickle -> lz4 -> loopback TCP -> lz4 -> unpickle, twice
(push and sample). ``LocalReplayClient`` removes the whole stack: it speaks
the exact Insert/SampleClient surface over a direct ``ReplayStore`` handle,
so ``push_trajectory`` hands the store THE object (no serialization — the
learner later collates the very arrays the actor produced) and ``sample``
hands them back. Rate limiting, eviction, spill durability and metrics are
untouched — they live in the store, not the transport.

Wiring is by address scheme so configs stay plain strings: an
``actor.replay.addr`` of ``"inproc"`` (or ``"local"``) resolves to the
process-registered store (``set_local_store``), which ``rl_train`` installs
under ``--replay --replay-fast-path``.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ..comm.serializer import maybe_decode
from ..resilience import retry_call
from .client import DEFAULT_REPLAY_POLICY
from .store import ReplayStore

#: addr spellings that mean "the process-local store, no socket"
INPROC_ADDRS = ("inproc", "local")

_local_store: Optional[ReplayStore] = None
_local_lock = threading.Lock()


def set_local_store(store: Optional[ReplayStore]) -> None:
    """Install (or clear, with None) this process's colocated store."""
    global _local_store
    with _local_lock:
        _local_store = store


def local_store() -> ReplayStore:
    with _local_lock:
        store = _local_store
    if store is None:
        raise RuntimeError(
            "no in-process replay store registered: 'inproc' replay "
            "addresses need rl_train --replay --replay-fast-path (or an "
            "explicit set_local_store) in this process"
        )
    return store


def is_inproc_addr(addr: str) -> bool:
    return str(addr).strip().lower() in INPROC_ADDRS


class LocalReplayClient:
    """Insert+Sample client surface over a direct store handle. Sampled
    items are the inserted objects themselves (identity-preserved) except
    spill-recovered ones, which decode transparently.

    Pacing parity with the TCP clients: ``RateLimitTimeout`` is retryable,
    and the TCP clients re-offer it under ``DEFAULT_REPLAY_POLICY`` (120 s
    deadline budget) — so this client does too. Without that, a colocated
    learner that outpaces a still-warming actor would crash on the first
    30 s limiter block where the wire path would have ridden it out."""

    def __init__(self, store: Optional[ReplayStore] = None,
                 retry_policy=None):
        self._store = store if store is not None else local_store()
        self._policy = retry_policy or DEFAULT_REPLAY_POLICY

    # ------------------------------------------------------------ insert side
    def insert(self, table: str, item: Any, priority: float = 1.0,
               timeout_s: Optional[float] = None, key: Optional[str] = None) -> int:
        # no idem key: there is no wire to lose an ack on, so the in-process
        # call is exactly-once by construction
        return retry_call(
            self._store.insert, table, item,
            priority=priority, timeout_s=60.0 if timeout_s is None else timeout_s,
            op="replay_local:insert", policy=self._policy,
        )

    # ------------------------------------------------------------ sample side
    def sample(self, table: str, batch_size: int = 1,
               timeout_s: Optional[float] = None) -> Tuple[List[Any], List[dict]]:
        sampled = retry_call(
            self._store.sample, table,
            batch_size=batch_size,
            timeout_s=60.0 if timeout_s is None else timeout_s,
            op="replay_local:sample", policy=self._policy,
        )
        return [maybe_decode(s.data) for s in sampled], [s.info() for s in sampled]

    def update_priorities(self, table: str, updates: Dict[int, float],
                          info: Optional[List[dict]] = None) -> int:
        return self._store.update_priorities(table, updates)

    # ---------------------------------------------------------------- common
    def ping(self) -> bool:
        return True

    def stats(self) -> dict:
        return self._store.stats()

    def tables(self) -> List[str]:
        return self._store.tables()

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
