"""Disk spill for acked inserts: the durability half of the replay store.

An insert is acked only after its trajectory is on disk, so a store crash
loses nothing a producer was told is safe. Layout under one spill root:

  ``<root>/<key>.spill``   one self-describing blob per item, written
                           through ``utils/storage`` (atomic tmp+rename,
                           fsync'd — the same write discipline checkpoints
                           use). The blob carries table/priority/CRC next to
                           the payload, so every file verifies standalone.
  ``<root>/MANIFEST``      periodically-rewritten CRC index (checkpoint
                           style): live keys + per-file crc32. Recovery
                           trusts the per-file CRC first and uses the
                           manifest as a cross-check / post-mortem record.

Ring semantics: at most ``max_items`` live files; appending past the cap
drops the oldest (counted — durability is bounded by configuration, never
silently). ``release(key)`` deletes a file once its item left the table
(first sample or eviction); ``recover()`` yields every live, CRC-valid item
for re-insertion after a restart.
"""
from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Dict, Iterator, List, Optional

from ..comm.serializer import dumps, loads
from ..obs import get_registry
from ..utils import storage

_SUFFIX = ".spill"
_MANIFEST = "MANIFEST"


class SpillRing:
    def __init__(self, root: str, max_items: int = 4096, manifest_every: int = 16):
        assert max_items >= 1
        self.root = root
        self.max_items = max_items
        self._manifest_every = max(1, manifest_every)
        self._lock = threading.Lock()
        self._seq = 0
        self._live: Dict[str, int] = {}  # key -> crc32 (insertion-ordered)
        self._ops_since_manifest = 0
        if "://" not in root:  # scheme'd backends (mem://, gs://) need no dir
            os.makedirs(root, exist_ok=True)
        reg = get_registry()
        self._g_items = reg.gauge(
            "distar_replay_spill_items", "acked-but-unsampled items on disk")
        self._c_writes = reg.counter(
            "distar_replay_spill_writes_total", "spill blobs written")
        self._c_dropped = reg.counter(
            "distar_replay_spill_dropped_total",
            "spilled items dropped by the ring bound (durability ceiling hit)")
        self._c_recovered = reg.counter(
            "distar_replay_spill_recovered_total", "items recovered on restart")
        self._c_corrupt = reg.counter(
            "distar_replay_spill_corrupt_total",
            "spill blobs failing CRC on recovery (skipped)")
        self._bootstrap_seq()

    # ------------------------------------------------------------- plumbing
    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + _SUFFIX)

    def _bootstrap_seq(self) -> None:
        """Continue the key sequence past any pre-crash files so a restarted
        store never reuses (and silently overwrites) a live key."""
        top = 0
        backend, rest = storage.resolve(self.root)
        for path in backend.list(os.path.join(rest, "")):
            name = os.path.basename(path)
            if not name.endswith(_SUFFIX):
                continue
            try:
                top = max(top, int(name[:-len(_SUFFIX)].rsplit("-", 1)[-1]) + 1)
            except ValueError:
                continue
        self._seq = top

    def reserve_key(self, table: str) -> str:
        with self._lock:
            key = f"{table}-{self._seq:012d}"
            self._seq += 1
            return key

    def _write_manifest_locked(self, force: bool = False) -> None:
        self._ops_since_manifest += 1
        if not force and self._ops_since_manifest < self._manifest_every:
            return
        self._ops_since_manifest = 0
        manifest = {"count": len(self._live), "files": dict(self._live)}
        storage.write_bytes(
            os.path.join(self.root, _MANIFEST), json.dumps(manifest).encode())

    # ------------------------------------------------------------------ api
    def append(self, key: str, table: str, item: object, priority: float) -> None:
        payload = dumps(item, compress=True)
        blob = dumps(
            {
                "key": key,
                "table": table,
                "priority": float(priority),
                "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
                "payload": payload,
            },
            compress=False,  # payload is already compressed
        )
        storage.write_bytes(self._path(key), blob)
        with self._lock:
            self._live[key] = zlib.crc32(blob) & 0xFFFFFFFF
            dropped: List[str] = []
            while len(self._live) > self.max_items:
                oldest = next(iter(self._live))
                del self._live[oldest]
                dropped.append(oldest)
            self._write_manifest_locked()
        self._c_writes.inc()
        self._g_items.set(len(self._live))
        for old in dropped:
            self._c_dropped.inc()
            self._unlink(old)

    def release(self, key: str) -> None:
        """The item left the table (sampled or evicted): its durability
        obligation is over."""
        with self._lock:
            was_live = self._live.pop(key, None) is not None
            if was_live:
                self._write_manifest_locked()
        if was_live:
            self._unlink(key)
        self._g_items.set(self.live_count())

    def _unlink(self, key: str) -> None:
        try:
            storage.delete(self._path(key))
        except (FileNotFoundError, OSError):
            pass

    def recover(self, keep_encoded: bool = False) -> Iterator[dict]:
        """Yield ``{key, table, priority, item}`` for every live CRC-valid
        blob (oldest first); corrupt blobs are counted, unlinked and
        skipped. Rebuilds the in-memory index as it goes, so a recovered
        ring keeps ring/release semantics.

        ``keep_encoded=True`` yields the item as a ``serializer.Opaque``
        wrapper around the stored (already-compressed) payload instead of
        decoding it: recovery skips the unpickle pass, and a later wire
        re-serve ships the blob without recompressing (CRC still verifies
        integrity either way)."""
        from ..comm.serializer import Opaque

        backend, rest = storage.resolve(self.root)
        paths = sorted(
            p for p in backend.list(os.path.join(rest, ""))
            if p.endswith(_SUFFIX)
        )
        manifest = self._read_manifest()
        for path in paths:
            key = os.path.basename(path)[: -len(_SUFFIX)]
            try:
                rec = loads(backend.read_bytes(path))
                payload = rec["payload"]
                if (zlib.crc32(payload) & 0xFFFFFFFF) != rec["crc32"]:
                    raise ValueError(f"crc mismatch for {key}")
                if manifest is not None and key in manifest:
                    blob = backend.read_bytes(path)
                    if (zlib.crc32(blob) & 0xFFFFFFFF) != manifest[key]:
                        raise ValueError(f"manifest crc mismatch for {key}")
                item = Opaque(payload) if keep_encoded else loads(payload)
            except Exception:
                self._c_corrupt.inc()
                self._unlink(key)
                continue
            with self._lock:
                self._live[key] = zlib.crc32(backend.read_bytes(path)) & 0xFFFFFFFF
            self._c_recovered.inc()
            yield {"key": key, "table": rec["table"],
                   "priority": rec["priority"], "item": item}
        with self._lock:
            self._write_manifest_locked(force=True)
        self._g_items.set(self.live_count())

    def _read_manifest(self) -> Optional[Dict[str, int]]:
        path = os.path.join(self.root, _MANIFEST)
        try:
            return dict(json.loads(storage.read_bytes(path))["files"])
        except Exception:
            return None  # manifest-less/garbled: per-file CRCs still verify

    def live_count(self) -> int:
        with self._lock:
            return len(self._live)

    def flush(self) -> None:
        """Force a manifest write (shutdown path)."""
        with self._lock:
            self._write_manifest_locked(force=True)

    def stats(self) -> dict:
        with self._lock:
            return {
                "root": self.root,
                "live": len(self._live),
                "max_items": self.max_items,
                "next_seq": self._seq,
            }
