"""Replay-store service: framed-TCP data plane + HTTP admin surface.

Wire format = ``comm.serializer`` (8-byte big-endian length prefix around a
pickled+compressed payload) with the ``serve/tcp_frontend`` conventions:
one request/response dict pair per frame, ``{"code": 0, ...}`` on success,
``{"code": <wire code>, "error": ...}`` typed on failure (errors.to_wire).

Requests:
  insert  {table, item, priority?, timeout_s?}    -> {code: 0, seq}
  sample  {table, batch_size?, timeout_s?}        -> {code: 0, items, info}
  update_priorities {table, updates}              -> {code: 0, applied}
  stats   {}                                      -> {code: 0, stats}
  tables  {}                                      -> {code: 0, tables}
  ping    {}                                      -> {code: 0, pong: True}

Blocking semantics live server-side: an insert/sample request parks its
connection's handler thread in the table's ``RateLimiter`` until the
operation is admitted or its ``timeout_s`` lapses (then answers the
retryable ``rate_limited`` wire error). The admin surface
(``ReplayAdminServer``) follows the CoordinatorServer pattern: GET
``/metrics`` (Prometheus scrape), the fleet-health routes, and GET
``/replay/stats`` for opsctl.
"""
from __future__ import annotations

import json
import socket
import threading
from typing import Optional

from ..comm.serializer import recv_msg, send_msg
from ..obs import get_registry
from .errors import ReplayError
from .store import ReplayStore


class ReplayServer:
    """Thread-per-connection framed-TCP server over one ``ReplayStore``."""

    def __init__(self, store: ReplayStore, host: str = "127.0.0.1", port: int = 0,
                 default_timeout_s: float = 30.0):
        self.store = store
        self.default_timeout_s = default_timeout_s
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        reg = get_registry()
        self._g_conns = reg.gauge(
            "distar_replay_server_connections", "open replay data-plane connections")
        self._c_requests = reg.counter(
            "distar_replay_server_requests_total", "replay request frames handled")

    def start(self) -> "ReplayServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="replay-accept", daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: closing the fd does not wake an accept()
            # blocked in another thread (tcp_frontend.py lesson)
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        t = self._accept_thread
        if t is not None:
            t.join(5.0)
            self._accept_thread = None

    # ------------------------------------------------------------------ loop
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:  # listener closed by stop()
                return
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            threading.Thread(
                target=self._serve_conn, args=(conn,), name="replay-conn",
                daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        self._g_conns.inc()
        with self._conns_lock:
            self._conns.add(conn)
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        req = recv_msg(conn)
                    except (ConnectionError, OSError):
                        return  # peer closed (possibly mid-frame)
                    except ValueError as e:
                        send_msg(conn, {"code": "bad_frame", "error": repr(e)})
                        return
                    self._c_requests.inc()
                    try:
                        send_msg(conn, self._dispatch(req))
                    except (ConnectionError, OSError):
                        return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            self._g_conns.dec()

    def _dispatch(self, req) -> dict:
        if not isinstance(req, dict) or "op" not in req:
            return {"code": "bad_request", "error": f"not a request dict: {type(req)}"}
        op = req["op"]
        timeout_s = float(req.get("timeout_s", self.default_timeout_s))
        try:
            if op == "insert":
                seq = self.store.insert(
                    req["table"], req["item"],
                    priority=float(req.get("priority", 1.0)), timeout_s=timeout_s,
                )
                return {"code": 0, "seq": seq}
            if op == "sample":
                sampled = self.store.sample(
                    req["table"], batch_size=int(req.get("batch_size", 1)),
                    timeout_s=timeout_s,
                )
                return {
                    "code": 0,
                    "items": [s.data for s in sampled],
                    "info": [s.info() for s in sampled],
                }
            if op == "update_priorities":
                return {"code": 0,
                        "applied": self.store.update_priorities(
                            req["table"], req["updates"])}
            if op == "stats":
                return {"code": 0, "stats": self.store.stats()}
            if op == "tables":
                return {"code": 0, "tables": self.store.tables()}
            if op == "ping":
                return {"code": 0, "pong": True}
            return {"code": "bad_request", "error": f"unknown op {op!r}"}
        except ReplayError as e:
            wire = e.to_wire()
            if wire.get("code") == "rate_limited":
                wire.update(side=getattr(e, "side", "?"), timeout_s=timeout_s,
                            state=getattr(e, "state", {}))
            return wire
        except Exception as e:  # a handler bug must not kill the connection
            return {"code": "replay_error", "error": repr(e)}


class ReplayAdminServer:
    """HTTP admin/stats surface on the CoordinatorServer pattern: GET
    ``/metrics`` (Prometheus text of this process's registry), the
    fleet-health routes (``/healthz``, ``/alerts``, ``/timeseries``), and
    GET ``/replay/stats`` (tables + limiter + spill JSON, the opsctl feed)."""

    def __init__(self, store: ReplayStore, host: str = "127.0.0.1", port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.store = store
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                from ..obs import handle_health_get, write_scrape_response

                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/metrics":
                    write_scrape_response(self)
                    return
                if path == "/replay/stats":
                    data = json.dumps(outer.store.stats(), default=str).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if handle_health_get(self, self.path):
                    return
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ReplayAdminServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
