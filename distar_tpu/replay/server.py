"""Replay-store service: framed-TCP data plane + HTTP admin surface.

Wire format = ``comm.serializer`` (8-byte big-endian length prefix around a
pickled+compressed payload) with the ``serve/tcp_frontend`` conventions:
one request/response dict pair per frame, ``{"code": 0, ...}`` on success,
``{"code": <wire code>, "error": ...}`` typed on failure (errors.to_wire).

Requests:
  hello   {compress?, codecs?}            -> {code: 0, compress, codec, shard}
  insert  {table, item, priority?, timeout_s?, idem?} -> {code: 0, seq}
  sample  {table, batch_size?, timeout_s?}        -> {code: 0, items, info}
  update_priorities {table, updates}              -> {code: 0, applied}
  stats   {}                                      -> {code: 0, stats}
  tables  {}                                      -> {code: 0, tables}
  ping    {}                                      -> {code: 0, pong: True}

Blocking semantics live server-side: an insert/sample request parks its
connection's handler thread in the table's ``RateLimiter`` until the
operation is admitted or its ``timeout_s`` lapses (then answers the
retryable ``rate_limited`` wire error). The admin surface
(``ReplayAdminServer``) follows the CoordinatorServer pattern: GET
``/metrics`` (Prometheus scrape), the fleet-health routes, and GET
``/replay/stats`` for opsctl.

Wire compression is negotiated per connection: the optional ``hello``
frame declares whether the client wants payload compression, the server
answers with the setting both sides will use (its own enablement ANDed
in), and every later frame on the connection honours it. A client that
never says hello gets the legacy always-compressed behaviour. Responses
whose bulk is already through the codec — ``Opaque`` spill re-serves —
skip the frame-level compression pass regardless (recompressing lz output
buys bytes-per-CPU nothing). ``distar_replay_{tx,rx}_bytes_{raw,wire}``
counters account both directions so the compression ratio actually paid
for is a scrapeable number, not a guess.

Transport is negotiated in the same ``hello``: a client advertising
``transports: [shm, tcp]`` plus this host's identity gets a shm ring pair
minted (``comm.shm_ring``) and the connection's data frames move over the
rings — zero socket, zero codec, pickle straight into mapped memory —
while the TCP socket stays open as the control channel and fallback leg
(a ring fault or peer death is detected typed and the client's next
attempt rides TCP). A hello whose codec/transport preference lists
contain no recognized name at all is answered with the typed
``bad_hello`` NACK instead of silently degrading.
"""
from __future__ import annotations

import json
import socket
import threading
from typing import Optional

from ..comm import shm_ring
from ..comm.serializer import (
    Opaque,
    dumps_sized,
    frame,
    loads_sized,
    negotiate_codec,
    read_frame,
    sock_recv_exact,
    supported_codecs,
)
from ..obs import (
    finish_trace,
    get_registry,
    join_trace,
    set_active_trace,
    tracing_enabled,
)
from .errors import BadFrameError, BadRequestError, ReplayError
from .store import ReplayStore


class ReplayServer:
    """Thread-per-connection framed-TCP server over one ``ReplayStore``."""

    def __init__(self, store: ReplayStore, host: str = "127.0.0.1", port: int = 0,
                 default_timeout_s: float = 30.0, compress: bool = True,
                 codecs: Optional[tuple] = None, transport: str = "auto",
                 ring_bytes: int = shm_ring.DEFAULT_RING_BYTES):
        self.store = store
        self.default_timeout_s = default_timeout_s
        #: server-side compression enablement; the per-connection setting is
        #: this ANDed with whatever the client's hello asks for
        self.compress = bool(compress)
        #: codecs this server is willing to speak (restrictable per deploy);
        #: the per-connection codec is the client's first preference in here
        self.codecs = tuple(codecs) if codecs is not None else supported_codecs()
        #: transport policy: "auto" negotiates shm with colocated clients,
        #: "shm" the same (shm never *forces* — the TCP leg always remains),
        #: "tcp" refuses rings entirely (the cross-host / drill posture)
        if transport not in ("auto", "shm", "tcp"):
            raise ValueError(f"transport must be auto|shm|tcp, got {transport!r}")
        self.transport = transport
        self.ring_bytes = int(ring_bytes)
        #: live per-transport connection counts (the opsctl digest's
        #: "active transport per connection" answer, served via /replay/stats)
        self._transports = {"tcp": 0, "shm": 0}
        self._transports_lock = threading.Lock()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._ring_services: set = set()
        self._conns_lock = threading.Lock()
        reg = get_registry()
        shard = getattr(store, "shard_id", "") or ""
        extra = {"shard": shard} if shard else {}
        self._g_conns = reg.gauge(
            "distar_replay_server_connections", "open replay data-plane connections",
            **extra)
        self._c_requests = reg.counter(
            "distar_replay_server_requests_total", "replay request frames handled",
            **extra)
        self._c_tx_raw = reg.counter(
            "distar_replay_tx_bytes_raw_total",
            "response payload bytes before wire compression", **extra)
        self._c_tx_wire = reg.counter(
            "distar_replay_tx_bytes_wire_total",
            "response payload bytes actually sent on the wire", **extra)
        self._c_rx_raw = reg.counter(
            "distar_replay_rx_bytes_raw_total",
            "request payload bytes after wire decompression", **extra)
        self._c_rx_wire = reg.counter(
            "distar_replay_rx_bytes_wire_total",
            "request payload bytes actually received on the wire", **extra)

    def start(self) -> "ReplayServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="replay-accept", daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: closing the fd does not wake an accept()
            # blocked in another thread (tcp_frontend.py lesson)
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
            rings = list(self._ring_services)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        # sever the shm leg SYNCHRONOUSLY: a closed socket does not stop a
        # ring pump, and a stopped server must not keep answering data
        # frames out of shared memory (the in-process kill-drill contract)
        for svc in rings:
            svc.stop()
        t = self._accept_thread
        if t is not None:
            t.join(5.0)
            self._accept_thread = None

    # ------------------------------------------------------------------ loop
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:  # listener closed by stop()
                return
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            threading.Thread(
                target=self._serve_conn, args=(conn,), name="replay-conn",
                daemon=True,
            ).start()

    # --------------------------------------------------------- counted wire IO
    def _recv_counted(self, conn: socket.socket):
        blob = read_frame(lambda n: sock_recv_exact(conn, n))
        obj, raw_len = loads_sized(blob)
        self._c_rx_wire.inc(len(blob))
        self._c_rx_raw.inc(raw_len)
        return obj

    def _send_counted(self, conn: socket.socket, obj, compress: bool,
                      codec: str = "lz4") -> None:
        # skip the compression pass when the response bulk is already
        # through the codec (Opaque spill re-serves): lz-of-lz costs a full
        # CPU pass for ~zero byte savings
        if compress and isinstance(obj, dict):
            items = obj.get("items")
            if items and any(isinstance(i, Opaque) for i in items):
                compress = False
        blob, raw_len = dumps_sized(obj, compress=compress, codec=codec)
        conn.sendall(frame(blob))
        self._c_tx_wire.inc(len(blob))
        self._c_tx_raw.inc(raw_len)

    def _count_transport(self, kind: str, delta: int) -> None:
        with self._transports_lock:
            self._transports[kind] = max(0, self._transports[kind] + delta)

    def transport_counts(self) -> dict:
        with self._transports_lock:
            return dict(self._transports)

    def _serve_conn(self, conn: socket.socket) -> None:
        self._g_conns.inc()
        with self._conns_lock:
            self._conns.add(conn)
        compress = self.compress  # legacy clients never negotiate: stay on
        codec = "lz4"  # ...and never leave the legacy codec
        ring_svc = None  # set when this connection negotiates shm
        self._count_transport("tcp", +1)
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        req = self._recv_counted(conn)
                    except (ConnectionError, OSError):
                        return  # peer closed (possibly mid-frame)
                    except ValueError as e:
                        self._send_counted(
                            conn, BadFrameError(repr(e)).to_wire(),
                            compress, codec)
                        return
                    self._c_requests.inc()
                    if isinstance(req, dict) and req.get("op") == "hello":
                        # a hello whose preference lists name NOTHING this
                        # protocol knows is garbage: NACK typed, never
                        # silently degrade (then drop the stream — a peer
                        # that desynced once can't be trusted framed)
                        nack = shm_ring.hello_nack(req)
                        if nack:
                            self._send_counted(
                                conn, {"code": "bad_hello", "error": nack},
                                compress, codec)
                            return
                        # per-connection negotiation: both sides commit to
                        # the ANDed compression setting and the intersected
                        # codec choice for every later frame
                        compress = self.compress and bool(req.get("compress", True))
                        codec = negotiate_codec(req.get("codecs"), self.codecs)
                        reply = {"code": 0, "compress": compress, "codec": codec,
                                 "shard": getattr(self.store, "shard_id", "")}
                        if ring_svc is None:
                            # transport leg: mint a ring pair when client +
                            # server share this host; data frames then move
                            # over shm while this socket stays the control/
                            # fallback channel
                            extra, peer = shm_ring.negotiate_server(
                                req, self.transport, self.ring_bytes,
                                op="replay")
                            reply.update(extra)
                            if peer is not None:
                                ring_svc = shm_ring.RingService(
                                    peer, self._dispatch,
                                    name="replay-shm-ring").start()
                                with self._conns_lock:
                                    self._ring_services.add(ring_svc)
                                self._count_transport("tcp", -1)
                                self._count_transport("shm", +1)
                        try:
                            self._send_counted(conn, reply, compress, codec)
                        except (ConnectionError, OSError):
                            return
                        continue
                    try:
                        self._send_counted(conn, self._dispatch(req), compress,
                                           codec)
                    except (ConnectionError, OSError):
                        return
        finally:
            if ring_svc is not None:
                ring_svc.stop()
                self._count_transport("shm", -1)
            else:
                self._count_transport("tcp", -1)
            with self._conns_lock:
                self._conns.discard(conn)
                if ring_svc is not None:
                    self._ring_services.discard(ring_svc)
            self._g_conns.dec()

    def _dispatch(self, req) -> dict:
        if not isinstance(req, dict) or "op" not in req:
            return BadRequestError(f"not a request dict: {type(req)}").to_wire()
        op = req["op"]
        timeout_s = float(req.get("timeout_s", self.default_timeout_s))
        # server-side span joining the client's wire trace field (both
        # transports — the field is inside the pickled frame either way);
        # installed as this handler thread's ACTIVE trace so the table's
        # rate limiter attributes its block time (blocked_s) to the request
        ctx = None
        if op in ("insert", "sample") and req.get("trace") and tracing_enabled():
            ctx = join_trace(req.get("trace"), f"replay_{op}",
                             table=str(req.get("table", "")),
                             shard=getattr(self.store, "shard_id", "") or "")
        prev = set_active_trace(ctx) if ctx is not None else None
        try:
            out = self._dispatch_op(req, op, timeout_s)
        finally:
            if ctx is not None:
                set_active_trace(prev)
        if ctx is not None:
            code = out.get("code")
            outcome = ("ok" if code == 0 else
                       "shed" if code in ("rate_limited", "draining") else "error")
            finish_trace(ctx, "replay_done", outcome=outcome)
        return out

    def _dispatch_op(self, req: dict, op: str, timeout_s: float) -> dict:
        try:
            if op == "insert":
                seq = self.store.insert(
                    req["table"], req["item"],
                    priority=float(req.get("priority", 1.0)), timeout_s=timeout_s,
                    idem=req.get("idem"),
                )
                return {"code": 0, "seq": seq}
            if op == "sample":
                sampled = self.store.sample(
                    req["table"], batch_size=int(req.get("batch_size", 1)),
                    timeout_s=timeout_s,
                )
                return {
                    "code": 0,
                    "items": [s.data for s in sampled],
                    "info": [s.info() for s in sampled],
                }
            if op == "update_priorities":
                return {"code": 0,
                        "applied": self.store.update_priorities(
                            req["table"], req["updates"])}
            if op == "stats":
                return {"code": 0, "stats": self.store.stats()}
            if op == "tables":
                return {"code": 0, "tables": self.store.tables()}
            if op == "ping":
                return {"code": 0, "pong": True}
            return BadRequestError(f"unknown op {op!r}").to_wire()
        except ReplayError as e:
            wire = e.to_wire()
            if wire.get("code") == "rate_limited":
                wire.update(side=getattr(e, "side", "?"), timeout_s=timeout_s,
                            state=getattr(e, "state", {}))
            return wire
        except Exception as e:  # a handler bug must not kill the connection
            return {"code": "replay_error", "error": repr(e)}


class ReplayAdminServer:
    """HTTP admin/stats surface on the CoordinatorServer pattern: GET
    ``/metrics`` (Prometheus text of this process's registry), the
    fleet-health routes (``/healthz``, ``/alerts``, ``/timeseries``), and
    GET ``/replay/stats`` (tables + limiter + spill JSON, the opsctl feed)."""

    def __init__(self, store: ReplayStore, host: str = "127.0.0.1", port: int = 0,
                 server: Optional[ReplayServer] = None,
                 on_drain: Optional[callable] = None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.store = store
        #: optional data-plane server handle: lets /replay/stats report the
        #: live per-connection transport split (shm vs tcp) for opsctl
        self.data_server = server
        #: drain hook the serving entrypoint installs: runs BEFORE the
        #: store flips to draining (deregister the coordinator lease first —
        #: a draining shard must leave discovery before it starts refusing)
        self.on_drain = on_drain
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                from ..obs import handle_health_get, write_scrape_response

                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/metrics":
                    write_scrape_response(self)
                    return
                if path == "/replay/stats":
                    stats = outer.store.stats()
                    if outer.data_server is not None:
                        stats["transports"] = outer.data_server.transport_counts()
                    data = json.dumps(stats, default=str).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if handle_health_get(self, self.path):
                    return
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path != "/drain":
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                # graceful retirement: deregister first (leave discovery
                # NOW), then refuse new inserts typed while the resident
                # tail keeps draining to samplers; the serving process
                # exits once residency reaches zero or its drain timeout
                try:
                    if outer.on_drain is not None:
                        try:
                            outer.on_drain()
                        except Exception:  # noqa: BLE001 - lease still lapses
                            # best-effort by contract (the lease expires on
                            # its own) but never silent: a deregister hook
                            # that always fails means every drain leaves a
                            # zombie discovery entry for a full lease
                            get_registry().counter(
                                "distar_replay_drain_hook_errors_total",
                                "drain deregister-hook failures (lease "
                                "expiry is the fallback)",
                            ).inc()
                    info = outer.store.begin_drain()
                    data = json.dumps({"code": 0, "info": info}).encode()
                except Exception as e:  # noqa: BLE001 - probe must not wedge us
                    data = json.dumps({"code": 1, "info": repr(e)}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ReplayAdminServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        # reap the serve loop before closing its socket under it
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._server.server_close()


def main(argv=None) -> int:
    """Minimal standalone shard: ``python -m distar_tpu.replay.server``.

    The jax-free twin of ``bin/rl_train --type replay`` (no health stack, no
    supervisor) — what the sharded bench and chaos drills spawn per shard so
    fleet members are real OS processes (separate GILs, real sockets), not
    threads sharing the parent's interpreter. Prints one parseable
    ``REPLAY-SHARD <host> <port>`` line once serving, then runs until
    SIGTERM/SIGINT or stdin EOF (so a dying parent reaps the fleet)."""
    import argparse
    import signal
    import sys
    import time

    from .spill import SpillRing
    from .store import TableConfig

    p = argparse.ArgumentParser(description="standalone replay shard")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--shard-id", default="")
    p.add_argument("--spill-dir", default="")
    p.add_argument("--spill-max", type=int, default=4096)
    p.add_argument("--max-size", type=int, default=4096)
    p.add_argument("--sampler", default="uniform",
                   choices=("fifo", "uniform", "prioritized"))
    p.add_argument("--spi", type=float, default=0.0,
                   help="samples-per-insert ratio (<=0 disables)")
    p.add_argument("--min-size", type=int, default=1)
    p.add_argument("--error-buffer", type=float, default=None)
    p.add_argument("--no-compress", dest="compress", action="store_false",
                   help="refuse wire compression in the hello negotiation")
    p.add_argument("--codecs", default="",
                   help="comma list restricting the codecs this shard will "
                        "negotiate (default: everything the host supports; "
                        "lz4 always remains the fallback)")
    p.add_argument("--transport", default="auto", choices=("auto", "shm", "tcp"),
                   help="data-plane transport policy: auto/shm negotiate "
                        "shared-memory rings with colocated clients, tcp "
                        "refuses rings (cross-host posture)")
    p.add_argument("--coordinator", default="",
                   help="coordinator host:port to register under the "
                        "replay_shard token (lease/heartbeat; sharded "
                        "clients and opsctl discover the fleet there)")
    p.add_argument("--lease-s", type=float, default=10.0)
    p.add_argument("--admin-port", type=int, default=-1,
                   help=">= 0 starts the HTTP admin surface (/replay/stats, "
                        "/metrics, POST /drain) on that port (0 = ephemeral; "
                        "default off). Advertised as admin_port meta on the "
                        "coordinator registration.")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="graceful-retirement budget: after POST /drain, "
                        "exit once every resident item drained out, or when "
                        "this many seconds passed — whichever comes first")
    args = p.parse_args(argv)

    cfg = TableConfig(
        max_size=args.max_size, sampler=args.sampler,
        samples_per_insert=None if args.spi <= 0 else args.spi,
        min_size_to_sample=max(args.min_size, 1),
        error_buffer=args.error_buffer,
    )
    spill = SpillRing(args.spill_dir, max_items=args.spill_max) \
        if args.spill_dir else None
    store = ReplayStore(table_factory=lambda name: cfg, spill=spill,
                        shard_id=args.shard_id, recover_encoded=True)
    recovered = store.recover()
    codecs = tuple(c for c in args.codecs.split(",") if c.strip()) or None
    server = ReplayServer(store, host=args.host, port=args.port,
                          compress=args.compress, codecs=codecs,
                          transport=args.transport).start()

    deregister = None
    admin = None
    if args.coordinator:
        from ..comm.discovery import unregister_endpoint
        from .sharding import register_shard

        chost, _, cport = args.coordinator.rpartition(":")
        coord = (chost or "127.0.0.1", int(cport))

    if args.admin_port >= 0:
        admin = ReplayAdminServer(store, host=args.host, port=args.admin_port,
                                  server=server).start()

    if args.coordinator:
        beat = register_shard(
            coord, server.host, server.port,
            meta={"shard_id": args.shard_id,
                  **({"admin_port": admin.port} if admin is not None else {})},
            lease_s=args.lease_s,
        )

        def deregister(beat=beat, coord=coord, host=server.host,
                       port=server.port):
            beat.stop_event.set()
            try:
                unregister_endpoint(coord, host, port)
            except Exception:  # noqa: BLE001 - best-effort; lease still lapses
                pass

        if admin is not None:
            # drain step 1: leave discovery before refusing any insert
            admin.on_drain = deregister

    # CLI entrypoint output: the parseable serving line callers wait for
    print(f"REPLAY-SHARD {server.host} {server.port} "  # lint: allow-print
          f"recovered={recovered}"
          + (f" admin={admin.port}" if admin is not None else ""), flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    drain_deadline = None
    try:
        import select

        while not stop.is_set():
            # poll (not block) stdin so a signal still exits promptly; EOF
            # on a piped stdin means the parent went away — reap ourselves
            ready, _, _ = select.select([sys.stdin], [], [], 0.5)
            if ready and not sys.stdin.buffer.read(1):
                break
            # graceful-retirement exit: once POST /drain flipped the store,
            # serve until the resident tail drained out (samples keep
            # flowing), bounded by --drain-timeout-s
            if store.draining:
                if drain_deadline is None:
                    drain_deadline = time.monotonic() + args.drain_timeout_s
                if (store.resident_items() == 0
                        or time.monotonic() > drain_deadline):
                    break
    except (OSError, ValueError, KeyboardInterrupt):
        pass
    if deregister is not None:
        deregister()
    server.stop()
    if admin is not None:
        admin.stop()
    if spill is not None:
        spill.flush()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
