"""Sharded replay fleet: consistent-hash routing + learner-side fan-in.

One ``ReplayStore`` tops out around ~4k inserts/s + ~9k samples/s at 16 KB
over loopback — enough for one learner, not for a pod. This module scales
the data plane horizontally the MindSpeed-RL distributed-dataflow way:
N independent stores (each with its own tables, rate limiter and spill),
with ALL routing decided client-side so the fleet needs no proxy tier.

Routing (``HashRing``): classic consistent hashing over ``vnode`` virtual
points per shard, keyed by a *stable* digest (md5 — NEVER ``hash()``, which
is salted per process). The shard identity is its ``host:port`` address, so
a restarted shard keeps its ring segment, and growing the fleet N -> N+1
remaps only ~1/(N+1) of the key space (tested). Every insert routes by
``(table, trajectory key)``; a directed read/update for the same key lands
on the same shard by construction.

Fan-in (``ShardedSampleClient``): the learner samples whole batches from
one shard at a time, rotating round-robin (or weighted by resident items).
The samples-per-insert invariant is enforced *per shard* — each store's own
``RateLimiter`` paces the batches it serves against the inserts it
received — so a stalled/dead/rate-limited shard blocks only itself: the
rotation skips it (counted) and keeps the learner fed from the rest of the
fleet within the caller's timeout.

Discovery (``ShardMap``): a static comma-separated address list, or the
coordinator's register/lease path — shard processes register under the
``replay_shard`` token and the map is read back (non-destructively) via
the ``peers`` route, so lease-evicted stores drop out of new maps.
"""
from __future__ import annotations

import bisect
import hashlib
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import get_registry
from ..resilience import CircuitOpenError, RetryableError, RetryPolicy
from .client import InsertClient, SampleClient
from .errors import (
    InvalidBatchError,
    RateLimitTimeout,
    ReplayError,
    StoreDrainingError,
    UnknownTableError,
)

#: coordinator token replay shards register under (bin/rl_train --type replay)
SHARD_TOKEN = "replay_shard"


def stable_hash(key: str) -> int:
    """64-bit digest that is identical across processes, machines and runs
    (md5 prefix; ``hash()`` is PYTHONHASHSEED-salted and would scatter the
    ring differently in every process)."""
    return int.from_bytes(hashlib.md5(key.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over named nodes with virtual points."""

    def __init__(self, nodes: Sequence[str], vnodes: int = 128):
        if not nodes:
            raise ValueError("hash ring needs at least one node")
        self.nodes = list(dict.fromkeys(nodes))  # order-preserving dedupe
        self.vnodes = int(vnodes)
        points: List[Tuple[int, str]] = [
            (stable_hash(f"{node}#{v}"), node)
            for node in self.nodes
            for v in range(self.vnodes)
        ]
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [n for _, n in points]

    def lookup(self, key: str) -> str:
        """Owning node for ``key``: first ring point clockwise of the key's
        hash (wrapping past the top)."""
        i = bisect.bisect_right(self._hashes, stable_hash(key))
        if i == len(self._hashes):
            i = 0
        return self._owners[i]


class ShardMap:
    """Ordered shard address list + the ring built over it.

    The canonical key for routing is ``"<table>/<key>"`` so per-player
    tables spread independently (two players' trajectory #7 need not share
    a shard). Addresses are the shard identities: stable across restarts,
    so recovery lands recovered items exactly where routing looks for them.
    """

    def __init__(self, addrs: Sequence[str], vnodes: int = 128):
        self.addrs = list(dict.fromkeys(a.strip() for a in addrs if a.strip()))
        if not self.addrs:
            raise ValueError("shard map needs at least one 'host:port' address")
        self._ring = HashRing(self.addrs, vnodes=vnodes)

    def __len__(self) -> int:
        return len(self.addrs)

    def shard_for(self, table: str, key: str) -> str:
        """Deterministic owner address for an item key within a table."""
        return self._ring.lookup(f"{table}/{key}")

    @classmethod
    def parse(cls, spec: str, vnodes: int = 128) -> "ShardMap":
        """``"h1:p1,h2:p2,..."`` -> map (a single address is a 1-shard map)."""
        return cls(str(spec).split(","), vnodes=vnodes)

    @classmethod
    def discover(cls, coordinator_addr: Tuple[str, int], token: str = SHARD_TOKEN,
                 vnodes: int = 128) -> "ShardMap":
        """Read the live shard fleet from the coordinator's non-destructive
        ``peers`` route (lease-expired shards have already been evicted).
        Raises ``ValueError`` when no shard has registered yet."""
        from ..comm.discovery import discover_endpoints

        records = discover_endpoints(coordinator_addr, token)
        addrs = sorted({f"{r['ip']}:{r['port']}" for r in records})
        if not addrs:
            host, port = coordinator_addr
            raise ValueError(
                f"no {token!r} registrations at coordinator {host}:{port} "
                "(are the replay shards up, and started with --coordinator-addr?)"
            )
        return cls(addrs, vnodes=vnodes)


def _split_addr(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class _ShardedBase:
    """Shared plumbing: one lazily-dialed client per shard, each with its
    own retry policy + circuit breaker (the PR 4 fabric, per shard — one
    wedged store must not open the breaker for the healthy rest)."""

    _client_cls: Callable = None  # type: ignore[assignment]

    def __init__(self, shard_map: ShardMap, retry_policy: Optional[RetryPolicy] = None,
                 compress: bool = True, timeout_s: float = 60.0,
                 codec: str = "lz4", transport: str = "auto"):
        self.shard_map = shard_map
        self._retry_policy = retry_policy
        self._compress = compress
        self._codec = codec
        self._transport = transport
        self._timeout_s = timeout_s
        self._clients: Dict[str, object] = {}
        self._lock = threading.Lock()
        #: shards observed mid-drain (typed ``draining`` answers): routed
        #: around until a membership refresh drops them from the map
        self._draining: set = set()
        self._refresher = None

    # -------------------------------------------------------- live membership
    def set_shard_map(self, shard_map: ShardMap) -> None:
        """Install a freshly discovered map (live membership: joins appear,
        drained/lease-evicted shards disappear — the ≤1/(N+1) consistent-
        hash remap bounds how many keys move). Clients held against
        departed shards are closed; drain marks for addresses no longer in
        the map are pruned."""
        with self._lock:
            self.shard_map = shard_map
            self._draining &= set(shard_map.addrs)
            dead = [a for a in self._clients if a not in shard_map.addrs]
            closed = [self._clients.pop(a) for a in dead]
        for c in closed:
            try:
                c.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass

    def start_refresh(self, coordinator_addr: Tuple[str, int],
                      interval_s: float = 10.0,
                      token: str = SHARD_TOKEN) -> None:
        """Periodically re-discover the shard fleet from the coordinator
        (the shared ``comm.discovery`` refresh idiom) so this long-lived
        client sees scale-ups and drains without a restart. Empty reads are
        ignored (a restarting broker must not wipe a working map)."""
        from ..comm.discovery import start_refresh

        def apply(records):
            addrs = sorted({f"{r['ip']}:{r['port']}" for r in records})
            if addrs and addrs != self.shard_map.addrs:
                self.set_shard_map(ShardMap(addrs))

        if self._refresher is None:
            self._refresher = start_refresh(coordinator_addr, token, apply,
                                            interval_s=interval_s)

    def note_draining(self, addr: str) -> None:
        """Route around ``addr`` until the membership refresh retires it."""
        with self._lock:
            self._draining.add(addr)
        get_registry().counter(
            "distar_replay_drains_observed_total",
            "typed draining answers that moved routing off a retiring shard",
            shard=addr,
        ).inc()

    def client_for(self, addr: str):
        with self._lock:
            client = self._clients.get(addr)
            if client is None:
                host, port = _split_addr(addr)
                client = type(self)._client_cls(
                    host, port, timeout_s=self._timeout_s,
                    retry_policy=self._retry_policy, compress=self._compress,
                    codec=self._codec, transport=self._transport,
                )
                self._clients[addr] = client
            return client

    def transports(self) -> Dict[str, str]:
        """Active transport per dialed shard connection (shm for colocated
        shards, tcp for cross-host ones — mixed fleets are expected)."""
        with self._lock:
            return {addr: c.transport_active
                    for addr, c in self._clients.items()}

    def ping(self) -> bool:
        return all(self.client_for(a).ping() for a in self.shard_map.addrs)

    def tables(self) -> List[str]:
        names = set()
        for addr in self.shard_map.addrs:
            try:
                names.update(self.client_for(addr).tables())
            # analysis: allow(retryable-swallowed) — fan-in isolation contract (docs/data_plane.md): a dead shard hides its tables, not the fleet's; per-shard failures surface via breaker/fanin-skip counters on the data path
            except (ReplayError, ConnectionError, OSError, CircuitOpenError):
                continue
        return sorted(names)

    def fleet_stats(self) -> Dict[str, dict]:
        """Per-shard ``/replay/stats`` payloads keyed by shard address;
        unreachable shards report ``{"error": ...}`` instead of hiding."""
        out: Dict[str, dict] = {}
        for addr in self.shard_map.addrs:
            try:
                out[addr] = self.client_for(addr).stats()
            except Exception as e:  # noqa: BLE001 - digest must never raise
                out[addr] = {"error": repr(e)}
        return out

    def stats(self) -> dict:
        return {"shards": self.fleet_stats()}

    def close(self) -> None:
        if self._refresher is not None:
            self._refresher.stop_event.set()
            self._refresher = None
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ShardedInsertClient(_ShardedBase):
    """Actor-side writer over the fleet: every trajectory routes to the
    shard owning ``(table, key)`` on the ring. Keys default to a
    process-unique monotonic sequence so concurrent actors spread load
    without coordination; pass an explicit ``key`` to pin related items
    (e.g. one episode) to one shard."""

    _client_cls = InsertClient

    def __init__(self, shard_map: ShardMap, **kwargs):
        super().__init__(shard_map, **kwargs)
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._key_base = f"{os.getpid():x}-{stable_hash(str(time.time())) & 0xFFFF:04x}"
        # counters are minted lazily: live membership means shards can join
        # after construction
        self._c_routed: Dict[str, object] = {}
        self._overlay_rings: Dict[tuple, HashRing] = {}

    def _routed_counter(self, addr: str):
        c = self._c_routed.get(addr)
        if c is None:
            c = self._c_routed[addr] = get_registry().counter(
                "distar_replay_shard_inserts_total",
                "inserts routed to each shard by the consistent-hash ring",
                shard=addr,
            )
        return c

    def next_key(self) -> str:
        with self._seq_lock:
            self._seq += 1
            return f"{self._key_base}-{self._seq}"

    def shard_for(self, table: str, key: str) -> str:
        """Owner for ``(table, key)`` under the CURRENT map, routed around
        shards observed mid-drain (a drained shard deregisters, so the next
        membership refresh makes the overlay permanent)."""
        m = self.shard_map
        with self._lock:
            draining = self._draining & set(m.addrs)
        if not draining or len(draining) >= len(m.addrs):
            return m.shard_for(table, key)
        cache_key = (tuple(m.addrs), frozenset(draining))
        ring = self._overlay_rings.get(cache_key)
        if ring is None:
            self._overlay_rings.clear()  # one live overlay at a time
            ring = self._overlay_rings[cache_key] = HashRing(
                [a for a in m.addrs if a not in draining])
        return ring.lookup(f"{table}/{key}")

    def insert(self, table: str, item, priority: float = 1.0,
               timeout_s: Optional[float] = None, key: Optional[str] = None) -> int:
        key = key if key is not None else self.next_key()
        # a shard answering the typed ``draining`` error is retiring: mark
        # it, re-route this key on the overlay ring (every future key skips
        # it too) and re-issue — at most once per fleet member
        for _ in range(max(len(self.shard_map), 1)):
            addr = self.shard_for(table, key)
            try:
                seq = self.client_for(addr).insert(
                    table, item, priority=priority, timeout_s=timeout_s)
            except StoreDrainingError:
                self.note_draining(addr)
                continue
            self._routed_counter(addr).inc()
            return seq
        raise StoreDrainingError(
            f"every shard in the {len(self.shard_map)}-member fleet is draining")


class ShardedSampleClient(_ShardedBase):
    """Learner-side fan-in: one whole batch per call from one shard,
    rotating round-robin (default) or weighted by resident items. A shard
    that is rate-limited, dead, or breaker-open is skipped — it blocks
    only itself — and the rotation keeps offering the rest of the fleet
    until the caller's ``timeout_s`` lapses. Per-shard spi holds because
    each store's own limiter admits (or blocks) the batches it serves."""

    _client_cls = SampleClient

    #: loaders key on this to hand per-item shard info back for routing
    sharded = True

    def __init__(self, shard_map: ShardMap, mode: str = "round_robin",
                 retry_policy: Optional[RetryPolicy] = None, **kwargs):
        assert mode in ("round_robin", "weighted"), mode
        # the inner client must fail FAST: rotation is the retry. The outer
        # loop re-offers a shard on later passes, which also redials through
        # a store restart within the caller's deadline.
        retry_policy = retry_policy or RetryPolicy(
            max_attempts=2, backoff_base_s=0.05, backoff_max_s=0.2, deadline_s=5.0)
        super().__init__(shard_map, retry_policy=retry_policy, **kwargs)
        self.mode = mode
        self._rr = 0
        self._weights: Dict[str, float] = {}
        self._weights_ts = 0.0
        # minted lazily: live membership means shards can join mid-run
        self._c_samples: Dict[str, object] = {}
        self._c_skips: Dict[str, object] = {}

    def _sample_counter(self, addr: str):
        c = self._c_samples.get(addr)
        if c is None:
            c = self._c_samples[addr] = get_registry().counter(
                "distar_replay_fanin_samples_total",
                "items served to the fan-in sampler, per shard", shard=addr)
        return c

    def _skip_counter(self, addr: str):
        c = self._c_skips.get(addr)
        if c is None:
            c = self._c_skips[addr] = get_registry().counter(
                "distar_replay_fanin_skips_total",
                "fan-in rotations that skipped a shard (pacing/fault/breaker)",
                shard=addr)
        return c

    # ----------------------------------------------------------- shard order
    def _refresh_weights(self, max_age_s: float = 5.0) -> None:
        now = time.monotonic()
        if now - self._weights_ts < max_age_s:
            return
        self._weights_ts = now
        for addr, st in self.fleet_stats().items():
            tables = st.get("tables") if isinstance(st, dict) else None
            self._weights[addr] = float(sum(
                t.get("size", 0) for t in (tables or {}).values())) if tables else 0.0

    def _order(self) -> List[str]:
        addrs = self.shard_map.addrs
        if self.mode == "weighted" and len(addrs) > 1:
            self._refresh_weights()
            start = self._rr
            self._rr += 1
            # fullest shards first; the rotating tiebreak keeps equal-weight
            # fleets fair instead of hammering the lexicographic winner
            return sorted(
                addrs,
                key=lambda a: (-self._weights.get(a, 0.0),
                               (addrs.index(a) - start) % len(addrs)),
            )
        start = self._rr
        self._rr += 1
        return [addrs[(start + i) % len(addrs)] for i in range(len(addrs))]

    # -------------------------------------------------------------------- api
    def sample(self, table: str, batch_size: int = 1,
               timeout_s: Optional[float] = None):
        deadline = time.monotonic() + (timeout_s if timeout_s is not None else 60.0)
        # short per-shard offers so one blocked store can't eat the budget;
        # a single-shard map degenerates to polling that store
        attempt_s = max(0.2, min(2.0, (timeout_s or 60.0) / (2 * len(self.shard_map))))
        unknown_tables = 0
        last_state: dict = {}
        while True:
            unknown_tables = 0
            for addr in self._order():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RateLimitTimeout("sample", timeout_s or 0.0, last_state)
                try:
                    items, info = self.client_for(addr).sample(
                        table, batch_size=batch_size,
                        timeout_s=min(attempt_s, remaining),
                    )
                except InvalidBatchError:
                    raise  # config error: waiting/rotating cannot fix it
                except RateLimitTimeout as e:
                    last_state = {"shard": addr, **(e.state or {})}
                    self._skip_counter(addr).inc()
                    continue
                except UnknownTableError:
                    unknown_tables += 1
                    self._skip_counter(addr).inc()
                    continue
                except (ReplayError, CircuitOpenError, RetryableError,
                        ConnectionError, OSError):
                    self._skip_counter(addr).inc()
                    continue
                for d in info:
                    d["shard"] = addr
                self._sample_counter(addr).inc(len(items))
                return items, info
            if unknown_tables == len(self.shard_map):
                raise UnknownTableError(
                    f"no shard in the fleet holds table {table!r}")
            if time.monotonic() >= deadline:
                raise RateLimitTimeout("sample", timeout_s or 0.0, last_state)

    def update_priorities(self, table: str, updates: Dict[int, float],
                          info: Optional[List[dict]] = None) -> int:
        """PER refresh across the fleet. With ``info`` (the sample-info dicts
        whose ``seq``/``shard`` pairs produced these updates) each update is
        routed to exactly its shard; without, the updates broadcast (unknown
        seqs are ignored server-side, so broadcast is correct but wasteful —
        and wrong only if two shards reuse a seq, which per-shard counters
        make likely: always pass info when you have it)."""
        by_shard: Dict[str, Dict[int, float]] = {}
        if info:
            shard_of = {int(d["seq"]): d.get("shard") for d in info if "seq" in d}
            for seq, pr in updates.items():
                addr = shard_of.get(int(seq))
                for target in ([addr] if addr else self.shard_map.addrs):
                    by_shard.setdefault(target, {})[int(seq)] = float(pr)
        else:
            for addr in self.shard_map.addrs:
                by_shard[addr] = {int(s): float(p) for s, p in updates.items()}
        applied = 0
        for addr, batch in by_shard.items():
            try:
                applied += self.client_for(addr).update_priorities(table, batch)
            # analysis: allow(retryable-swallowed) — priority updates are best-effort PER (docs/data_plane.md): a dead shard's items are gone anyway, and the applied count the caller gets back reflects the skip
            except (ReplayError, ConnectionError, OSError, CircuitOpenError):
                continue
        return applied


def register_shard(coordinator_addr: Tuple[str, int], host: str, port: int,
                   meta: Optional[dict] = None, lease_s: Optional[float] = None,
                   heartbeat_interval_s: Optional[float] = None,
                   stop_event: Optional[threading.Event] = None) -> threading.Thread:
    """Register one shard under ``SHARD_TOKEN`` and keep its lease alive
    from a daemon thread (re-registering when the broker says it lost us —
    the PR 4 heartbeat contract). Returns the started thread."""
    from ..comm.discovery import register_endpoint

    return register_endpoint(
        coordinator_addr, SHARD_TOKEN, host, port, meta=meta, lease_s=lease_s,
        heartbeat_interval_s=heartbeat_interval_s, stop_event=stop_event,
    )
