"""Distributed trajectory store: Reverb-style tables with prioritized
sampling, samples-per-insert rate control, and fsync'd disk spill so acked
inserts survive a store crash.

The decoupling layer between the actor fleet and the learner(s): actors
``InsertClient.insert`` trajectories into per-player tables, learners
``SampleClient.sample`` batches out, and the ``RateLimiter`` makes the
reuse ratio (and therefore staleness) a configured invariant instead of an
accident of queue sizes. See docs/data_plane.md for the shuttle-path vs
store-path contract.
"""
from .client import DEFAULT_REPLAY_POLICY, InsertClient, SampleClient
from .errors import (
    InvalidBatchError,
    ItemCorruptError,
    RateLimitTimeout,
    ReplayError,
    StoreDrainingError,
    UnknownTableError,
    error_from_wire,
)
from .local import LocalReplayClient, is_inproc_addr, local_store, set_local_store
from .server import ReplayAdminServer, ReplayServer
from .sharding import (
    SHARD_TOKEN,
    HashRing,
    ShardMap,
    ShardedInsertClient,
    ShardedSampleClient,
    register_shard,
    stable_hash,
)
from .spill import SpillRing
from .store import (
    RateLimiter,
    ReplayStore,
    ReplayTable,
    SampledItem,
    SumTree,
    TableConfig,
)

__all__ = [
    "DEFAULT_REPLAY_POLICY",
    "InsertClient",
    "SampleClient",
    "InvalidBatchError",
    "ItemCorruptError",
    "RateLimitTimeout",
    "ReplayError",
    "StoreDrainingError",
    "UnknownTableError",
    "error_from_wire",
    "LocalReplayClient",
    "is_inproc_addr",
    "local_store",
    "set_local_store",
    "ReplayAdminServer",
    "ReplayServer",
    "SHARD_TOKEN",
    "HashRing",
    "ShardMap",
    "ShardedInsertClient",
    "ShardedSampleClient",
    "register_shard",
    "stable_hash",
    "SpillRing",
    "RateLimiter",
    "ReplayStore",
    "ReplayTable",
    "SampledItem",
    "SumTree",
    "TableConfig",
]
