"""Replay-store clients: acked inserts and blocking samples over framed TCP.

Both clients follow the ``serve/tcp_frontend`` ServeClient shape — one
connection, one request in flight, transport faults reconnect-and-retry
under a ``resilience.RetryPolicy`` behind a per-client ``CircuitBreaker``
(no connect storms against a dead store). Typed wire errors rehydrate into
the ``replay.errors`` taxonomy; ``rate_limited`` is *retryable* (the store
is pacing, not failing), so a default-policy client transparently rides
through limiter blocks AND store restarts within its deadline budget.

Exactly-once inserts: every logical ``insert`` mints one idempotency key
that rides EVERY retry of that insert. A retry after the ambiguous failure
(server committed, ack lost when the connection died) is answered from the
store's idem cache with the original seq instead of re-applying — no
duplicate item, no duplicate spill blob. (A retry that crosses a store
*restart* still lands as the documented at-least-once duplicate: the cache
is process-lifetime, and a duplicate trajectory is benign for RL training.)

Wire compression is negotiated once per connection: ``_connect`` sends a
``hello`` declaring this client's preference — on/off AND a codec
preference list (``lz4`` default, ``zstd`` when the host has a binding) —
the server answers the ANDed setting plus the chosen codec name, and both
directions honour them. A pre-negotiation server (or one that answers
hello with an error) degrades to the legacy always-compressed lz4
contract, so mixed-version fleets interoperate.
"""
from __future__ import annotations

import socket
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..comm.serializer import maybe_decode, recv_msg, send_msg, supported_codecs
from ..resilience import CircuitBreaker, RetryPolicy, retry_call
from .errors import error_from_wire

#: store RPCs ride through limiter blocks and a several-second store
#: restart by default; the deadline bounds how long an actor/learner can
#: be parked before the fault surfaces to its supervisor
DEFAULT_REPLAY_POLICY = RetryPolicy(
    max_attempts=6, backoff_base_s=0.2, backoff_max_s=3.0, deadline_s=120.0,
)


class _ReplayClientBase:
    def __init__(self, host: str, port: int, timeout_s: float = 60.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 op_prefix: str = "replay", compress: bool = True,
                 codec: str = "lz4"):
        self._addr = (host, port)
        self._timeout_s = timeout_s
        self._policy = retry_policy or DEFAULT_REPLAY_POLICY
        self._breaker = breaker or CircuitBreaker(
            op=f"{op_prefix}:{host}:{port}", failure_threshold=8, reset_after_s=5.0)
        self._op_prefix = op_prefix
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        #: what this side ASKS for; the per-connection negotiated settings
        #: (server's enablement/choice ANDed in) land in _neg_* on connect
        self._want_compress = bool(compress)
        self._neg_compress = bool(compress)
        # preference list: the asked-for codec first, lz4 as the universal
        # fallback; only codecs THIS host can decode are ever offered
        prefs = [c for c in dict.fromkeys((codec, "lz4"))
                 if c in supported_codecs()]
        self._want_codecs = prefs or ["lz4"]
        self._neg_codec = "lz4"
        self.server_shard_id: str = ""

    def _connect(self) -> None:
        self.close()
        self._sock = socket.create_connection(self._addr, timeout=self._timeout_s)
        self._sock.settimeout(self._timeout_s)
        try:
            send_msg(self._sock, {"op": "hello", "compress": self._want_compress,
                                  "codecs": list(self._want_codecs)},
                     compress=False)
            resp = recv_msg(self._sock)
        except (ConnectionError, OSError, ValueError):
            self.close()
            raise
        if isinstance(resp, dict) and resp.get("code") == 0 and "compress" in resp:
            self._neg_compress = bool(resp["compress"])
            self._neg_codec = str(resp.get("codec") or "lz4")
            self.server_shard_id = str(resp.get("shard", "") or "")
        else:
            # pre-negotiation server: it answered hello with an error frame
            # and will compress every response — mirror the legacy contract
            self._neg_compress = True
            self._neg_codec = "lz4"

    def _call_once(self, req: dict) -> dict:
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                send_msg(self._sock, req, compress=self._neg_compress,
                         codec=self._neg_codec)
                resp = recv_msg(self._sock)
            except (ConnectionError, OSError, ValueError):
                # stream no longer trustworthy: drop it so the retry dials
                self.close()
                raise
        if resp.get("code") != 0:
            raise error_from_wire(resp)
        return resp

    def _call(self, req: dict) -> dict:
        # NOTE rate_limited subclasses RetryableError, so retry_call backs
        # off and re-offers; repeated full-timeout blocks eventually open the
        # breaker, which is the desired fail-fast once a store is truly wedged
        return retry_call(
            self._call_once, req, op=f"{self._op_prefix}:{req.get('op', '?')}",
            policy=self._policy, breaker=self._breaker,
        )

    def ping(self) -> bool:
        return self._call({"op": "ping"})["pong"]

    def stats(self) -> dict:
        return self._call({"op": "stats"})["stats"]

    def tables(self) -> List[str]:
        return self._call({"op": "tables"})["tables"]

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class InsertClient(_ReplayClientBase):
    """Actor-side writer: ``insert`` returns only once the store acked (item
    resident + spilled to disk when the store runs with a spill ring)."""

    def __init__(self, host: str, port: int, **kwargs):
        kwargs.setdefault("op_prefix", "replay_insert")
        super().__init__(host, port, **kwargs)

    def insert(self, table: str, item: Any, priority: float = 1.0,
               timeout_s: Optional[float] = None) -> int:
        # one idem key per LOGICAL insert, minted here so every retry of
        # this call carries the same token: a commit whose ack the wire ate
        # answers the cached seq on re-offer instead of double-applying
        req = {"op": "insert", "table": table, "item": item,
               "priority": priority, "idem": uuid.uuid4().hex}
        if timeout_s is not None:
            req["timeout_s"] = timeout_s
        return self._call(req)["seq"]


class SampleClient(_ReplayClientBase):
    """Learner-side reader: blocking batched samples plus the PER
    priority-refresh hook."""

    def __init__(self, host: str, port: int, **kwargs):
        kwargs.setdefault("op_prefix", "replay_sample")
        super().__init__(host, port, **kwargs)

    def sample(self, table: str, batch_size: int = 1,
               timeout_s: Optional[float] = None) -> Tuple[List[Any], List[dict]]:
        req = {"op": "sample", "table": table, "batch_size": batch_size}
        if timeout_s is not None:
            req["timeout_s"] = timeout_s
        resp = self._call(req)
        # spill re-serves arrive as pre-encoded Opaque payloads (the server
        # skipped recompression); unwrap here so consumers never see them
        return [maybe_decode(i) for i in resp["items"]], resp["info"]

    def update_priorities(self, table: str, updates: Dict[int, float]) -> int:
        return self._call(
            {"op": "update_priorities", "table": table, "updates": updates}
        )["applied"]
