"""Replay-store clients: acked inserts and blocking samples over framed TCP.

Both clients follow the ``serve/tcp_frontend`` ServeClient shape — one
connection, one request in flight, transport faults reconnect-and-retry
under a ``resilience.RetryPolicy`` behind a per-client ``CircuitBreaker``
(no connect storms against a dead store). Typed wire errors rehydrate into
the ``replay.errors`` taxonomy; ``rate_limited`` is *retryable* (the store
is pacing, not failing), so a default-policy client transparently rides
through limiter blocks AND store restarts within its deadline budget.

Exactly-once inserts: every logical ``insert`` mints one idempotency key
that rides EVERY retry of that insert. A retry after the ambiguous failure
(server committed, ack lost when the connection died) is answered from the
store's idem cache with the original seq instead of re-applying — no
duplicate item, no duplicate spill blob. (A retry that crosses a store
*restart* still lands as the documented at-least-once duplicate: the cache
is process-lifetime, and a duplicate trajectory is benign for RL training.)

Wire compression is negotiated once per connection: ``_connect`` sends a
``hello`` declaring this client's preference — on/off AND a codec
preference list (``lz4`` default, ``zstd`` when the host has a binding) —
the server answers the ANDed setting plus the chosen codec name, and both
directions honour them. A pre-negotiation server (or one that answers
hello with an error) degrades to the legacy always-compressed lz4
contract, so mixed-version fleets interoperate.

Transport rides the same ``hello`` (``transport="auto"``, the default):
when client and server share a host the server mints a shared-memory ring
pair (``comm.shm_ring``) and every data frame moves over the rings —
pickle straight into mapped memory, no socket, no codec — while the TCP
socket stays connected as the control channel and fallback leg. Any shm
fault (peer death mid-frame, oversized frame, CRC corruption) is typed:
the client counts the fallback, drops the rings, and the SAME logical
call completes over TCP — with inserts carrying their idempotency key
across the legs, the fallback is exactly-once from the caller's seat.
``transport="tcp"`` keeps the hello byte-identical to the pre-shm wire.
"""
from __future__ import annotations

import socket
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..comm import shm_ring
from ..comm.serializer import maybe_decode, recv_msg, send_msg, supported_codecs
from ..obs import (
    finish_trace,
    set_active_trace,
    start_trace,
    tracing_enabled,
    wire_ctx,
)
from ..resilience import CircuitBreaker, RetryPolicy, retry_call
from .errors import error_from_wire

#: store RPCs ride through limiter blocks and a several-second store
#: restart by default; the deadline bounds how long an actor/learner can
#: be parked before the fault surfaces to its supervisor
DEFAULT_REPLAY_POLICY = RetryPolicy(
    max_attempts=6, backoff_base_s=0.2, backoff_max_s=3.0, deadline_s=120.0,
)


class _ReplayClientBase:
    def __init__(self, host: str, port: int, timeout_s: float = 60.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 op_prefix: str = "replay", compress: bool = True,
                 codec: str = "lz4", transport: str = "auto"):
        self._addr = (host, port)
        self._timeout_s = timeout_s
        self._policy = retry_policy or DEFAULT_REPLAY_POLICY
        self._breaker = breaker or CircuitBreaker(
            op=f"{op_prefix}:{host}:{port}", failure_threshold=8, reset_after_s=5.0)
        self._op_prefix = op_prefix
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        #: what this side ASKS for; the per-connection negotiated settings
        #: (server's enablement/choice ANDed in) land in _neg_* on connect
        self._want_compress = bool(compress)
        self._neg_compress = bool(compress)
        # preference list: the asked-for codec first, lz4 as the universal
        # fallback; only codecs THIS host can decode are ever offered
        prefs = [c for c in dict.fromkeys((codec, "lz4"))
                 if c in supported_codecs()]
        self._want_codecs = prefs or ["lz4"]
        self._neg_codec = "lz4"
        #: transport preference; the per-connection outcome lands in _shm
        #: (a live ring pair) — None means this connection runs framed TCP
        shm_ring.offer_transports(transport)  # validate the name early
        self._transport = transport
        self._shm: Optional[shm_ring.ShmPeer] = None
        self.server_shard_id: str = ""

    @property
    def transport_active(self) -> str:
        """The leg this connection's data frames currently ride."""
        return "shm" if self._shm is not None else "tcp"

    def _connect(self) -> None:
        self.close()
        self._sock = socket.create_connection(self._addr, timeout=self._timeout_s)
        self._sock.settimeout(self._timeout_s)
        hello = {"op": "hello", "compress": self._want_compress,
                 "codecs": list(self._want_codecs)}
        offers = shm_ring.offer_transports(self._transport)
        if "shm" in offers:
            # only a hello that can actually lead to rings carries the
            # transport keys — a --transport tcp client stays byte-identical
            # to the pre-shm wire
            hello["transports"] = offers
            hello["host"] = shm_ring.host_identity()
        try:
            send_msg(self._sock, hello, compress=False)
            resp = recv_msg(self._sock)
        except (ConnectionError, OSError, ValueError):
            self.close()
            raise
        if isinstance(resp, dict) and resp.get("code") == "bad_hello":
            # the server recognized NOTHING we offered: a config/version
            # fault that degrading would only hide — surface it typed
            self.close()
            raise error_from_wire(resp)
        if isinstance(resp, dict) and resp.get("code") == 0 and "compress" in resp:
            self._neg_compress = bool(resp["compress"])
            self._neg_codec = str(resp.get("codec") or "lz4")
            self.server_shard_id = str(resp.get("shard", "") or "")
            if "shm" in offers:
                self._shm = shm_ring.maybe_attach(resp, op=self._op_prefix)
        else:
            # pre-negotiation server: it answered hello with an error frame
            # and will compress every response — mirror the legacy contract
            self._neg_compress = True
            self._neg_codec = "lz4"

    def _drop_shm(self) -> None:
        shm, self._shm = self._shm, None
        if shm is not None:
            shm.close()

    def _call_once(self, req: dict) -> dict:
        with self._lock:
            if self._sock is None:
                self._connect()
            resp = None
            if self._shm is not None:
                try:
                    resp = self._shm.request(req, timeout_s=self._timeout_s)
                except shm_ring.ShmTimeout:
                    # peer alive but wedged past the budget: same contract
                    # as a socket timeout — drop everything, let the retry
                    # dial (and renegotiate) fresh
                    self._drop_shm()
                    self.close()
                    raise
                except shm_ring.ShmError as e:
                    # ring fault (peer death mid-frame, oversized frame,
                    # corruption): typed + counted, then THIS call falls
                    # back to the TCP leg below — zero loss for the caller
                    shm_ring.note_fallback(e.reason)
                    self._drop_shm()
            if resp is None:
                try:
                    send_msg(self._sock, req, compress=self._neg_compress,
                             codec=self._neg_codec)
                    resp = recv_msg(self._sock)
                except (ConnectionError, OSError, ValueError):
                    # stream no longer trustworthy: drop it so the retry dials
                    self.close()
                    raise
        if resp.get("code") != 0:
            raise error_from_wire(resp)
        return resp

    def _call(self, req: dict) -> dict:
        # NOTE rate_limited subclasses RetryableError, so retry_call backs
        # off and re-offers; repeated full-timeout blocks eventually open the
        # breaker, which is the desired fail-fast once a store is truly wedged
        return retry_call(
            self._call_once, req, op=f"{self._op_prefix}:{req.get('op', '?')}",
            policy=self._policy, breaker=self._breaker,
        )

    def _traced_call(self, req: dict, name: str) -> dict:
        """Data-plane RPC under a client span: the compact wire trace field
        rides the frame (TCP or shm leg alike — it's inside the pickled
        request), the store's server span joins it, and shm ring-full waits
        annotate this span via the active-trace threadlocal. The span
        resolves ``shed`` when the limiter paced us out (retryable wire
        answers), ``error`` on real faults."""
        ctx = None
        if tracing_enabled():
            ctx = start_trace(name, table=str(req.get("table", "")))
            req = dict(req)
            req["trace"] = wire_ctx(ctx)
        on_shm = self._shm is not None  # only the shm leg reads the active trace
        prev = set_active_trace(ctx) if on_shm else None
        try:
            resp = self._call(req)
        except BaseException as e:
            shed = getattr(e, "code", "") in ("rate_limited", "draining")
            finish_trace(ctx, "client_done",
                         outcome="shed" if shed else "error")
            raise
        finally:
            if on_shm:
                set_active_trace(prev)
        finish_trace(ctx, "client_done")
        return resp

    def ping(self) -> bool:
        return self._call({"op": "ping"})["pong"]

    def stats(self) -> dict:
        return self._call({"op": "stats"})["stats"]

    def tables(self) -> List[str]:
        return self._call({"op": "tables"})["tables"]

    def close(self) -> None:
        self._drop_shm()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class InsertClient(_ReplayClientBase):
    """Actor-side writer: ``insert`` returns only once the store acked (item
    resident + spilled to disk when the store runs with a spill ring)."""

    def __init__(self, host: str, port: int, **kwargs):
        kwargs.setdefault("op_prefix", "replay_insert")
        super().__init__(host, port, **kwargs)

    def insert(self, table: str, item: Any, priority: float = 1.0,
               timeout_s: Optional[float] = None) -> int:
        # one idem key per LOGICAL insert, minted here so every retry of
        # this call carries the same token: a commit whose ack the wire ate
        # answers the cached seq on re-offer instead of double-applying
        req = {"op": "insert", "table": table, "item": item,
               "priority": priority, "idem": uuid.uuid4().hex}
        if timeout_s is not None:
            req["timeout_s"] = timeout_s
        return self._traced_call(req, "replay_insert")["seq"]


class SampleClient(_ReplayClientBase):
    """Learner-side reader: blocking batched samples plus the PER
    priority-refresh hook."""

    def __init__(self, host: str, port: int, **kwargs):
        kwargs.setdefault("op_prefix", "replay_sample")
        super().__init__(host, port, **kwargs)

    def sample(self, table: str, batch_size: int = 1,
               timeout_s: Optional[float] = None) -> Tuple[List[Any], List[dict]]:
        req = {"op": "sample", "table": table, "batch_size": batch_size}
        if timeout_s is not None:
            req["timeout_s"] = timeout_s
        resp = self._traced_call(req, "replay_sample")
        # spill re-serves arrive as pre-encoded Opaque payloads (the server
        # skipped recompression); unwrap here so consumers never see them
        return [maybe_decode(i) for i in resp["items"]], resp["info"]

    def update_priorities(self, table: str, updates: Dict[int, float]) -> int:
        return self._call(
            {"op": "update_priorities", "table": table, "updates": updates}
        )["applied"]
