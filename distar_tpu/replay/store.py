"""Reverb-style trajectory tables: prioritized sampling + rate control.

The DI-star data plane is point-to-point push with consume-once semantics
(actor -> shuttle -> learner pull cache); the backpressure story is "the
deque is full". This module is the decoupling layer the Podracer/RLAX TPU
scaling recipes call for: a per-player ``ReplayTable`` holding trajectories
behind an explicit ``RateLimiter``, so actors and learners run at
independently-supervised speeds while the *ratio* between them — how often
each trajectory is trained on, and therefore how stale the average sample
is — is a configured invariant instead of an accident of queue sizes.

Samplers:
  * ``prioritized`` — sum-tree proportional sampling (with replacement) over
    ``priority ** priority_exponent``; per-item sample counts tracked.
  * ``uniform``     — degenerate prioritized case (every priority forced 1).
  * ``fifo``        — consume-once oldest-first pop, the legacy shuttle-path
    semantics expressed as a table (without replacement; items leave on
    sample).

Eviction: FIFO when ``max_size`` is hit, plus ``max_staleness_s`` sweeps
(items older than the bound will never be worth training on). Every item
departure — first sample for consume-once release, or eviction — fires the
``on_release`` hook the store uses to drop the item from the disk spill.

Rate control (``RateLimiter``): with ``spi = samples_per_insert``,
``min_size`` inserts are free, then the limiter keeps

    samples  ≈  spi * (inserts - min_size)      (within ± error_buffer)

by blocking samplers when actors fall behind and blocking inserters when
the learner does. ``error_buffer`` is in sample units and is clamped to at
least ``max(1, spi)`` so single-step progress is always possible. The
buffer also bounds the largest admissible sample batch
(``RateLimiter.max_sample_batch``): a batch the buffer can never admit
would park sampler AND inserter forever, so ``sample`` rejects it up front
with the non-retryable ``InvalidBatchError`` — size the buffer to at least
``max(1, spi) * batch_size`` (Reverb sizes its min/max_diff to the batch
the same way; the launcher defaults ``--replay-error-buffer`` accordingly).
"""
from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..obs import annotate_active, get_registry
from .errors import (
    InvalidBatchError,
    RateLimitTimeout,
    StoreDrainingError,
    UnknownTableError,
)

SAMPLERS = ("prioritized", "uniform", "fifo")


class SumTree:
    """Flat-array binary sum tree over ``capacity`` slots: O(log n) priority
    updates and prefix-sum descent for proportional sampling."""

    def __init__(self, capacity: int):
        assert capacity >= 1
        n = 1
        while n < capacity:
            n *= 2
        self._n = n
        self._tree = [0.0] * (2 * n)

    def set(self, slot: int, value: float) -> None:
        assert 0 <= slot < self._n and value >= 0.0
        i = slot + self._n
        self._tree[i] = value
        i //= 2
        while i >= 1:
            self._tree[i] = self._tree[2 * i] + self._tree[2 * i + 1]
            i //= 2

    def get(self, slot: int) -> float:
        return self._tree[slot + self._n]

    @property
    def total(self) -> float:
        return self._tree[1]

    def find(self, mass: float) -> int:
        """Slot whose cumulative-priority interval contains ``mass``
        (callers draw ``mass`` uniformly from [0, total))."""
        i = 1
        while i < self._n:
            left = 2 * i
            if mass < self._tree[left] or self._tree[left + 1] <= 0.0:
                i = left
            else:
                mass -= self._tree[left]
                i = left + 1
        return i - self._n


class RateLimiter:
    """Samples-per-insert gate shared by one table's inserters and samplers.

    Thread-safe; both sides block on one condition variable, and every
    commit wakes all waiters (an insert can unblock samplers and vice
    versa). Cumulative block time per side is the
    ``distar_replay_limiter_block_seconds_total`` counter — the single most
    diagnostic replay metric (it says *which* side of the fleet is slow).
    """

    def __init__(self, samples_per_insert: Optional[float] = 1.0,
                 min_size_to_sample: int = 1,
                 error_buffer: Optional[float] = None,
                 table: str = "", shard: str = ""):
        """``samples_per_insert=None`` disables ratio enforcement entirely
        (pure buffer semantics — the legacy pull-cache contract); only
        ``min_size_to_sample`` still gates sampling. ``shard`` labels the
        block-time series so colocated shard-fleet members (chaos drills,
        --replay-shards smoke runs) don't collapse into one series."""
        assert samples_per_insert is None or samples_per_insert > 0.0
        assert min_size_to_sample >= 1
        self.spi = None if samples_per_insert is None else float(samples_per_insert)
        self.min_size = int(min_size_to_sample)
        floor = max(1.0, self.spi or 1.0)
        self.error_buffer = max(floor, float(error_buffer if error_buffer is not None else floor))
        self._cv = threading.Condition()
        self._inserts = 0
        self._samples = 0
        self._block_s = {"insert": 0.0, "sample": 0.0}
        reg = get_registry()
        extra = {"shard": shard} if shard else {}
        self._c_block = {
            side: reg.counter(
                "distar_replay_limiter_block_seconds_total",
                "cumulative wall-clock the rate limiter blocked each side",
                table=table, side=side, **extra,
            )
            for side in ("insert", "sample")
        }

    # ----------------------------------------------------------- predicates
    def can_insert(self, n: int = 1) -> bool:
        if self.spi is None or self._inserts + n <= self.min_size:
            return True
        adj = self._inserts + n - self.min_size
        return self.spi * adj <= self._samples + self.error_buffer

    def can_sample(self, n: int = 1) -> bool:
        if self._inserts < self.min_size:
            return False
        if self.spi is None:
            return True
        adj = self._inserts - self.min_size
        return self._samples + n <= self.spi * adj + self.error_buffer

    def max_sample_batch(self) -> float:
        """Largest batch a sampler can EVER be admitted with: inserters can
        run at most ``floor(eb / spi)`` adjusted inserts ahead of a drained
        sampler before the ratio blocks them, at which point
        ``can_sample(n)`` needs ``n <= spi * floor(eb / spi) + eb``. A batch
        above this bound deadlocks both sides — the sampler waits for
        inserts the limiter will never allow, the inserter waits for samples
        that can never be drawn — so callers reject it with a config error
        instead of timing out forever."""
        if self.spi is None:
            return float("inf")
        return self.spi * math.floor(self.error_buffer / self.spi + 1e-9) + self.error_buffer

    # -------------------------------------------------------------- waiting
    def await_cond(self, predicate: Callable[[], bool], timeout_s: Optional[float],
                   side: str) -> None:
        """Block until ``predicate()`` holds (evaluated under the limiter's
        condition lock, re-checked on every commit). Raises
        ``RateLimitTimeout`` — retryable — when ``timeout_s`` elapses."""
        t0 = time.monotonic()
        with self._cv:
            ok = self._cv.wait_for(predicate, timeout=timeout_s)
        waited = time.monotonic() - t0
        if waited > 0.0005:
            self._block_s[side] += waited
            self._c_block[side].inc(waited)
            # attribute the flow-control wait to the request being served
            # (the replay server installs its span as this handler thread's
            # active trace) — the waterfall's blocked_s segment
            annotate_active("blocked_s", waited)
        if not ok:
            raise RateLimitTimeout(side, timeout_s or 0.0, self.state())

    def commit_insert(self, n: int = 1) -> None:
        with self._cv:
            self._inserts += n
            self._cv.notify_all()

    def commit_sample(self, n: int = 1) -> None:
        with self._cv:
            self._samples += n
            self._cv.notify_all()

    def notify(self) -> None:
        """Wake waiters after a table mutation the commit paths didn't see
        (eviction freeing size, shutdown)."""
        with self._cv:
            self._cv.notify_all()

    def release_pacing(self) -> None:
        """Drain mode: stop enforcing the samples-per-insert ratio (and drop
        the min-size gate to 1) so the resident tail can drain out to
        samplers even though inserts have stopped — a paced drain would
        otherwise park the last learners forever against a counter that will
        never advance."""
        with self._cv:
            self.spi = None
            self.min_size = 1
            self._cv.notify_all()

    def state(self) -> dict:
        return {
            "inserts": self._inserts,
            "samples": self._samples,
            "samples_per_insert": self.spi,
            "min_size_to_sample": self.min_size,
            "error_buffer": self.error_buffer,
            "can_insert": self.can_insert(),
            "can_sample": self.can_sample(),
            "block_insert_s": round(self._block_s["insert"], 3),
            "block_sample_s": round(self._block_s["sample"], 3),
        }


@dataclass
class _Item:
    seq: int
    data: Any
    priority: float
    ts: float
    sample_count: int = 0
    spill_key: Optional[str] = None


@dataclass
class SampledItem:
    """One sampled trajectory plus the metadata the learner's staleness /
    reuse telemetry needs (travels as the ``info`` half of a sample reply)."""

    data: Any
    seq: int
    priority: float
    sample_count: int
    staleness_s: float

    def info(self) -> dict:
        return {
            "seq": self.seq,
            "priority": self.priority,
            "sample_count": self.sample_count,
            "staleness_s": round(self.staleness_s, 4),
        }


@dataclass
class TableConfig:
    """Declarative per-table settings (the server builds tables from this;
    one config per player token)."""

    max_size: int = 1024
    sampler: str = "prioritized"
    priority_exponent: float = 1.0
    #: None disables the samples-per-insert ratio (pure buffer semantics)
    samples_per_insert: Optional[float] = 1.0
    min_size_to_sample: int = 1
    error_buffer: Optional[float] = None
    max_staleness_s: Optional[float] = None
    seed: int = 0

    def __post_init__(self):
        assert self.sampler in SAMPLERS, f"sampler {self.sampler!r} not in {SAMPLERS}"
        assert self.max_size >= 1
        if self.sampler == "fifo" and (self.samples_per_insert or 0) > 1.0:
            # consume-once removes items on sample: each insert can yield at
            # most one sample, so a reuse ratio > 1 deadlocks by construction
            # (sampler starved of items, inserter blocked on the ratio)
            raise ValueError(
                "fifo (consume-once) cannot satisfy samples_per_insert > 1; "
                "use the uniform or prioritized sampler for trajectory reuse"
            )


class ReplayTable:
    def __init__(self, name: str, config: Optional[TableConfig] = None,
                 on_release: Optional[Callable[[_Item, str], None]] = None,
                 shard: str = ""):
        import random

        self.name = name
        self.shard = shard
        self.config = config or TableConfig()
        cfg = self.config
        self._rng = random.Random(cfg.seed)
        self._lock = threading.RLock()
        self._items: Dict[int, _Item] = {}  # insertion-ordered (dict semantics)
        self._tree = SumTree(cfg.max_size)
        self._next_seq = 0
        self._on_release = on_release
        self.limiter = RateLimiter(
            samples_per_insert=cfg.samples_per_insert,
            min_size_to_sample=cfg.min_size_to_sample,
            error_buffer=cfg.error_buffer,
            table=name, shard=shard,
        )
        reg = get_registry()
        # shard label only when set: a single-store deployment keeps the
        # exact series names every dashboard/rule already matches on, while
        # a fleet gets one series per (table, shard) — the per-shard axis
        # the default rulebook evaluates over
        extra = {"shard": shard} if shard else {}
        self._c_inserts = reg.counter(
            "distar_replay_inserts_total", "trajectories inserted",
            table=name, **extra)
        self._c_samples = reg.counter(
            "distar_replay_samples_total", "trajectory samples served",
            table=name, **extra)
        self._c_evict = {
            reason: reg.counter(
                "distar_replay_evictions_total", "items evicted by policy",
                table=name, reason=reason, **extra,
            )
            for reason in ("size", "staleness")
        }
        self._g_size = reg.gauge(
            "distar_replay_table_size", "items resident in the table",
            table=name, **extra)
        self._g_occ = reg.gauge(
            "distar_replay_table_occupancy", "resident share of max_size (0..1)",
            table=name, **extra)
        self._h_staleness = reg.histogram(
            "distar_replay_sampled_staleness_seconds",
            "age of items at sampling time", table=name, **extra)
        self._h_reuse = reg.histogram(
            "distar_replay_sampled_reuse",
            "per-item sample count at sampling time", table=name, **extra)

    # ------------------------------------------------------------- internals
    def _slot(self, seq: int) -> int:
        return seq % self.config.max_size

    def _publish_size(self) -> None:
        n = len(self._items)
        self._g_size.set(n)
        self._g_occ.set(n / self.config.max_size)

    def _release(self, item: _Item, reason: str) -> None:
        if self._on_release is not None:
            try:
                self._on_release(item, reason)
            except Exception:  # a broken spill hook must not kill the table
                pass

    def _evict_oldest(self, reason: str) -> None:
        """Caller holds the lock."""
        seq, item = next(iter(self._items.items()))
        del self._items[seq]
        self._tree.set(self._slot(seq), 0.0)
        self._c_evict[reason].inc()
        self._release(item, reason)

    def _sweep_staleness(self, now: float) -> None:
        """Caller holds the lock; items are insertion-ordered so the sweep
        stops at the first young-enough item."""
        bound = self.config.max_staleness_s
        if bound is None:
            return
        while self._items:
            item = next(iter(self._items.values()))
            if now - item.ts <= bound:
                break
            self._evict_oldest("staleness")

    def _tree_value(self, priority: float) -> float:
        if self.config.sampler == "uniform":
            return 1.0
        return max(priority, 1e-9) ** self.config.priority_exponent

    # ------------------------------------------------------------------- api
    def insert(self, data: Any, priority: float = 1.0,
               timeout_s: Optional[float] = 60.0, spill_key: Optional[str] = None,
               restore: bool = False) -> int:
        """Insert one trajectory; blocks under the rate limiter, returns the
        item's table-unique ``seq``. ``restore=True`` is the spill-recovery
        path: it skips the limiter *wait* (recovery must never deadlock on a
        learner that isn't back yet) but still commits the insert count so
        post-restart pacing stays correct."""
        if not restore:
            self.limiter.await_cond(self.limiter.can_insert, timeout_s, "insert")
        with self._lock:
            self._sweep_staleness(time.time())
            if len(self._items) >= self.config.max_size:
                self._evict_oldest("size")
            seq = self._next_seq
            self._next_seq += 1
            item = _Item(seq=seq, data=data, priority=float(priority),
                         ts=time.time(), spill_key=spill_key)
            self._items[seq] = item
            self._tree.set(self._slot(seq), self._tree_value(item.priority))
            self._publish_size()
        self._c_inserts.inc()
        self.limiter.commit_insert()
        return seq

    def _available(self, n: int) -> bool:
        if self.config.sampler == "fifo":
            return len(self._items) >= n  # without replacement
        return len(self._items) >= 1  # with replacement: one item suffices

    def sample(self, batch_size: int = 1,
               timeout_s: Optional[float] = 60.0) -> List[SampledItem]:
        """Draw ``batch_size`` items; blocks under the rate limiter and on
        availability. Prioritized/uniform draw with replacement; fifo pops
        oldest-first (consume-once)."""
        assert batch_size >= 1
        limit = self.limiter.max_sample_batch()
        if batch_size > limit:
            raise InvalidBatchError(
                f"batch_size={batch_size} can never be admitted by table "
                f"{self.name!r}: samples_per_insert={self.limiter.spi:g} with "
                f"error_buffer={self.limiter.error_buffer:g} caps admissible "
                f"batches at {limit:g}; raise error_buffer to at least "
                f"max(1, samples_per_insert) * batch_size or shrink the batch"
            )
        self.limiter.await_cond(
            lambda: self.limiter.can_sample(batch_size) and self._available(batch_size),
            timeout_s, "sample",
        )
        now = time.time()
        out: List[SampledItem] = []
        with self._lock:
            self._sweep_staleness(now)
            if not self._available(batch_size):
                # a staleness sweep emptied the window between wait and lock:
                # surface as the same retryable pacing error
                raise RateLimitTimeout("sample", timeout_s or 0.0, self.limiter.state())
            if self.config.sampler == "fifo":
                for _ in range(batch_size):
                    seq, item = next(iter(self._items.items()))
                    del self._items[seq]
                    self._tree.set(self._slot(seq), 0.0)
                    item.sample_count += 1
                    out.append(SampledItem(item.data, seq, item.priority,
                                           item.sample_count, now - item.ts))
                    self._release(item, "consumed")
            else:
                seqs = list(self._items)
                for _ in range(batch_size):
                    total = self._tree.total
                    if total > 0.0:
                        slot = self._tree.find(self._rng.random() * total)
                        # map the slot back to the live seq occupying it
                        item = self._items.get(self._seq_for_slot(slot))
                    else:
                        item = None
                    if item is None:  # numeric edge: fall back to uniform
                        item = self._items[self._rng.choice(seqs)]
                    first_sample = item.sample_count == 0
                    item.sample_count += 1
                    out.append(SampledItem(item.data, item.seq, item.priority,
                                           item.sample_count, now - item.ts))
                    if first_sample:
                        self._release(item, "sampled")
            self._publish_size()
        for s in out:
            self._h_staleness.observe(s.staleness_s)
            self._h_reuse.observe(s.sample_count)
        self._c_samples.inc(len(out))
        self.limiter.commit_sample(len(out))
        return out

    def _seq_for_slot(self, slot: int) -> int:
        """Live seq occupying ``slot`` (ring layout: at most one candidate)."""
        base = self._next_seq - 1
        # candidates: the most recent seq congruent to slot mod max_size
        cand = base - ((base - slot) % self.config.max_size)
        return cand

    def update_priorities(self, updates: Dict[int, float]) -> int:
        """Re-prioritize live items (PER's learner-side TD-error refresh);
        unknown seqs are ignored. Returns how many were applied."""
        applied = 0
        with self._lock:
            for seq, priority in updates.items():
                item = self._items.get(int(seq))
                if item is None:
                    continue
                item.priority = float(priority)
                self._tree.set(self._slot(item.seq), self._tree_value(item.priority))
                applied += 1
        return applied

    def size(self) -> int:
        with self._lock:
            return len(self._items)

    def stats(self) -> dict:
        with self._lock:
            n = len(self._items)
            now = time.time()
            oldest = min((now - i.ts for i in self._items.values()), default=0.0)
            newest = max((now - i.ts for i in self._items.values()), default=0.0)
        return {
            "name": self.name,
            **({"shard": self.shard} if self.shard else {}),
            "size": n,
            "max_size": self.config.max_size,
            "occupancy": round(n / self.config.max_size, 4),
            "sampler": self.config.sampler,
            "oldest_item_s": round(newest, 3),
            "newest_item_s": round(oldest, 3),
            "limiter": self.limiter.state(),
        }


class ReplayStore:
    """Named-table container + the spill hookup (durability for acked
    inserts). ``table_factory`` auto-creates tables on first reference —
    per-player tables appear as the league mints players, no pre-declaration
    step."""

    #: bound on remembered insert idempotency keys (an LRU of the newest
    #: ones; far larger than any retry window's in-flight count)
    IDEM_CACHE = 8192

    def __init__(self, table_factory: Optional[Callable[[str], TableConfig]] = None,
                 spill: Optional[object] = None, shard_id: str = "",
                 recover_encoded: bool = False):
        self._factory = table_factory
        self._spill = spill
        self.shard_id = shard_id
        #: recover spilled items as pre-encoded ``Opaque`` payloads — skips
        #: the unpickle on recovery AND the recompress on every wire
        #: re-serve (the serving roles turn this on; default off so direct
        #: in-process consumers keep seeing plain objects)
        self._recover_encoded = recover_encoded
        self._tables: Dict[str, ReplayTable] = {}
        self._idem: Dict[str, int] = {}  # idem key -> acked seq (insertion-ordered)
        self._draining = False
        self._lock = threading.Lock()
        self._c_dedup = get_registry().counter(
            "distar_replay_insert_dedup_total",
            "retried inserts answered from the idempotency cache "
            "(ack lost after commit — without this they double-apply)",
            **({"shard": shard_id} if shard_id else {}),
        )

    # --------------------------------------------------------------- tables
    def create_table(self, name: str, config: Optional[TableConfig] = None) -> ReplayTable:
        with self._lock:
            if name in self._tables:
                return self._tables[name]
            table = ReplayTable(name, config=config, on_release=self._make_release(),
                                shard=self.shard_id)
            self._tables[name] = table
            return table

    def _make_release(self):
        spill = self._spill

        def release(item: _Item, reason: str) -> None:
            if spill is not None and item.spill_key is not None:
                spill.release(item.spill_key)

        return release

    def table(self, name: str) -> ReplayTable:
        with self._lock:
            table = self._tables.get(name)
        if table is not None:
            return table
        if self._factory is None:
            raise UnknownTableError(f"no table {name!r} (and no factory configured)")
        return self.create_table(name, self._factory(name))

    def tables(self) -> List[str]:
        with self._lock:
            return sorted(self._tables)

    # ------------------------------------------------------------------ ops
    def insert(self, table: str, item: Any, priority: float = 1.0,
               timeout_s: Optional[float] = 60.0,
               idem: Optional[str] = None) -> int:
        """Durable acked insert: the item lands on disk — fsync'd, CRC'd —
        and THEN in the table, before the seq is returned. The spill write
        must come first: the moment ``tbl.insert`` makes the item live, a
        concurrent sampler or size eviction can fire ``on_release`` ->
        ``spill.release(key)``, which must find the blob or it leaks as an
        orphan (recovered as a duplicate forever, eating the ring bound).
        A blob whose table insert then fails (rate-limit timeout) is
        released here — the caller was never acked. A crash between append
        and insert leaves an unacked blob that recovery re-inserts; the
        producer's retry makes that the documented at-least-once duplicate,
        never a loss.

        ``idem`` makes a client retry safe against the *ambiguous* failure
        (server committed, ack lost on the wire): a repeated key within the
        bounded cache window answers the original seq without re-applying —
        no duplicate item, no duplicate spill blob, no double limiter
        commit. The cache is process-lifetime only; a retry that crosses a
        store restart still lands as the documented at-least-once
        duplicate."""
        if self._draining:
            # graceful retirement: a retry of an ALREADY-acked insert is
            # still answered from the idem cache (the ack must hold across
            # the drain edge), but genuinely new work is refused typed so
            # routing moves it to a surviving shard
            if idem is not None:
                with self._lock:
                    cached = self._idem.get(idem)
                if cached is not None:
                    self._c_dedup.inc()
                    return cached
            raise StoreDrainingError(
                "store is draining; new inserts are refused (route to a "
                "surviving shard)")
        if idem is not None:
            with self._lock:
                cached = self._idem.get(idem)
            if cached is not None:
                self._c_dedup.inc()
                return cached
        tbl = self.table(table)
        spill_key = None
        if self._spill is not None:
            spill_key = self._spill.reserve_key(table)
            self._spill.append(spill_key, table, item, priority)
        try:
            seq = tbl.insert(item, priority=priority, timeout_s=timeout_s,
                             spill_key=spill_key)
        except Exception:
            if spill_key is not None:
                self._spill.release(spill_key)
            raise
        if idem is not None:
            with self._lock:
                self._idem[idem] = seq
                while len(self._idem) > self.IDEM_CACHE:
                    self._idem.pop(next(iter(self._idem)))
        return seq

    def sample(self, table: str, batch_size: int = 1,
               timeout_s: Optional[float] = 60.0) -> List[SampledItem]:
        return self.table(table).sample(batch_size=batch_size, timeout_s=timeout_s)

    def update_priorities(self, table: str, updates: Dict[int, float]) -> int:
        return self.table(table).update_priorities(updates)

    def recover(self) -> int:
        """Re-insert every spilled (acked-but-unsampled) trajectory; the
        crash-restart half of the durability contract. Returns the count."""
        if self._spill is None:
            return 0
        n = 0
        for rec in self._spill.recover(keep_encoded=self._recover_encoded):
            tbl = self.table(rec["table"])
            tbl.insert(rec["item"], priority=rec["priority"],
                       spill_key=rec["key"], restore=True)
            n += 1
        return n

    # ---------------------------------------------------------------- drain
    def begin_drain(self) -> dict:
        """Enter graceful retirement: refuse NEW inserts with the typed
        ``draining`` wire error (idem-cached retries of already-acked
        inserts still answer their seq) while samples keep being served, so
        the resident tail drains out to the learner fan-in instead of being
        shed wholesale. Idempotent; the serving process exits once
        ``resident_items()`` reaches zero (or its drain timeout lapses) and
        the spill has flushed."""
        if not self._draining:
            self._draining = True
            for name in self.tables():
                self.table(name).limiter.release_pacing()
            get_registry().counter(
                "distar_replay_drains_total",
                "graceful drains started on this store",
                **({"shard": self.shard_id} if self.shard_id else {}),
            ).inc()
        return {"draining": True, "resident": self.resident_items()}

    @property
    def draining(self) -> bool:
        return self._draining

    def resident_items(self) -> int:
        """Items still resident across every table — what a drain waits on."""
        return sum(self.table(name).stats().get("size", 0)
                   for name in self.tables())

    def stats(self) -> dict:
        out = {"tables": {name: self.table(name).stats() for name in self.tables()}}
        out["draining"] = self._draining
        if self.shard_id:
            out["shard"] = self.shard_id
        if self._spill is not None:
            out["spill"] = self._spill.stats()
        return out
