"""Fleet supervision: spawn, watch, respawn and gracefully retire members.

The process-level complement of ``resilience.Supervisor`` (which watches
threads inside one process): a ``SubprocessFleet`` owns N real OS processes
of one role — serve gateways (``serve.fleet.gateway_proc``) or replay
shards (``replay.server``), both jax-free and sub-second to start — and a
``FleetSupervisor`` bundles the fleets behind the scale_up/scale_down
surface the ``Autoscaler`` drives.

Contracts:

* **spawn** — members are real subprocesses printing the standard parseable
  ready line (``SERVE-GATEWAY host tcp http`` / ``REPLAY-SHARD host port
  ...``); with a coordinator configured they self-register, so discovery
  (and thereby every live-membership client) sees the join without help.
* **respawn** — an unexpected member death (exit without a drain) is
  respawned under a PR 4 ``RestartPolicy`` budget (max respawns per sliding
  window); exhausting the budget retires the slot and counts a giveup
  instead of flapping forever.
* **retire** — scale-down is GRACEFUL: ``POST /drain`` on the member's
  admin surface (deregister-then-shed, sessions/items migrate via the
  client-side handoff paths), then wait for the process to exit itself;
  only a drain-timeout escalates to SIGTERM. A member killed mid-drain is
  NOT respawned — it was leaving — but its spill/affinity identity stays
  recoverable (the elastic chaos drill proves the tail).
"""
from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..obs import get_registry
from ..resilience.supervisor import RestartPolicy

#: fleet kinds this module knows how to parse/drain
KINDS = ("gateway", "replay", "actor")


@dataclass
class FleetMember:
    fleet: str
    proc: subprocess.Popen
    addr: str                       # data-plane identity "host:port"
    http_addr: Optional[str] = None  # drain/status surface "host:port"
    started_ts: float = field(default_factory=time.monotonic)
    draining: bool = False
    meta: dict = field(default_factory=dict)

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


def _post(addr: str, path: str, timeout: float = 5.0) -> Optional[dict]:
    try:
        req = urllib.request.Request(
            f"http://{addr}{path}", data=b"{}",
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except Exception:  # noqa: BLE001 - drain is best-effort; timeout escalates
        return None


class SubprocessFleet:
    """One elastic fleet of subprocess members.

    ``build_cmd(index)`` returns the argv for a new member (index is a
    monotonic spawn counter — spill directories and shard ids key off it);
    ``kind`` picks the ready-line/drain conventions. Members print their
    ready line on stdout; stdin is held open (closing it reaps the member,
    the established fleet-process idiom)."""

    DRAIN_PATH = {"gateway": "/serve/drain", "replay": "/drain",
                  "actor": "/actor/drain"}
    READY_TOKEN = {"gateway": "SERVE-GATEWAY", "replay": "REPLAY-SHARD",
                   "actor": "LEAGUE-ACTOR"}

    def __init__(self, name: str, kind: str,
                 build_cmd: Callable[[int], List[str]],
                 restart_policy: Optional[RestartPolicy] = None,
                 drain_timeout_s: float = 30.0,
                 min_members: int = 0):
        assert kind in KINDS, kind
        self.name = name
        self.kind = kind
        self.build_cmd = build_cmd
        self.policy = restart_policy or RestartPolicy(max_restarts=3,
                                                      window_s=120.0)
        self.drain_timeout_s = float(drain_timeout_s)
        self.min_members = int(min_members)
        self._members: List[FleetMember] = []
        self._spawned = 0
        self._respawn_times: deque = deque()
        self.gave_up = False
        self._lock = threading.RLock()
        reg = get_registry()
        self._c_spawns = reg.counter(
            "distar_fleet_supervisor_spawns_total",
            "fleet member processes spawned", fleet=name)
        self._c_respawns = reg.counter(
            "distar_fleet_supervisor_respawns_total",
            "fleet members respawned after an unexpected death", fleet=name)
        self._c_drains = reg.counter(
            "distar_fleet_supervisor_drains_total",
            "graceful member retirements initiated", fleet=name)
        self._g_members = reg.gauge(
            "distar_fleet_supervisor_members",
            "live members per supervised fleet", fleet=name)

    # ------------------------------------------------------------------ spawn
    def spawn(self) -> FleetMember:
        """Start one member and wait for its ready line. Raises on a member
        that dies before serving — the caller (autoscaler) counts that as a
        failed decision, not a silent no-op."""
        with self._lock:
            index = self._spawned
            self._spawned += 1
        cmd = self.build_cmd(index)
        proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        line = proc.stdout.readline().split()
        token = self.READY_TOKEN[self.kind]
        if len(line) < 3 or line[0] != token:
            try:
                proc.kill()
            except OSError:
                pass
            raise RuntimeError(
                f"{self.name} member failed to start (wanted {token!r} "
                f"ready line, got {line!r})")
        host, port = line[1], line[2]
        named = dict(t.split("=", 1) for t in line[3:] if "=" in t)
        if self.kind == "gateway":
            http = f"{host}:{line[3]}" if len(line) > 3 and "=" not in line[3] \
                else None
        else:
            http = f"{host}:{named['admin']}" if named.get("admin") else None
        named["index"] = str(index)
        member = FleetMember(self.name, proc, f"{host}:{port}",
                             http_addr=http, meta=named)
        with self._lock:
            self._members.append(member)
            self._g_members.set(len(self._members))
        self._c_spawns.inc()
        return member

    # ----------------------------------------------------------------- retire
    def drain(self, member: FleetMember,
              block: bool = False) -> threading.Thread:
        """Begin graceful retirement of one member: POST its drain route
        (deregister-then-shed server-side), then wait for the process to
        exit on its own — escalating to SIGTERM only after the drain
        timeout. Runs on a background thread (drains take as long as the
        slowest migrating session); ``block=True`` joins it."""
        member.draining = True
        self._c_drains.inc()

        def run():
            if member.http_addr:
                _post(member.http_addr, self.DRAIN_PATH[self.kind])
            deadline = time.monotonic() + self.drain_timeout_s
            while member.alive and time.monotonic() < deadline:
                time.sleep(0.1)
            if member.alive:
                try:
                    member.proc.terminate()
                    member.proc.wait(timeout=5.0)
                except Exception:  # noqa: BLE001 - last resort below
                    try:
                        member.proc.kill()
                    except OSError:
                        pass
            try:
                member.proc.stdin.close()
            except Exception:  # noqa: BLE001 - already gone
                pass
            with self._lock:
                if member in self._members:
                    self._members.remove(member)
                self._g_members.set(len(self._members))

        t = threading.Thread(target=run, name=f"{self.name}-drain", daemon=True)
        t.start()
        if block:
            t.join(self.drain_timeout_s + 10.0)
        return t

    # ------------------------------------------------------------------ watch
    def check_once(self) -> None:
        """One watchdog pass: respawn unexpectedly dead members under the
        restart budget (a member killed mid-drain was leaving — no
        respawn)."""
        with self._lock:
            dead = [m for m in self._members
                    if not m.alive and not m.draining]
            for m in dead:
                self._members.remove(m)
            self._g_members.set(len(self._members))
        for m in dead:
            if not self._budget_ok():
                self.gave_up = True
                get_registry().counter(
                    "distar_resilience_task_giveups_total",
                    "supervised tasks abandoned (restart budget exhausted)",
                    # analysis: allow(metric-label-cardinality) — fleet names come from the operator's static FleetSupervisor config (serve/replay), never from request data
                    task=f"fleet:{self.name}",
                ).inc()
                continue
            try:
                self.spawn()
                self._c_respawns.inc()
            except RuntimeError:
                continue  # next pass retries within the same budget

    def _budget_ok(self) -> bool:
        now = time.monotonic()
        while self._respawn_times and \
                now - self._respawn_times[0] > self.policy.window_s:
            self._respawn_times.popleft()
        if len(self._respawn_times) >= self.policy.max_restarts:
            return False
        self._respawn_times.append(now)
        return True

    # ---------------------------------------------------------------- surface
    def members(self) -> List[FleetMember]:
        with self._lock:
            return list(self._members)

    def active_members(self) -> List[FleetMember]:
        return [m for m in self.members() if not m.draining and m.alive]

    def addrs(self) -> List[str]:
        return [m.addr for m in self.active_members()]

    def draining_addrs(self) -> List[str]:
        return [m.addr for m in self.members() if m.draining]

    def pids(self) -> List[int]:
        return [m.proc.pid for m in self.members() if m.alive]

    def stop(self) -> None:
        """Reap everything (shutdown path, not graceful drain)."""
        for m in self.members():
            m.draining = True
            try:
                m.proc.stdin.close()
            except Exception:  # noqa: BLE001 - already gone
                pass
        for m in self.members():
            try:
                m.proc.wait(timeout=10.0)
            except Exception:  # noqa: BLE001 - escalate
                try:
                    m.proc.kill()
                except OSError:
                    pass
        with self._lock:
            self._members.clear()
            self._g_members.set(0)


class FleetSupervisor:
    """The pluggable backend the ``Autoscaler`` drives: named fleets with a
    uniform scale/retire surface and one watchdog thread respawning crashed
    members under their budgets."""

    def __init__(self, watch_interval_s: float = 0.5):
        self._fleets: Dict[str, SubprocessFleet] = {}
        self.watch_interval_s = watch_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_fleet(self, fleet: SubprocessFleet) -> "FleetSupervisor":
        self._fleets[fleet.name] = fleet
        return self

    def fleet(self, name: str) -> SubprocessFleet:
        return self._fleets[name]

    def fleets(self) -> List[str]:
        return sorted(self._fleets)

    # ---------------------------------------------------------------- scaling
    def actual(self, name: str) -> int:
        return len(self._fleets[name].active_members())

    def scale_up(self, name: str, n: int = 1) -> List[str]:
        fleet = self._fleets[name]
        return [fleet.spawn().addr for _ in range(max(0, int(n)))]

    def scale_down(self, name: str, n: int = 1) -> List[str]:
        """Gracefully retire ``n`` members, newest first (LIFO keeps the
        stable core's ring segments untouched), never below the fleet's
        ``min_members``. Returns the addresses now draining."""
        fleet = self._fleets[name]
        active = sorted(fleet.active_members(), key=lambda m: -m.started_ts)
        allowed = max(0, len(active) - fleet.min_members)
        victims = active[:min(max(0, int(n)), allowed)]
        for m in victims:
            fleet.drain(m)
        return [m.addr for m in victims]

    # ------------------------------------------------------------------ watch
    def start(self) -> "FleetSupervisor":
        if self._thread is not None:
            return self

        def run():
            while not self._stop.wait(self.watch_interval_s):
                for fleet in list(self._fleets.values()):
                    try:
                        fleet.check_once()
                    except Exception:  # noqa: BLE001 - watchdog never dies
                        continue

        self._thread = threading.Thread(target=run, name="fleet-watch",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, reap: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if reap:
            for fleet in self._fleets.values():
                fleet.stop()

    # ---------------------------------------------------------------- surface
    def status(self) -> dict:
        out = {}
        for name, fleet in self._fleets.items():
            out[name] = {
                "members": [
                    {"addr": m.addr, "http": m.http_addr, "pid": m.proc.pid,
                     "alive": m.alive, "draining": m.draining}
                    for m in fleet.members()
                ],
                "active": len(fleet.active_members()),
                "draining": fleet.draining_addrs(),
                "gave_up": fleet.gave_up,
            }
        return out


def gateway_cmd(slots: int = 32, coordinator: str = "",
                extra: Optional[List[str]] = None) -> Callable[[int], List[str]]:
    """Standard ``gateway_proc`` member command builder."""
    def build(index: int) -> List[str]:
        cmd = [sys.executable, "-m", "distar_tpu.serve.fleet.gateway_proc",
               "--port", "0", "--http-port", "0", "--slots", str(slots)]
        if coordinator:
            cmd += ["--coordinator", coordinator]
        return cmd + list(extra or [])
    return build


def replay_cmd(spill_root: str = "", coordinator: str = "",
               sampler: str = "fifo",
               extra: Optional[List[str]] = None) -> Callable[[int], List[str]]:
    """Standard ``replay.server`` member command builder (admin surface on,
    spill per member index so a restarted member recovers ITS tail)."""
    import os

    def build(index: int) -> List[str]:
        cmd = [sys.executable, "-m", "distar_tpu.replay.server",
               "--port", "0", "--admin-port", "0",
               "--shard-id", f"s{index}", "--sampler", sampler,
               "--min-size", "1"]
        if spill_root:
            cmd += ["--spill-dir", os.path.join(spill_root, f"s{index}")]
        if coordinator:
            cmd += ["--coordinator", coordinator]
        return cmd + list(extra or [])
    return build
