"""Core-pinning harness: make multi-process perf numbers physically honest.

Every fleet bench this repo has committed so far runs its members on ONE
time-shared core, so "2 shards = 2x" claims are physics violations the
artifacts flag in-band (``host_cores: 1`` / ``scaling_valid: false``). This
module is the other half of that honesty contract: when the host actually
HAS cores, pin each fleet process to its own disjoint core set
(``os.sched_setaffinity`` — taskset's syscall) and the driving client to a
reserved core, then write a **provenance block** into the artifact so
``tools/perf_gate.py`` can verify the claim. When the host does not have
enough cores, ``plan`` REFUSES — it never pretends: the artifact keeps
``scaling_valid: false`` with the refusal reason in-band.

The contract, enforced by ``perf_gate``'s scaling gate:

    an artifact may claim ``scaling_valid: true`` ONLY with a ``pinning``
    block whose ``pinned`` is true and whose ``host_cores`` is >= 2 (and a
    matching top-level ``host_cores``); anything else is refused exit 2.

``tools/pin.py`` is the CLI over this module (plan / pin a pid / exec a
command pinned); ``tools/loadgen.py --mode fleet``, the ``BENCH_MODE=
replay`` sweeps and the chaos drills call it directly.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: the provenance block's tool tag (perf_gate matches on it)
TOOL = "tools/pin.py"


def host_cores() -> int:
    """Cores THIS process may schedule onto (the affinity mask, not the
    machine total — a cgroup/taskset-restricted run must not claim cores it
    cannot use)."""
    if hasattr(os, "sched_getaffinity"):
        return len(os.sched_getaffinity(0))
    return os.cpu_count() or 1


def can_pin() -> bool:
    return hasattr(os, "sched_setaffinity")


@dataclass
class PinPlan:
    """A per-process core assignment, or an explicit refusal.

    ``assignments[i]`` is the core list for fleet process ``i``;
    ``client_cores`` is the reserved set for the driving client (load
    generator / learner fan-in). ``pinned`` is False when the host cannot
    honestly separate the processes — callers MUST then keep
    ``scaling_valid: false``."""

    pinned: bool
    host_cores: int
    assignments: List[List[int]] = field(default_factory=list)
    client_cores: List[int] = field(default_factory=list)
    refused_reason: str = ""

    def provenance(self, applied: Optional[Dict[str, List[int]]] = None) -> dict:
        """The artifact block perf_gate's scaling gate verifies. ``applied``
        maps role/pid labels to the core lists actually installed."""
        out = {
            "tool": TOOL,
            "pinned": self.pinned,
            "host_cores": self.host_cores,
        }
        if self.pinned:
            out["assignments"] = applied if applied is not None else {
                f"proc{i}": cores for i, cores in enumerate(self.assignments)
            }
            out["client_cores"] = list(self.client_cores)
        else:
            out["refused_reason"] = self.refused_reason or "insufficient cores"
        return out


def plan(n_procs: int, reserve_client: int = 1,
         cores: Optional[List[int]] = None) -> PinPlan:
    """Plan a one-core-per-process assignment for ``n_procs`` fleet
    processes plus ``reserve_client`` cores for the driving side.

    REFUSES (``pinned=False``) rather than over-subscribing: a host with
    fewer than ``n_procs + reserve_client`` schedulable cores cannot give
    each process its own silicon, so any scaling measured there is
    context-switch arithmetic, not a separation claim. Also refuses on
    platforms without ``sched_setaffinity`` (macOS) — claiming pinning
    without the syscall would be exactly the dishonesty this gate exists to
    stop."""
    n_procs = int(n_procs)
    reserve_client = max(0, int(reserve_client))
    if n_procs < 1:
        raise ValueError("plan needs n_procs >= 1")
    if cores is None:
        cores = sorted(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
            else list(range(os.cpu_count() or 1))
    total = len(cores)
    if not can_pin():
        return PinPlan(pinned=False, host_cores=total,
                       refused_reason="platform has no sched_setaffinity")
    need = n_procs + reserve_client
    if total < need or total < 2:
        return PinPlan(
            pinned=False, host_cores=total,
            refused_reason=(
                f"{total} schedulable core(s) < {n_procs} fleet process(es)"
                f" + {reserve_client} client core(s): pinning would still "
                "time-share"))
    # one core per fleet process, the remainder to the client side — the
    # client is usually the fan-out bottleneck and may be multi-threaded
    assignments = [[cores[i]] for i in range(n_procs)]
    client = cores[n_procs:] if reserve_client else cores[n_procs:] or cores
    return PinPlan(pinned=True, host_cores=total, assignments=assignments,
                   client_cores=list(client) or [cores[-1]])


def pin_pid(pid: int, cores: List[int]) -> bool:
    """Install an affinity mask on a live process (0 = self). Returns False
    instead of raising when the platform or permissions refuse — callers
    must then downgrade their claim, not crash the bench."""
    if not can_pin() or not cores:
        return False
    try:
        os.sched_setaffinity(int(pid), set(int(c) for c in cores))
        return True
    except (OSError, ValueError):
        return False


def apply(plan_: PinPlan, pids: List[int],
          client_pid: int = 0) -> Optional[dict]:
    """Apply a plan to live fleet processes (+ the calling client). Returns
    the provenance block on full success, ``None`` when any pin failed —
    the all-or-nothing contract: a half-pinned fleet is still time-shared
    somewhere, so no provenance may be claimed."""
    if not plan_.pinned:
        return None
    if len(pids) > len(plan_.assignments):
        return None
    applied: Dict[str, List[int]] = {}
    for pid, cores in zip(pids, plan_.assignments):
        if not pin_pid(pid, cores):
            return None
        applied[f"pid{pid}"] = list(cores)
    if plan_.client_cores:
        if not pin_pid(client_pid, plan_.client_cores):
            return None
        applied["client"] = list(plan_.client_cores)
    return plan_.provenance(applied)


def pin_fleet(pids: List[int], reserve_client: int = 1) -> dict:
    """The one-call harness benches and drills use: plan for ``len(pids)``
    processes, apply when the host allows, and ALWAYS return a provenance
    block — ``pinned: true`` with the installed assignments, or ``pinned:
    false`` with the refusal reason, in-band either way."""
    p = plan(len(pids), reserve_client=reserve_client)
    if not p.pinned:
        return p.provenance()
    prov = apply(p, pids)
    if prov is None:
        refused = PinPlan(pinned=False, host_cores=p.host_cores,
                          refused_reason="sched_setaffinity failed on a "
                                         "fleet member (permissions?)")
        return refused.provenance()
    return prov


def scaling_valid(provenance: dict, min_cores: int = 2) -> bool:
    """The ONLY way an artifact should compute its ``scaling_valid`` flag:
    true iff pinning was actually installed on a host with enough cores."""
    return bool(provenance.get("pinned")) and \
        int(provenance.get("host_cores", 0)) >= int(min_cores)
