"""Coordinator-side autoscaler: TSDB signals -> fleet membership decisions.

The control loop ROADMAP item 3 names: the obs stack already ships every
member's registry into the coordinator's ``TimeSeriesStore`` (and the
collector below folds member /status probes into the same store for fleets
that don't ship), so elasticity is a pure read-evaluate-act loop over data
that already exists:

  read      windowed per-member aggregates out of the TSDB
            (``TimeSeriesStore.query`` — the health-rules primitive),
            reduced across the fleet's member sources;
  evaluate  declarative ``ScalePolicy`` rules with HYSTERESIS (a breach
            must hold ``for_count`` consecutive evaluations, exactly the
            ``HealthRule`` debounce) and a per-fleet COOLDOWN (scale
            actions are rate-limited so up/down can't flap);
  act       drive the pluggable ``FleetSupervisor``: scale-up spawns a
            member (it self-registers; live-membership clients see the
            join on their next refresh), scale-down gracefully drains the
            newest member (sessions/items migrate via the typed drain
            handoff paths).

Signals worth scaling on (``default_policies``): gateway session residency
vs fleet slot capacity and shed rate; replay insert-limiter block time
(actors starving against a full fleet) and table residency. Anything in
the TSDB is a valid signal — feeder/actor starvation rules compose the
same way.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..obs import get_registry
from ..obs.timeseries import TimeSeriesStore
from .supervisor import FleetSupervisor

#: canonical TSDB signal names the collector records per member source
SIG_GW_ACTIVE = "distar_serve_sessions_active"
SIG_GW_SLOTS = "distar_serve_session_slots"
SIG_GW_SHED = "distar_serve_shed_total"
SIG_GW_QUEUE = "distar_serve_queue_depth"
SIG_RP_ITEMS = "distar_replay_items"
SIG_RP_CAPACITY = "distar_replay_capacity"
SIG_RP_BLOCK_INSERT = "distar_replay_limiter_block_seconds_total"


@dataclass
class ScalePolicy:
    """One declarative scaling rule over the TSDB.

    ``value = reduce_over_members(agg_over_window(signal)) [/ same(divide_by)]``

    Scale UP when ``value > up_when`` for ``for_count`` consecutive
    evaluations; scale DOWN when ``value < down_when`` holds the same way.
    ``agg`` follows the TSDB query fields (``last``/``mean``/``rate``...);
    ``rate`` turns counters (shed totals, limiter block seconds) into
    per-second slopes. Cooldown lives per FLEET (shared by its policies) so
    one rule's scale-up can't be immediately undone by another's
    scale-down."""

    name: str
    fleet: str
    signal: str
    agg: str = "last"
    reduce: str = "sum"              # sum | mean | max across member sources
    divide_by: Optional[str] = None  # ratio signals (residency / capacity)
    up_when: Optional[float] = None
    down_when: Optional[float] = None
    window_s: float = 30.0
    for_count: int = 2
    step: int = 1

    def __post_init__(self):
        assert self.reduce in ("sum", "mean", "max"), self.reduce
        assert self.up_when is not None or self.down_when is not None, \
            f"policy {self.name!r} has neither up_when nor down_when"


@dataclass
class _PolicyState:
    up_streak: int = 0
    down_streak: int = 0
    last_value: Optional[float] = None


def default_policies(gateway_fleet: str = "gateway",
                     replay_fleet: str = "replay",
                     residency_up: float = 0.85, residency_down: float = 0.30,
                     shed_rate_up: float = 0.5,
                     block_rate_up: float = 0.2,
                     window_s: float = 30.0,
                     for_count: int = 2) -> List[ScalePolicy]:
    """The stock elastic rulebook (docs/serving.md, elasticity section)."""
    return [
        ScalePolicy(name="gateway_residency", fleet=gateway_fleet,
                    signal=SIG_GW_ACTIVE, divide_by=SIG_GW_SLOTS,
                    up_when=residency_up, down_when=residency_down,
                    window_s=window_s, for_count=for_count),
        ScalePolicy(name="gateway_shed_rate", fleet=gateway_fleet,
                    signal=SIG_GW_SHED, agg="rate",
                    up_when=shed_rate_up,
                    window_s=window_s, for_count=for_count),
        ScalePolicy(name="replay_insert_block", fleet=replay_fleet,
                    signal=SIG_RP_BLOCK_INSERT, agg="rate",
                    up_when=block_rate_up,
                    window_s=window_s, for_count=for_count),
        ScalePolicy(name="replay_residency", fleet=replay_fleet,
                    signal=SIG_RP_ITEMS, divide_by=SIG_RP_CAPACITY,
                    up_when=residency_up, down_when=residency_down,
                    window_s=window_s, for_count=for_count),
    ]


class MemberProbe:
    """Folds fleet-member /status probes into the TSDB so every fleet feeds
    the same store whether or not its members run a TelemetryShipper.
    Sources are named ``<fleet>:<addr>``; a member that left the fleet has
    its series EVICTED (the satellite contract: membership churn must not
    exhaust the series cap)."""

    def __init__(self, store: TimeSeriesStore, supervisor: FleetSupervisor):
        self.store = store
        self.supervisor = supervisor
        self._known: Dict[str, set] = {}

    def _record_gateway(self, source: str, info: dict, ts: float) -> None:
        sess = info.get("sessions") or {}
        reqs = info.get("requests") or {}
        self.store.record(SIG_GW_ACTIVE, float(sess.get("active", 0)),
                          ts=ts, source=source)
        self.store.record(SIG_GW_SLOTS, float(sess.get("num_slots", 0)),
                          ts=ts, source=source)
        self.store.record(SIG_GW_SHED, float(reqs.get("shed", 0.0)),
                          ts=ts, source=source)
        self.store.record(SIG_GW_QUEUE, float(info.get("queue_depth", 0)),
                          ts=ts, source=source)

    def _record_replay(self, source: str, stats: dict, ts: float) -> None:
        size = cap = 0.0
        block = 0.0
        for t in (stats.get("tables") or {}).values():
            size += float(t.get("size", 0))
            cap += float(t.get("max_size", 0))
            lim = t.get("limiter") or {}
            block += float(lim.get("block_insert_s", 0.0))
        self.store.record(SIG_RP_ITEMS, size, ts=ts, source=source)
        self.store.record(SIG_RP_CAPACITY, cap, ts=ts, source=source)
        self.store.record(SIG_RP_BLOCK_INSERT, block, ts=ts, source=source)

    def collect_once(self) -> int:
        """One probe pass over every active member; returns sources fed.
        Departed members' series are evicted from the store."""
        import json as _json
        import urllib.request

        fed = 0
        now = time.time()
        for name in self.supervisor.fleets():
            fleet = self.supervisor.fleet(name)
            current = set()
            for m in fleet.active_members():
                if not m.http_addr:
                    continue
                source = f"{name}:{m.addr}"
                current.add(source)
                try:
                    if fleet.kind == "gateway":
                        req = urllib.request.Request(
                            f"http://{m.http_addr}/serve/status", data=b"{}",
                            headers={"Content-Type": "application/json"},
                            method="POST")
                        with urllib.request.urlopen(req, timeout=3.0) as resp:
                            body = _json.loads(resp.read())
                        info = body.get("info") if body.get("code") == 0 else None
                        if info:
                            self._record_gateway(source, info, now)
                            fed += 1
                    else:
                        with urllib.request.urlopen(
                                f"http://{m.http_addr}/replay/stats",
                                timeout=3.0) as resp:
                            stats = _json.loads(resp.read())
                        self._record_replay(source, stats, now)
                        fed += 1
                except Exception:  # noqa: BLE001 - a dead member is the watcher's job
                    get_registry().counter(
                        "distar_autoscaler_probe_failures_total",
                        "member status probes that failed", fleet=name,
                    ).inc()
            for gone in self._known.get(name, set()) - current:
                self.store.evict_source(gone)
            self._known[name] = current
        return fed

    def member_sources(self, fleet: str) -> List[str]:
        return sorted(self._known.get(fleet, set()))


class Autoscaler:
    """The evaluate-act loop over ScalePolicies + a FleetSupervisor.

    One decision per fleet per pass: any up-policy winning outranks every
    down-policy (scale-up is the safe direction under load); a down needs
    EVERY down-capable policy below its threshold — a fleet at low
    residency but high shed rate is mis-balanced, not oversized. Cooldown
    is per fleet and applies to BOTH directions."""

    def __init__(self, store: TimeSeriesStore, supervisor: FleetSupervisor,
                 policies: List[ScalePolicy],
                 limits: Optional[Dict[str, tuple]] = None,
                 cooldown_s: float = 30.0, interval_s: float = 2.0,
                 probe: Optional[MemberProbe] = None):
        self.store = store
        self.supervisor = supervisor
        self.policies = list(policies)
        #: per-fleet (min_members, max_members); default (1, 8)
        self.limits = dict(limits or {})
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self.probe = probe
        self._states: Dict[str, _PolicyState] = {
            p.name: _PolicyState() for p in self.policies}
        self._cooldown_until: Dict[str, float] = {}
        self._last_decision: Optional[dict] = None
        self._decisions: List[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = get_registry()
        self._c_decisions = {
            d: reg.counter(
                "distar_autoscaler_decisions_total",
                "scaling actions taken, by direction", direction=d)
            for d in ("up", "down")
        }

    # -------------------------------------------------------------- signals
    def _reduce(self, policy: ScalePolicy, signal: str,
                sources: List[str]) -> Optional[float]:
        values: List[float] = []
        for source in sources:
            for name in self.store.matching_names(signal, source=source):
                q = self.store.query(name, window_s=policy.window_s,
                                     source=source)
                if q is None:
                    continue
                v = q["rate"] if policy.agg == "rate" else q.get(policy.agg)
                if v is not None:
                    values.append(float(v))
        if not values:
            return None
        if policy.reduce == "sum":
            return sum(values)
        if policy.reduce == "mean":
            return sum(values) / len(values)
        return max(values)

    def policy_value(self, policy: ScalePolicy) -> Optional[float]:
        """The fleet-level value this policy compares against its
        thresholds; None with no data (no data is never a breach — the
        health-rules convention)."""
        if self.probe is not None:
            sources = self.probe.member_sources(policy.fleet)
        else:
            sources = [s for s in self.store.sources()
                       if s.startswith(f"{policy.fleet}:")]
        if not sources:
            return None
        value = self._reduce(policy, policy.signal, sources)
        if value is None:
            return None
        if policy.divide_by:
            denom = self._reduce(policy, policy.divide_by, sources)
            if not denom:
                return None
            value = value / denom
        return value

    # ------------------------------------------------------------- evaluate
    def evaluate_once(self, now: Optional[float] = None) -> List[dict]:
        """One collect->evaluate->act pass; returns the decisions taken."""
        now = time.monotonic() if now is None else now
        if self.probe is not None:
            try:
                self.probe.collect_once()
            except Exception:  # noqa: BLE001 - probing must not kill the loop
                pass
        votes_up: Dict[str, List[str]] = {}
        votes_down: Dict[str, List[str]] = {}
        down_blocked: Dict[str, bool] = {}
        with self._lock:
            for policy in self.policies:
                st = self._states[policy.name]
                value = self.policy_value(policy)
                st.last_value = value
                up = value is not None and policy.up_when is not None \
                    and value > policy.up_when
                down = value is not None and policy.down_when is not None \
                    and value < policy.down_when
                st.up_streak = st.up_streak + 1 if up else 0
                st.down_streak = st.down_streak + 1 if down else 0
                if st.up_streak >= policy.for_count:
                    votes_up.setdefault(policy.fleet, []).append(
                        f"{policy.name}={value:.4g}>{policy.up_when:g}")
                if policy.down_when is not None:
                    if st.down_streak >= policy.for_count:
                        votes_down.setdefault(policy.fleet, []).append(
                            f"{policy.name}={value:.4g}<{policy.down_when:g}")
                    else:
                        # a down-capable policy not yet convinced blocks the
                        # whole fleet's scale-down (conservative direction)
                        down_blocked[policy.fleet] = True
        decisions = []
        for fleet in self.supervisor.fleets():
            if now < self._cooldown_until.get(fleet, 0.0):
                continue
            lo, hi = self.limits.get(fleet, (1, 8))
            actual = self.supervisor.actual(fleet)
            step = max((p.step for p in self.policies if p.fleet == fleet),
                       default=1)
            if fleet in votes_up and actual < hi:
                added = self.supervisor.scale_up(fleet, min(step, hi - actual))
                decision = {"ts": time.time(), "fleet": fleet,
                            "direction": "up", "from": actual,
                            "to": actual + len(added), "members": added,
                            "reason": "; ".join(votes_up[fleet])}
            elif fleet in votes_down and not down_blocked.get(fleet) \
                    and actual > lo:
                drained = self.supervisor.scale_down(
                    fleet, min(step, actual - lo))
                if not drained:
                    continue
                decision = {"ts": time.time(), "fleet": fleet,
                            "direction": "down", "from": actual,
                            "to": actual - len(drained), "members": drained,
                            "reason": "; ".join(votes_down[fleet])}
            else:
                continue
            self._cooldown_until[fleet] = now + self.cooldown_s
            self._c_decisions[decision["direction"]].inc()
            get_registry().gauge(
                "distar_autoscaler_target_members",
                "membership the autoscaler last decided for each fleet",
                fleet=fleet,
            ).set(decision["to"])
            with self._lock:
                # reset streaks so one sustained breach = one action per
                # cooldown window, not one per evaluation
                for policy in self.policies:
                    if policy.fleet == fleet:
                        st = self._states[policy.name]
                        st.up_streak = st.down_streak = 0
                self._last_decision = decision
                self._decisions.append(decision)
                del self._decisions[:-64]
            decisions.append(decision)
        for fleet in self.supervisor.fleets():
            get_registry().gauge(
                "distar_autoscaler_members",
                "actual live membership per supervised fleet", fleet=fleet,
            ).set(self.supervisor.actual(fleet))
        return decisions

    # -------------------------------------------------------------- control
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.evaluate_once()
                except Exception:  # noqa: BLE001 - the loop must never die
                    continue

        self._thread = threading.Thread(target=run, name="autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -------------------------------------------------------------- surface
    def status(self) -> dict:
        """The ``GET /autoscaler`` payload (opsctl's digest): per-policy
        state, per-fleet target vs actual + in-progress drains, the last
        decision and its reason."""
        now = time.monotonic()
        with self._lock:
            policies = {
                p.name: {
                    "fleet": p.fleet, "signal": p.signal, "agg": p.agg,
                    "value": self._states[p.name].last_value,
                    "up_when": p.up_when, "down_when": p.down_when,
                    "up_streak": self._states[p.name].up_streak,
                    "down_streak": self._states[p.name].down_streak,
                    "for_count": p.for_count,
                }
                for p in self.policies
            }
            last = dict(self._last_decision) if self._last_decision else None
            history = list(self._decisions[-8:])
        fleets = {}
        for name in self.supervisor.fleets():
            lo, hi = self.limits.get(name, (1, 8))
            cooldown = max(0.0, self._cooldown_until.get(name, 0.0) - now)
            fleets[name] = {
                "actual": self.supervisor.actual(name),
                "min": lo, "max": hi,
                "draining": self.supervisor.fleet(name).draining_addrs(),
                "cooldown_remaining_s": round(cooldown, 1),
                "gave_up": self.supervisor.fleet(name).gave_up,
            }
        return {"ts": time.time(), "fleets": fleets, "policies": policies,
                "last_decision": last, "decisions": history,
                "cooldown_s": self.cooldown_s}


# --------------------------------------------------------- process handle
_scaler_lock = threading.Lock()
_scaler: Optional[Autoscaler] = None


def get_autoscaler() -> Optional[Autoscaler]:
    """The process-wide autoscaler handle (the coordinator's /autoscaler
    route answers from it); None when no entrypoint installed one."""
    with _scaler_lock:
        return _scaler


def set_autoscaler(scaler: Optional[Autoscaler]) -> Optional[Autoscaler]:
    """Install (or clear) the process handle; returns the previous one."""
    global _scaler
    with _scaler_lock:
        prev, _scaler = _scaler, scaler
        return prev
