"""Elastic fleet control plane (ROADMAP item 3).

The coordinator-side loop that lets the serving/replay fleets reshape
themselves under load instead of being hand-sized at launch:

* ``pinning``    — the core-pinning harness that makes multi-process perf
  numbers honest (``scaling_valid: true`` requires its provenance block);
* ``supervisor`` — ``FleetSupervisor``/``SubprocessFleet``: spawn, watch,
  respawn-under-budget and gracefully drain real member processes;
* ``autoscaler`` — ``Autoscaler`` + declarative ``ScalePolicy`` rules over
  the obs TSDB, with hysteresis and cooldown, driving the supervisor.

See docs/serving.md (elasticity) and docs/data_plane.md (shard drain).
"""
from .autoscaler import (
    SIG_GW_ACTIVE,
    SIG_GW_QUEUE,
    SIG_GW_SHED,
    SIG_GW_SLOTS,
    SIG_RP_BLOCK_INSERT,
    SIG_RP_CAPACITY,
    SIG_RP_ITEMS,
    Autoscaler,
    MemberProbe,
    ScalePolicy,
    default_policies,
    get_autoscaler,
    set_autoscaler,
)
from .pinning import PinPlan, can_pin, host_cores, pin_fleet, pin_pid, plan, scaling_valid
from .supervisor import (
    FleetMember,
    FleetSupervisor,
    SubprocessFleet,
    gateway_cmd,
    replay_cmd,
)

__all__ = [
    "SIG_GW_ACTIVE",
    "SIG_GW_QUEUE",
    "SIG_GW_SHED",
    "SIG_GW_SLOTS",
    "SIG_RP_BLOCK_INSERT",
    "SIG_RP_CAPACITY",
    "SIG_RP_ITEMS",
    "Autoscaler",
    "MemberProbe",
    "ScalePolicy",
    "default_policies",
    "get_autoscaler",
    "set_autoscaler",
    "PinPlan",
    "can_pin",
    "host_cores",
    "pin_fleet",
    "pin_pid",
    "plan",
    "scaling_valid",
    "FleetMember",
    "FleetSupervisor",
    "SubprocessFleet",
    "gateway_cmd",
    "replay_cmd",
]
