"""The skill observatory: continuous evaluation arena over checkpoint
history, a durable payoff matrix with Wilson confidence intervals, and
ELO/TrueSkill rating trajectories — the measured substrate the PFSP
league matchmakes from."""
from .evaluator import ArenaEvaluator, anchor_policy
from .store import (
    ANCHORS,
    ArenaStore,
    get_arena_store,
    match_key,
    match_seed,
    set_arena_store,
    wilson_interval,
)

__all__ = [
    "ANCHORS",
    "ArenaEvaluator",
    "ArenaStore",
    "anchor_policy",
    "get_arena_store",
    "match_key",
    "match_seed",
    "set_arena_store",
    "wilson_interval",
]
