"""Arena evaluator: turns checkpoint history into reported matches.

The evaluator is the worker half of the arena: it discovers the model
roster from :class:`~distar_tpu.utils.checkpoint.CheckpointManager` role
keys (player ids are ``role:step``, e.g. ``main:300``), asks the store —
in-process or over the coordinator's ``arena_next`` route — for one
deterministic assignment, replays that assignment as a batched jaxenv
``head_to_head`` (the PRNG scenario set is a pure function of the
assignment's seed), and reports the whole batch under idempotent match
keys. Reports are all-or-nothing: a kill mid-batch loses the batch, the
restarted evaluator re-receives the identical assignment, and the keys
make the replay exact — zero lost, zero double-counted.

Scripted anchors (``attack_nearest``, ``idle``) need no checkpoint and
ground the rating scale even with a single model lineage.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence

import jax

from ..envs.jaxenv import EnvConfig, ScenarioConfig
from ..envs.jaxenv.winrate import (
    attack_nearest_policy,
    head_to_head,
    idle_policy,
    model_policy,
)
from ..obs import get_registry
from ..utils.checkpoint import CheckpointManager, load_params
from .store import ANCHORS, ArenaStore, match_key


def anchor_policy(name: str):
    if name == "attack_nearest":
        return attack_nearest_policy()
    if name == "idle":
        return idle_policy()
    raise KeyError(f"unknown arena anchor: {name}")


def _skill_block(ratings: dict) -> Optional[dict]:
    """The in-band skill ledger ``perf_gate skill`` gates across rounds:
    the newest generation's ELO relative to the mean of the scripted
    anchors (the fixed points of the rating scale)."""
    players = (ratings or {}).get("players") or {}
    gens = {p: i for p, i in players.items() if not i.get("anchor")}
    anchors = {p: i for p, i in players.items() if i.get("anchor")}
    if not gens or not anchors:
        return None

    def step(pid: str) -> int:
        try:
            return int(pid.rsplit(":", 1)[1])
        except (ValueError, IndexError):
            return -1

    newest = max(gens, key=lambda p: (step(p), p))
    anchor_mean = sum(i["elo"] for i in anchors.values()) / len(anchors)
    return {
        "player": newest,
        "anchor_relative": gens[newest]["elo"] - anchor_mean,
        "matches": gens[newest].get("games"),
        "anchor": "mean(" + ",".join(sorted(anchors)) + ")",
    }


class ArenaEvaluator:
    """One evaluation worker over a checkpoint directory + scripted anchors.

    ``store`` (in-process) or ``coordinator_addr`` (remote) selects the
    reporting plane; exactly one must be given. ``roles`` are the
    CheckpointManager role keys whose generations enter the roster
    ("" is the default/teacher lineage, shown as ``main``).
    """

    def __init__(self, ckpt_dir: str, model_cfg: dict,
                 store: Optional[ArenaStore] = None,
                 coordinator_addr: Optional[tuple] = None,
                 roles: Sequence[str] = ("",),
                 anchors: Sequence[str] = ANCHORS,
                 episodes: int = 8,
                 env_cfg: Optional[EnvConfig] = None,
                 scenario_cfg: Optional[ScenarioConfig] = None):
        if (store is None) == (coordinator_addr is None):
            raise ValueError("need exactly one of store / coordinator_addr")
        self.ckpt_dir = ckpt_dir
        self.model_cfg = model_cfg
        self.store = store
        self.coordinator_addr = coordinator_addr
        self.roles = tuple(roles)
        self.anchors = tuple(anchors)
        self.episodes = int(episodes)
        self.env_cfg = env_cfg if env_cfg is not None else EnvConfig()
        self.scenario_cfg = (scenario_cfg if scenario_cfg is not None
                             else ScenarioConfig(
                                 units_per_squad=self.env_cfg.units_per_squad,
                                 max_units=self.env_cfg.units_per_squad))
        self._model = None
        self._policies: Dict[str, object] = {}
        self._paths: Dict[str, str] = {}
        self.batches_done = 0
        self.matches_reported = 0
        self._ledger: List[dict] = []
        self._wall_start = time.monotonic()

    # ---------------------------------------------------------------- roster
    def refresh_roster(self) -> List[str]:
        """Player ids newest-first across role keys (newest overall first)."""
        entries = []
        for role in self.roles:
            mgr = CheckpointManager(self.ckpt_dir, role=role)
            label = role or "main"
            for gen in mgr.generations():
                pid = f"{label}:{int(gen.get('step', 0))}"
                entries.append((int(gen.get("step", 0)), pid, gen["path"]))
        entries.sort(key=lambda e: (-e[0], e[1]))
        players = []
        for _, pid, path in entries:
            if pid not in players:
                players.append(pid)
                self._paths[pid] = path
        return players

    def _policy(self, pid: str):
        pol = self._policies.get(pid)
        if pol is not None:
            return pol
        if pid in self.anchors:
            pol = anchor_policy(pid)
        else:
            if self._model is None:
                from ..model import Model, default_model_config
                from ..utils import deep_merge_dicts

                self._model = Model(deep_merge_dicts(
                    default_model_config(), self.model_cfg or {}))
            params = load_params(self._paths[pid])
            pol = model_policy(self._model, params)
        self._policies[pid] = pol
        return pol

    # -------------------------------------------------------------- wire plane
    def _rpc(self, route: str, body: dict):
        from ..comm.coordinator import coordinator_request

        host, port = self.coordinator_addr
        resp = coordinator_request(host, port, route, body)
        if resp.get("code") != 0:
            raise RuntimeError(f"{route} failed: {resp.get('info')}")
        return resp.get("info")

    def _ask(self, players: List[str]) -> Optional[dict]:
        if self.store is not None:
            return self.store.next_match(players, episodes=self.episodes)
        return self._rpc("arena_next",
                         {"players": players, "episodes": self.episodes})

    def _report(self, records: List[dict]) -> dict:
        if self.store is not None:
            return self.store.report_batch(records)
        return self._rpc("arena_report", {"matches": records})

    # ---------------------------------------------------------------- one step
    def evaluate_once(self) -> Optional[dict]:
        """Roster refresh -> ask -> head_to_head -> whole-batch report.

        Returns the summary dict (assignment + head_to_head stats + report
        accounting) or None when no assignment is available.
        """
        players = self.refresh_roster()
        assignment = self._ask(players)
        if not assignment:
            return None
        home, away = assignment["home"], assignment["away"]
        rnd, seed = int(assignment["round"]), int(assignment["seed"])
        episodes = int(assignment.get("episodes", self.episodes))
        keys = jax.random.split(jax.random.PRNGKey(seed), episodes)
        res = head_to_head(self._policy(home), self._policy(away),
                           keys=keys, env_cfg=self.env_cfg,
                           scenario_cfg=self.scenario_cfg)
        per_match_s = res["duration_s"] / max(episodes, 1)
        records = [
            {"key": match_key(home, away, rnd, i),
             "home": home, "away": away, "round": rnd,
             "winner": m["winner"], "game_steps": m["game_steps"],
             "duration_s": per_match_s}
            for i, m in enumerate(res["matches"])
        ]
        ack = self._report(records)
        self.batches_done += 1
        self.matches_reported += int(ack.get("applied", 0))
        self._ledger.append({"home": home, "away": away, "round": rnd,
                             "seed": seed, "episodes": episodes,
                             "win_rate": res["win_rate"],
                             "duration_s": res["duration_s"],
                             "applied": int(ack.get("applied", 0)),
                             "duplicates": int(ack.get("duplicates", 0))})
        reg = get_registry()
        reg.counter("distar_arena_eval_batches_total",
                    "head-to-head scenario batches the evaluator completed"
                    ).inc()
        reg.gauge("distar_arena_eval_matches_per_s",
                  "arena matches evaluated per second (batch episodes / "
                  "batch wall, compile included)"
                  ).set(episodes / max(res["duration_s"], 1e-9))
        return {"assignment": assignment, "result": res, "ack": ack}

    # ----------------------------------------------------------------- artifact
    def artifact(self, ratings: Optional[dict] = None) -> dict:
        """The ``ARENA_r*.json`` payload: throughput + rating ledger, honesty
        flags in-band (1-core CPU runs must say so)."""
        wall = max(time.monotonic() - self._wall_start, 1e-9)
        total_eps = sum(e["episodes"] for e in self._ledger)
        try:
            cores = len(os.sched_getaffinity(0))
        except AttributeError:
            cores = os.cpu_count() or 1
        doc = {
            "bench": "arena",
            # headline trajectory row (tools/perf_gate.py collect_trajectory)
            "metric": "arena match throughput (batched jaxenv head-to-head, "
                      "compile included)",
            "value": total_eps / wall,
            "unit": "matches/s",
            "matches_total": self.matches_reported,
            "batches": self.batches_done,
            "wall_s": wall,
            "matches_per_s": total_eps / wall,
            "device": jax.devices()[0].platform,
            "host_cores": cores,
            "scaling_valid": False,
            "ledger": self._ledger,
        }
        if ratings is not None:
            doc["ratings"] = ratings
            block = _skill_block(ratings)
            if block is not None:
                doc["arena"] = block
        return doc

    def write_artifact(self, path: str, ratings: Optional[dict] = None,
                       extra: Optional[dict] = None) -> str:
        doc = self.artifact(ratings=ratings)
        if extra:
            doc.update(extra)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        return path
