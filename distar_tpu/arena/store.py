"""Arena store: the durable skill ledger behind the evaluation arena.

Wires the dormant seed ladder modules (``league/payoff.py``, ``league/
elo.py``, ``league/trueskill.py``) into the live system: every reported
match updates a per-pair payoff matrix (counts + Wilson confidence
intervals), the incremental ELO ladder, the TrueSkill ladder, and a
per-player :class:`~distar_tpu.league.payoff.Payoff` record — then ships
the ratings as ``distar_arena_*`` gauges into the TSDB.

Exactly-once accounting is by construction, not coordination: every match
carries an **idempotent key** ``{home}|{away}|r{round}e{episode}`` derived
from the (deterministically scheduled) pair, the per-pair round counter,
and the episode index within the PRNG-keyed scenario batch. An evaluator
that dies mid-batch reports nothing (reports are whole-batch), re-asks,
and receives the *same* assignment — the round counter only advances when
results for it are applied — so a replayed batch either fills the hole
exactly or dedups exactly.

Scheduling is uncertainty-directed: the widest-Wilson-interval pair plays
next (unplayed pairs have width 1.0 and drain first), with an anchor
round-robin floor so the newest generation keeps meeting the scripted
anchors that ground the rating scale. Durability follows the league
autosave idiom: atomic journal (tmp+fsync+rename) + a daemon autosave
thread; a coordinator restart reloads ratings, payoff, round counters AND
the seen-key set, so idempotency survives the restart too.
"""
from __future__ import annotations

import math
import pickle
import threading
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..league.algorithms import pfsp
from ..league.elo import DRAW, LOSS, WIN, ELORating
from ..league.payoff import Payoff
from ..league.trueskill import TrueSkill
from ..obs import get_registry

#: scripted policies that ground the rating scale even with one lineage
ANCHORS = ("attack_nearest", "idle")

Z95 = 1.96  # two-sided 95% normal quantile for the Wilson interval


def wilson_interval(wins: float, draws: float, losses: float,
                    z: float = Z95) -> Tuple[float, float]:
    """Wilson score interval on the draw-counts-half success rate.

    Returns ``(low, high)``; the uninformative ``(0, 1)`` with no games.
    """
    n = wins + draws + losses
    if n <= 0:
        return 0.0, 1.0
    p = (wins + 0.5 * draws) / n
    denom = 1.0 + z * z / n
    center = (p + z * z / (2 * n)) / denom
    half = z * math.sqrt(p * (1.0 - p) / n + z * z / (4 * n * n)) / denom
    return max(0.0, center - half), min(1.0, center + half)


def match_key(home: str, away: str, round_idx: int, episode: int) -> str:
    """The idempotent identity of one match (pair + scenario round + seed
    index). Reporting the same key twice is a dedup, never a double-count."""
    return f"{home}|{away}|r{int(round_idx)}e{int(episode)}"


def match_seed(a: str, b: str, round_idx: int) -> int:
    """Deterministic PRNG seed for one (unordered pair, round) scenario set —
    a pure function of the assignment so a restarted evaluator replays the
    exact same episodes."""
    lo, hi = sorted((a, b))
    return zlib.crc32(f"{lo}|{hi}|r{int(round_idx)}".encode())


def _pair_key(a: str, b: str) -> Tuple[str, str]:
    return tuple(sorted((a, b)))  # type: ignore[return-value]


class ArenaStore:
    """Coordinator-hosted payoff matrix + rating ladders + match scheduler."""

    def __init__(self, path: Optional[str] = None,
                 anchors: Sequence[str] = ANCHORS,
                 anchor_period: int = 4,
                 seen_cap: int = 100_000,
                 payoff_min_games: int = 1,
                 payoff_window: int = 256):
        self._lock = threading.Lock()
        self.path = path
        self.anchors = tuple(anchors)
        self.anchor_period = max(1, int(anchor_period))
        self._seen_cap = int(seen_cap)
        self._payoff_min_games = payoff_min_games
        self._payoff_window = payoff_window
        # ordered-pair (home, away) -> {wins, draws, losses, games}, home view
        self._pairs: Dict[Tuple[str, str], Dict[str, int]] = {}
        # unordered-pair -> next scenario round to schedule (advances only
        # when results for the current round are applied)
        self._next_round: Dict[Tuple[str, str], int] = {}
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self.elo = ELORating()
        self.trueskill = TrueSkill()
        self.payoffs: Dict[str, Payoff] = {}
        self.matches_total = 0
        self.duplicates_total = 0
        self._autosave_stop: Optional[threading.Event] = None
        self._autosave_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- scheduling
    def next_match(self, players: Sequence[str],
                   episodes: int = 8) -> Optional[dict]:
        """Deterministic uncertainty-directed assignment.

        ``players`` is the model roster newest-first (anchors are implicit).
        Pure function of *reported* state: re-asking without reporting
        returns the same assignment, which is what makes the idempotent
        keys exact under evaluator kill/restart.
        """
        with self._lock:
            roster = list(dict.fromkeys(list(players) + list(self.anchors)))
            if len(roster) < 2:
                return None
            completed = sum(self._next_round.values())
            pair: Optional[Tuple[str, str]] = None
            if players and self.anchors and completed % self.anchor_period == 0:
                # anchor floor: newest generation meets the rotating anchor
                anchor = self.anchors[(completed // self.anchor_period)
                                      % len(self.anchors)]
                if players[0] != anchor:
                    pair = _pair_key(players[0], anchor)
            if pair is None:
                # widest Wilson interval first; ties break lexicographically
                best = None
                for i, a in enumerate(roster):
                    for b in roster[i + 1:]:
                        w, d, l = self._merged_counts(a, b)
                        lo, hi = wilson_interval(w, d, l)
                        cand = (-(hi - lo), _pair_key(a, b))
                        if best is None or cand < best:
                            best = cand
                assert best is not None
                pair = best[1]
            rnd = self._next_round.get(pair, 0)
            # alternate the home seat round-over-round to cancel asymmetry
            home, away = pair if rnd % 2 == 0 else (pair[1], pair[0])
            return {"home": home, "away": away, "round": rnd,
                    "seed": match_seed(home, away, rnd),
                    "episodes": int(episodes)}

    def _merged_counts(self, a: str, b: str) -> Tuple[int, int, int]:
        """(wins, draws, losses) from a's perspective over both seatings."""
        ab = self._pairs.get((a, b), {})
        ba = self._pairs.get((b, a), {})
        wins = ab.get("wins", 0) + ba.get("losses", 0)
        draws = ab.get("draws", 0) + ba.get("draws", 0)
        losses = ab.get("losses", 0) + ba.get("wins", 0)
        return wins, draws, losses

    # -------------------------------------------------------------- reporting
    def report_batch(self, records: Sequence[dict]) -> dict:
        """Apply match records exactly once; duplicates dedup by key.

        Each record: ``{key, home, away, round, winner, game_steps,
        duration_s}`` with ``winner`` in {"home", "away", "draw"}.
        Returns ``{"applied": n, "duplicates": m}``.
        """
        applied = duplicates = 0
        with self._lock:
            for rec in records:
                key = str(rec["key"])
                if key in self._seen:
                    duplicates += 1
                    continue
                self._seen[key] = None
                while len(self._seen) > self._seen_cap:
                    self._seen.popitem(last=False)
                self._apply(rec)
                applied += 1
            self.matches_total += applied
            self.duplicates_total += duplicates
        self._publish_metrics()
        return {"applied": applied, "duplicates": duplicates}

    def _apply(self, rec: dict) -> None:
        home, away = str(rec["home"]), str(rec["away"])
        winner = str(rec.get("winner", "draw"))
        st = self._pairs.setdefault(
            (home, away), {"wins": 0, "draws": 0, "losses": 0, "games": 0})
        stat_home = {"game_steps": float(rec.get("game_steps", 0.0)),
                     "game_duration": float(rec.get("duration_s", 0.0))}
        stat_away = dict(stat_home)
        if winner == "home":
            st["wins"] += 1
            self.elo.update(home, away, WIN)
            self.trueskill.update(home, away)
            stat_home["winrate"], stat_away["winrate"] = 1.0, 0.0
        elif winner == "away":
            st["losses"] += 1
            self.elo.update(home, away, LOSS)
            self.trueskill.update(away, home)
            stat_home["winrate"], stat_away["winrate"] = 0.0, 1.0
        else:
            st["draws"] += 1
            self.elo.update(home, away, DRAW)
            self.trueskill.update(home, away, draw=True)
            stat_home["winrate"] = stat_away["winrate"] = 0.5
        st["games"] += 1
        self._payoff(home).update(away, stat_home)
        self._payoff(away).update(home, stat_away)
        pair = _pair_key(home, away)
        rnd = int(rec.get("round", 0))
        self._next_round[pair] = max(self._next_round.get(pair, 0), rnd + 1)

    def _payoff(self, pid: str) -> Payoff:
        p = self.payoffs.get(pid)
        if p is None:
            p = self.payoffs[pid] = Payoff(
                warm_up_size=self._payoff_window,
                min_win_rate_games=self._payoff_min_games)
        return p

    # -------------------------------------------------------------- snapshots
    def players(self) -> List[str]:
        with self._lock:
            return sorted({p for pair in self._pairs for p in pair}
                          | set(self.anchors))

    def ratings_snapshot(self) -> dict:
        """``GET /arena/ratings`` payload: ladders + match accounting."""
        with self._lock:
            elo_r = self.elo.ratings(start_from_zero=False)
            roster = sorted({p for pair in self._pairs for p in pair}
                            | set(self.anchors))
            players = {}
            for p in roster:
                mu, sigma = self.trueskill._get(p)
                games = sum(self._pairs.get((p, o), {}).get("games", 0)
                            + self._pairs.get((o, p), {}).get("games", 0)
                            for o in roster if o != p)
                players[p] = {
                    "elo": elo_r.get(p, self.elo.init_elo),
                    "trueskill_mu": mu, "trueskill_sigma": sigma,
                    "trueskill_exposed": mu - 3.0 * sigma,
                    "games": games,
                    "anchor": p in self.anchors,
                }
            return {"players": players,
                    "anchors": list(self.anchors),
                    "matches_total": self.matches_total,
                    "duplicates_total": self.duplicates_total}

    def payoff_snapshot(self) -> dict:
        """``GET /arena/payoff`` payload: matrix + Wilson CIs + PFSP preview."""
        with self._lock:
            roster = sorted({p for pair in self._pairs for p in pair}
                            | set(self.anchors))
            cells = []
            for i, a in enumerate(roster):
                for b in roster[i + 1:]:
                    w, d, l = self._merged_counts(a, b)
                    n = w + d + l
                    lo, hi = wilson_interval(w, d, l)
                    cells.append({
                        "a": a, "b": b, "wins": w, "draws": d, "losses": l,
                        "games": n,
                        "win_rate": (w + 0.5 * d) / n if n else 0.5,
                        "wilson_low": lo, "wilson_high": hi,
                    })
            preview = self._pfsp_preview_locked(roster)
            return {"players": roster, "cells": cells,
                    "pfsp_preview": preview,
                    "pfsp_weighting": "variance"}

    def pfsp_preview(self, roster: Sequence[str]) -> Dict[str, Dict[str, float]]:
        """Public PFSP-weight rows over an explicit roster — the league
        matchmaker's read path (it must weight exactly what the payoff
        snapshot previews, so both call one implementation)."""
        with self._lock:
            return self._pfsp_preview_locked(list(roster))

    def _pfsp_preview_locked(self, roster: List[str]) -> Dict[str, Dict[str, float]]:
        """Read-only PFSP opponent weights per player: the paper's variance
        weighting ``w(1-w)`` over observed winrates (0.5 for unplayed pairs),
        normalized — what the league PR will matchmake from."""
        preview: Dict[str, Dict[str, float]] = {}
        for p in roster:
            opponents = [o for o in roster if o != p]
            if not opponents:
                continue
            wrs = []
            for o in opponents:
                w, d, l = self._merged_counts(p, o)
                n = w + d + l
                wrs.append((w + 0.5 * d) / n if n else 0.5)
            weights = pfsp(np.asarray(wrs), weighting="variance")
            preview[p] = {o: float(wt) for o, wt in zip(opponents, weights)}
        return preview

    # ---------------------------------------------------------------- metrics
    def _publish_metrics(self) -> None:
        with self._lock:
            elo_r = self.elo.ratings(start_from_zero=False)
            ts = {p: self.trueskill.exposed(p) for p in self.trueskill.ratings}
            matches, dups = self.matches_total, self.duplicates_total
            pairs = len({_pair_key(*k) for k in self._pairs})
            newest = self._newest_player_locked()
        reg = get_registry()
        for player, rating in elo_r.items():
            reg.gauge("distar_arena_rating_elo",
                      "ELO rating per arena player (ladder offsets + init)",
                      player=player).set(rating)
        for player, exposed in ts.items():
            reg.gauge("distar_arena_rating_trueskill",
                      "conservative TrueSkill rating (mu - 3*sigma) per arena player",
                      player=player).set(exposed)
        reg.gauge("distar_arena_matches_applied",
                  "matches applied to the payoff matrix (post-dedup)").set(matches)
        reg.gauge("distar_arena_duplicates",
                  "match reports dropped as idempotent-key duplicates").set(dups)
        reg.gauge("distar_arena_pairs",
                  "distinct player pairs with at least one match").set(pairs)
        if newest is not None and newest in elo_r:
            rating = elo_r[newest]
            reg.gauge("distar_arena_main_rating",
                      "ELO of the newest non-anchor generation").set(rating)
            reg.gauge(
                "distar_arena_main_rating_inverted",
                "negated main-lineage ELO — trending_up here means the newest "
                "generation is LOSING rating (the regression rule's input)",
            ).set(-rating)

    def _newest_player_locked(self) -> Optional[str]:
        """Newest non-anchor player by the ``role:step`` id convention
        (max step wins); None when only anchors are known."""
        best: Tuple[int, str] = (-1, "")
        for pair in self._pairs:
            for p in pair:
                if p in self.anchors:
                    continue
                step = -1
                if ":" in p:
                    try:
                        step = int(p.rsplit(":", 1)[1])
                    except ValueError:
                        step = -1
                if (step, p) > best:
                    best = (max(step, 0), p)
        return best[1] or None

    # -------------------------------------------------------------- durability
    def _state_locked(self) -> dict:
        return {
            "pairs": dict(self._pairs),
            "next_round": dict(self._next_round),
            "seen": list(self._seen.keys()),
            "elo": self.elo,
            "trueskill": self.trueskill,
            "payoffs": self.payoffs,
            "matches_total": self.matches_total,
            "duplicates_total": self.duplicates_total,
        }

    def state_blob(self) -> dict:
        """Detached full-ledger state — the HA snapshot payload (journal
        snapshots and the warm-standby follower feed both carry it). The
        pickle round-trip detaches the live ladder objects so later matches
        can't mutate a snapshot already handed out."""
        with self._lock:
            return pickle.loads(pickle.dumps(self._state_locked()))

    def load_state(self, data: dict) -> None:
        """Adopt a ``state_blob()``/journal payload wholesale — ratings,
        payoff matrix, round counters AND the seen-key set, so idempotent
        dedup keeps holding across restarts and failovers."""
        with self._lock:
            self._pairs = dict(data["pairs"])
            self._next_round = dict(data["next_round"])
            self._seen = OrderedDict((k, None) for k in data["seen"])
            self.elo = data["elo"]
            self.trueskill = data["trueskill"]
            self.payoffs = data["payoffs"]
            self.matches_total = int(data["matches_total"])
            self.duplicates_total = int(data["duplicates_total"])
        self._publish_metrics()

    def save(self, path: Optional[str] = None) -> str:
        """Atomic journal (tmp+fsync+rename via the storage layer): a
        coordinator killed mid-save leaves the previous journal intact."""
        from ..utils import storage

        path = path or self.path
        assert path, "ArenaStore.save needs a path"
        with self._lock:
            blob = pickle.dumps(self._state_locked())
        storage.write_bytes(path, blob)
        return path

    def load(self, path: Optional[str] = None) -> None:
        from ..utils import storage

        path = path or self.path
        assert path, "ArenaStore.load needs a path"
        self.load_state(pickle.loads(storage.read_bytes(path)))

    def maybe_load(self) -> bool:
        """Load the journal at ``self.path`` if present; False otherwise."""
        from ..utils import storage

        if self.path and storage.exists(self.path):
            self.load(self.path)
            return True
        return False

    def start_autosave(self, path: Optional[str] = None,
                       interval_s: float = 30.0) -> str:
        """Periodic journaling on a daemon thread (the league-autosave
        idiom): journaling failures must never kill match accounting."""
        path = path or self.path
        assert path, "ArenaStore.start_autosave needs a path"
        assert interval_s > 0
        self.path = path
        self.stop_autosave()
        self._autosave_stop = threading.Event()
        stop = self._autosave_stop

        def run():
            saves = get_registry().counter(
                "distar_arena_autosaves_total", "arena journals written")
            while not stop.wait(interval_s):
                try:
                    self.save(path)
                    saves.inc()
                except Exception:
                    pass  # next tick retries; the previous journal is intact

        self._autosave_thread = threading.Thread(
            target=run, daemon=True, name="arena-autosave")
        self._autosave_thread.start()
        return path

    def stop_autosave(self) -> None:
        stop, thread = self._autosave_stop, self._autosave_thread
        if stop is not None:
            stop.set()
        if thread is not None:
            thread.join(timeout=10.0)
            self._autosave_thread = None


# --------------------------------------------------------------- process-global
_STORE: Optional[ArenaStore] = None
_STORE_LOCK = threading.Lock()


def set_arena_store(store: Optional[ArenaStore]) -> None:
    global _STORE
    with _STORE_LOCK:
        _STORE = store


def get_arena_store() -> Optional[ArenaStore]:
    with _STORE_LOCK:
        return _STORE
