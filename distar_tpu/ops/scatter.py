"""Scatter-connection: write per-entity embeddings onto the spatial map.

Role of the reference's scatter_connection (distar/agent/default/model/
module_utils.py:11-34): each entity's D-dim embedding is added (or written)
at its (x, y) cell of a [B, H, W, D] map.

TPU-first formulation: one flat `.at[...].add` per batch over a [B*H*W, D]
buffer — XLA lowers this to a native scatter on TPU with the embedding dim D
as the contiguous minor axis (the reference instead transposes to [D, B*H*W]
and scatters per channel). 'cover' mode uses `.set` with the reference's
same last-writer-wins-ish semantics (ties resolved by scatter order is NOT
guaranteed; use 'add' in training, as the reference default config does).

A Pallas kernel for the fused scatter+conv-project path lives in
`pallas_kernels.py` once profiling justifies it; this op is already
memory-bound-optimal under XLA.
"""
from __future__ import annotations

import jax.numpy as jnp


def scatter_connection(
    embeddings: jnp.ndarray,  # [B, N, D]
    locations: jnp.ndarray,  # [B, N, 2] as (x, y) int
    spatial_size,  # (H, W)
    mode: str = "add",
    impl: str = "xla",  # 'xla' | 'pallas' | 'pallas_onehot' (add mode only)
) -> jnp.ndarray:
    """Return [B, H, W, D] map with embeddings scattered at entity cells."""
    B, N, D = embeddings.shape
    H, W = spatial_size
    x = jnp.clip(locations[..., 0].astype(jnp.int32), 0, W - 1)
    y = jnp.clip(locations[..., 1].astype(jnp.int32), 0, H - 1)
    flat_idx = y * W + x  # [B, N] in row-major (y, x) order

    if impl in ("pallas", "pallas_onehot"):
        assert mode == "add", "pallas scatter implements add mode"
        from .pallas_kernels import scatter_add_connection, scatter_add_onehot

        kernel = scatter_add_onehot if impl == "pallas_onehot" else scatter_add_connection
        return kernel(embeddings, flat_idx, H * W).reshape(B, H, W, D)
    if impl != "xla":
        raise ValueError(f"unknown scatter impl {impl!r} (xla|pallas|pallas_onehot)")

    batch_bias = jnp.arange(B, dtype=jnp.int32)[:, None] * (H * W)
    flat = (flat_idx + batch_bias).reshape(-1)  # [B*N]
    buf = jnp.zeros((B * H * W, D), dtype=embeddings.dtype)
    flat_emb = embeddings.reshape(B * N, D)
    if mode == "add":
        buf = buf.at[flat].add(flat_emb)
    elif mode == "cover":
        buf = buf.at[flat].set(flat_emb)
    else:
        raise NotImplementedError(mode)
    return buf.reshape(B, H, W, D)
