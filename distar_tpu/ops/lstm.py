"""LSTM cells and a stacked scan-based runner.

Fills the role of the reference's TorchScript LN-LSTM core
(reference: distar/agent/default/model/lstm.py: LSTMCell :69-93,
LayerNormLSTMCell :120+, StackedLSTM). TPU-first design: execution is
LAYER-MAJOR — per layer, the input projection for ALL timesteps is one
big [T*B, D] x [D, 4H] matmul on the MXU (the cuDNN-style split), and
only the small recurrent [B, H] x [H, 4H] matmul + gate pointwise stays
inside the `lax.scan` over time. Identical parameters and numerics to the
step-per-layer formulation (equivalence-tested); `layer_major=False`
restores the time-major scan. State layout is a tuple of (h, c) pairs,
one per layer, each [B, hidden].
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any
LSTMState = Tuple[jnp.ndarray, jnp.ndarray]  # (h, c) each [B, H]


class PlainLSTMCell(nn.Module):
    """Standard LSTM cell: gates = x W_ih + h W_hh + b."""

    hidden_size: int
    dtype: Dtype = jnp.float32

    def setup(self):
        self.ih = nn.Dense(4 * self.hidden_size, dtype=self.dtype)
        self.hh = nn.Dense(4 * self.hidden_size, dtype=self.dtype)

    def input_proj(self, x):
        """The x-dependent half of the gates; batched over any leading dims
        (one MXU matmul for a whole [T, B, D] sequence)."""
        return self.ih(x)

    def step_from_proj(self, ih, state: LSTMState) -> Tuple[jnp.ndarray, LSTMState]:
        h, c = state
        gates = ih + self.hh(h)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        # recurrent state stays in the carry's dtype (f32 under mixed
        # precision) so scan carries type-check and accumulation is stable
        h_new = h_new.astype(h.dtype)
        c_new = c_new.astype(c.dtype)
        return h_new, (h_new, c_new)

    def __call__(self, x, state: LSTMState) -> Tuple[jnp.ndarray, LSTMState]:
        return self.step_from_proj(self.input_proj(x), state)


class LayerNormLSTMCell(nn.Module):
    """LSTM cell with layer-normalised input/recurrent projections and cell
    state, matching the reference's LayerNormLSTMCell gate structure:
    gates = LN(x W_ih) + LN(h W_hh); c' = LN(f*c + i*g); h' = o * tanh(c')."""

    hidden_size: int
    dtype: Dtype = jnp.float32

    def setup(self):
        self.ih = nn.Dense(4 * self.hidden_size, use_bias=False, dtype=self.dtype)
        self.ln_ih = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)
        self.hh = nn.Dense(4 * self.hidden_size, use_bias=False, dtype=self.dtype)
        self.ln_hh = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)
        self.ln_c = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)

    def input_proj(self, x):
        """LN(x W_ih); LayerNorm is per-row, so batching the whole [T, B, D]
        sequence through one matmul is numerically identical to per-step."""
        return self.ln_ih(self.ih(x))

    def step_from_proj(self, ih, state: LSTMState) -> Tuple[jnp.ndarray, LSTMState]:
        h, c = state
        gates = ih + self.ln_hh(self.hh(h))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = self.ln_c(
            jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        )
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        h_new = h_new.astype(h.dtype)
        c_new = c_new.astype(c.dtype)
        return h_new, (h_new, c_new)

    def __call__(self, x, state: LSTMState) -> Tuple[jnp.ndarray, LSTMState]:
        return self.step_from_proj(self.input_proj(x), state)


class StackedLSTM(nn.Module):
    """N stacked cells over time.

    Input [T, B, D] -> output [T, B, H] plus final per-layer states.
    Layer-major by default: each layer hoists its input projection out of
    the time scan (see module docstring); `layer_major=False` scans
    time-major with all layer states in one carry.
    """

    hidden_size: int
    num_layers: int
    norm: str = "LN"  # 'LN' -> LayerNormLSTMCell, 'none' -> PlainLSTMCell
    dtype: Dtype = jnp.float32
    # lax.scan unroll factor: >1 fuses that many timesteps per loop
    # iteration — fewer loop boundaries for the 64-step unrolls whose
    # per-step matmuls are far too small to fill the MXU at batch ~6.
    # Measured, not assumed: bench BENCH_LSTM_UNROLL / config
    # encoder.core_lstm.scan_unroll
    scan_unroll: int = 1
    layer_major: bool = True

    def setup(self):
        cell_cls = LayerNormLSTMCell if self.norm == "LN" else PlainLSTMCell
        self.cells = [
            cell_cls(self.hidden_size, self.dtype, name=f"layer{i}")
            for i in range(self.num_layers)
        ]

    def init_state(self, batch_size: int) -> Tuple[LSTMState, ...]:
        # carry in f32 regardless of compute dtype (accumulation stability)
        z = jnp.zeros((batch_size, self.hidden_size), dtype=jnp.float32)
        return tuple((z, z) for _ in range(self.num_layers))

    def _step(self, states, x):
        new_states = []
        for cell, st in zip(self.cells, states):
            x, st = cell(x, st)
            new_states.append(st)
        return tuple(new_states), x

    def __call__(
        self, xs: jnp.ndarray, states: Optional[Tuple[LSTMState, ...]] = None
    ) -> Tuple[jnp.ndarray, Tuple[LSTMState, ...]]:
        if states is None:
            states = self.init_state(xs.shape[1])
        if self.is_initializing():
            # trace one step eagerly so params exist before scan
            final, y = self._step(states, xs[0])
            ys = jnp.broadcast_to(y[None], (xs.shape[0],) + y.shape)
            return ys, final
        if not self.layer_major:
            final, ys = nn.transforms.scan(
                lambda mdl, carry, x: mdl._step(carry, x),
                variable_broadcast="params",
                split_rngs={"params": False},
                unroll=self.scan_unroll,
            )(self, states, xs)
            return ys, final
        # layer-major: hoist each layer's input projection out of the scan
        h_seq = xs
        new_states = []
        for cell, st in zip(self.cells, states):
            proj = cell.input_proj(h_seq)  # [T, B, 4H]: ONE MXU matmul
            st, h_seq = nn.transforms.scan(
                lambda mdl, carry, p: tuple(reversed(mdl.step_from_proj(p, carry))),
                variable_broadcast="params",
                split_rngs={"params": False},
                unroll=self.scan_unroll,
            )(cell, st, proj)
            new_states.append(st)
        return h_seq, tuple(new_states)
