"""LSTM cells and a stacked scan-based runner.

Fills the role of the reference's TorchScript LN-LSTM core
(reference: distar/agent/default/model/lstm.py: LSTMCell :69-93,
LayerNormLSTMCell :120+, StackedLSTM). TPU-first design: the time loop is a
single `jax.lax.scan` whose body is one fused cell step per layer — XLA
unrolls nothing, compiles once for any T, and the 4*hidden gate matmul lands
on the MXU. State layout is a tuple of (h, c) pairs, one per layer, each
[B, hidden].
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any
LSTMState = Tuple[jnp.ndarray, jnp.ndarray]  # (h, c) each [B, H]


class PlainLSTMCell(nn.Module):
    """Standard LSTM cell: gates = x W_ih + h W_hh + b."""

    hidden_size: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, state: LSTMState) -> Tuple[jnp.ndarray, LSTMState]:
        h, c = state
        gates = nn.Dense(4 * self.hidden_size, dtype=self.dtype, name="ih")(x) + nn.Dense(
            4 * self.hidden_size, dtype=self.dtype, name="hh"
        )(h)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        # recurrent state stays in the carry's dtype (f32 under mixed
        # precision) so scan carries type-check and accumulation is stable
        h_new = h_new.astype(h.dtype)
        c_new = c_new.astype(c.dtype)
        return h_new, (h_new, c_new)


class LayerNormLSTMCell(nn.Module):
    """LSTM cell with layer-normalised input/recurrent projections and cell
    state, matching the reference's LayerNormLSTMCell gate structure:
    gates = LN(x W_ih) + LN(h W_hh); c' = LN(f*c + i*g); h' = o * tanh(c')."""

    hidden_size: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, state: LSTMState) -> Tuple[jnp.ndarray, LSTMState]:
        h, c = state
        ih = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="ln_ih")(
            nn.Dense(4 * self.hidden_size, use_bias=False, dtype=self.dtype, name="ih")(x)
        )
        hh = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="ln_hh")(
            nn.Dense(4 * self.hidden_size, use_bias=False, dtype=self.dtype, name="hh")(h)
        )
        gates = ih + hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="ln_c")(
            jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        )
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        h_new = h_new.astype(h.dtype)
        c_new = c_new.astype(c.dtype)
        return h_new, (h_new, c_new)


class StackedLSTM(nn.Module):
    """N stacked cells scanned over time.

    Input [T, B, D] -> output [T, B, H] plus final per-layer states. The scan
    carries all layer states; per step each layer feeds the next.
    """

    hidden_size: int
    num_layers: int
    norm: str = "LN"  # 'LN' -> LayerNormLSTMCell, 'none' -> PlainLSTMCell
    dtype: Dtype = jnp.float32
    # lax.scan unroll factor: >1 fuses that many timesteps per loop
    # iteration — fewer loop boundaries for the 64-step unrolls whose
    # per-step matmuls are far too small to fill the MXU at batch ~6.
    # Measured, not assumed: bench BENCH_LSTM_UNROLL / config
    # encoder.core_lstm.scan_unroll
    scan_unroll: int = 1

    def setup(self):
        cell_cls = LayerNormLSTMCell if self.norm == "LN" else PlainLSTMCell
        self.cells = [
            cell_cls(self.hidden_size, self.dtype, name=f"layer{i}")
            for i in range(self.num_layers)
        ]

    def init_state(self, batch_size: int) -> Tuple[LSTMState, ...]:
        # carry in f32 regardless of compute dtype (accumulation stability)
        z = jnp.zeros((batch_size, self.hidden_size), dtype=jnp.float32)
        return tuple((z, z) for _ in range(self.num_layers))

    def _step(self, states, x):
        new_states = []
        for cell, st in zip(self.cells, states):
            x, st = cell(x, st)
            new_states.append(st)
        return tuple(new_states), x

    def __call__(
        self, xs: jnp.ndarray, states: Optional[Tuple[LSTMState, ...]] = None
    ) -> Tuple[jnp.ndarray, Tuple[LSTMState, ...]]:
        if states is None:
            states = self.init_state(xs.shape[1])
        if self.is_initializing():
            # trace one step eagerly so params exist before scan
            final, y = self._step(states, xs[0])
            ys = jnp.broadcast_to(y[None], (xs.shape[0],) + y.shape)
            return ys, final
        final, ys = nn.transforms.scan(
            lambda mdl, carry, x: mdl._step(carry, x),
            variable_broadcast="params",
            split_rngs={"params": False},
            unroll=self.scan_unroll,
        )(self, states, xs)
        return ys, final
