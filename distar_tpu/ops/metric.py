"""Distance metrics for the Z pseudo-rewards (host-side numpy).

Role parity with the reference metrics (reference: distar/ctools/torch_utils/
metric.py): levenshtein with a per-match location-cost hook (matching build
orders still pay for misplaced locations), hamming over cumulative-stat
bags, and the clamped L2 location cost. These run per env step on the actor
host, so numpy is the right tool (no device roundtrip for a 20-element DP).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np


def l2_distance(a, b, min_val: float = 0.0, max_val: float = 0.8, threshold: float = 5.0,
                spatial_x: int = 160) -> float:
    """Clamped L2 between two flat map indices (cost of a matched build-order
    step placed at the wrong spot)."""
    a, b = float(a), float(b)
    x0, y0 = a % spatial_x, a // spatial_x
    x1, y1 = b % spatial_x, b // spatial_x
    l2 = np.sqrt((x1 - x0) ** 2 + (y1 - y0) ** 2)
    return float(np.clip(l2 / threshold, min_val, max_val))


def levenshtein_distance(
    behaviour: np.ndarray,
    target: np.ndarray,
    behaviour_extra: Optional[np.ndarray] = None,
    target_extra: Optional[np.ndarray] = None,
    extra_fn: Optional[Callable] = None,
) -> float:
    """Edit distance; when tokens match, ``extra_fn`` prices the per-step
    extras (locations) instead of a free match."""
    behaviour = np.asarray(behaviour)
    target = np.asarray(target)
    n1, n2 = len(behaviour), len(target)
    if n1 == 0 or n2 == 0:
        return float(max(n1, n2))
    dp = np.zeros((n1 + 1, n2 + 1), dtype=np.float64)
    dp[0, :] = np.arange(n2 + 1)
    dp[:, 0] = np.arange(n1 + 1)
    for i in range(1, n1 + 1):
        for j in range(1, n2 + 1):
            if behaviour[i - 1] == target[j - 1]:
                cost = (
                    extra_fn(behaviour_extra[i - 1], target_extra[j - 1]) if extra_fn else 0.0
                )
                dp[i, j] = dp[i - 1, j - 1] + cost
            else:
                dp[i, j] = min(dp[i - 1, j] + 1, dp[i, j - 1] + 1, dp[i - 1, j - 1] + 1)
    return float(dp[n1, n2])


def hamming_distance(behaviour: np.ndarray, target: np.ndarray) -> float:
    behaviour = np.asarray(behaviour).astype(bool)
    target = np.asarray(target).astype(bool)
    assert behaviour.shape == target.shape
    return float((behaviour != target).sum(-1))
