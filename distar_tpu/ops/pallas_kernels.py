"""Pallas TPU kernels for the framework's hot ops.

The two #1 kernel candidates named in the survey (SURVEY.md §2.3:
module_utils scatter_connection; §5: entity transformer as a Pallas masked
attention):

* ``masked_attention``   — fused softmax(QK^T + mask)V over the <=512-entity
  set. One (batch, head) program: scores, mask, a numerically-stable softmax,
  and the value matmul all stay in VMEM; both matmuls hit the MXU at
  (512 x 64/128) tiles. Saves the HBM round-trips XLA's unfused
  mask->softmax->matmul chain can incur at small batch.
* ``scatter_add_connection`` — per-batch scatter-add of entity embeddings
  into the flattened (H*W, D) map via a fori_loop of dynamic row updates
  (entity count is static at 512; padding rows write via a validity mask to
  row 0 with zero weight).
* ``scatter_add_onehot`` — the same scatter-add as a chunked one-hot
  matmul: the [N, chunk] one-hot tile is built in VMEM (iota-compare) and
  consumed by the MXU, replacing the loop kernel's serial row updates.

All run under ``interpret=True`` on CPU (tests compare against the jnp
reference implementations) and lower natively on TPU. Enable via
``attn_impl='pallas'`` on ops.Transformer (model config key
``encoder.entity.attention_impl``) and ``impl='pallas'|'pallas_onehot'``
on ops.scatter_connection; defaults should follow
``tools/bench_kernels.py``'s on-silicon table.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9


# --------------------------------------------------------------- attention
def _attention_kernel(q_ref, k_ref, v_ref, mask_ref, out_ref, *, scale: float):
    q = q_ref[0, 0]  # [N, Dh]
    k = k_ref[0, 0]  # [N, Dh]
    v = v_ref[0, 0]  # [N, Dh]
    mask = mask_ref[0, 0]  # [1, N] key validity
    score = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [N, N]
    score = jnp.where(mask.astype(jnp.bool_), score, NEG_INF)
    score = score - jnp.max(score, axis=-1, keepdims=True)
    p = jnp.exp(score)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    # f32 accumulation throughout; the write narrows to the output dtype
    # (bf16 under mixed precision — halves the HBM write, matches the XLA
    # path's einsum output dtype)
    out_ref[0, 0] = jnp.dot(
        p, v, preferred_element_type=jnp.float32
    ).astype(out_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def masked_attention(
    q: jnp.ndarray,  # [B, H, N, Dh]
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,  # [B, N] bool key validity
    interpret: Optional[bool] = None,  # None: native on TPU, interpret elsewhere
) -> jnp.ndarray:
    """Fused masked attention. Differentiable: the forward runs the Pallas
    kernel; the backward recomputes the softmax in plain XLA (flash-attention
    style pallas-fwd/recompute-bwd split — the backward is matmul-dominated
    and XLA tiles it onto the MXU fine)."""
    return _masked_attention_fwd_kernel(q, k, v, mask, interpret)


def _masked_attention_fwd_kernel(q, k, v, mask, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, N, Dh = q.shape
    scale = 1.0 / (Dh ** 0.5)
    mask2 = mask[:, None, None, :].astype(jnp.float32)  # [B, 1, 1, N]
    mask2 = jnp.broadcast_to(mask2, (B, H, 1, N))

    grid = (B, H)

    def idx(b, h):
        return (b, h, 0, 0)

    return pl.pallas_call(
        functools.partial(_attention_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((B, H, N, Dh), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, N, Dh), idx, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, N, Dh), idx, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, N, Dh), idx, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1, N), idx, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, N, Dh), idx, memory_space=pltpu.VMEM),
        interpret=interpret,
    )(q, k, v, mask2)


def _masked_attention_vjp_fwd(q, k, v, mask, interpret):
    out = _masked_attention_fwd_kernel(q, k, v, mask, interpret)
    return out, (q, k, v, mask)


def _masked_attention_vjp_bwd(interpret, res, dout):
    # recompute in f32 regardless of the primal dtype: the forward kernel
    # accumulates in f32, and a bf16 softmax recompute here would
    # differentiate a visibly different p than the forward computed
    q0, k0, v0, mask = res
    q, k, v = (t.astype(jnp.float32) for t in (q0, k0, v0))
    dout = dout.astype(jnp.float32)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    score = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    score = jnp.where(mask[:, None, None, :], score, NEG_INF)
    p = jax.nn.softmax(score, axis=-1)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dout)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dout, v)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q) * scale
    return dq.astype(q0.dtype), dk.astype(k0.dtype), dv.astype(v0.dtype), None


masked_attention.defvjp(_masked_attention_vjp_fwd, _masked_attention_vjp_bwd)


def masked_attention_reference(q, k, v, mask):
    """jnp oracle with identical semantics."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    score = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    score = jnp.where(mask[:, None, None, :], score, NEG_INF)
    p = jax.nn.softmax(score, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# ----------------------------------------------------------------- scatter
def _scatter_kernel(emb_ref, idx_ref, out_ref, *, n_entities: int):
    # zero the output tile, then accumulate entity rows at dynamic offsets.
    # idx lives in SMEM: scalar reads that drive dynamic slices belong there
    # (and VMEM's (8, 128) block-tiling rule doesn't apply to SMEM blocks).
    out_ref[0] = jnp.zeros_like(out_ref[0])

    def body(i, _):
        row = idx_ref[0, i]  # flat cell index (already validity-masked)
        out_ref[0, pl.ds(row, 1), :] += emb_ref[0, pl.ds(i, 1), :]
        return 0

    jax.lax.fori_loop(0, n_entities, body, 0)


def scatter_add_connection(
    embeddings: jnp.ndarray,  # [B, N, D] (invalid entities must be zeroed)
    flat_idx: jnp.ndarray,  # [B, N] int cell index (clipped to [0, H*W))
    hw: int,
    interpret: Optional[bool] = None,  # None: native on TPU, interpret elsewhere
) -> jnp.ndarray:
    """Per-batch scatter-add; returns [B, H*W, D]. Differentiable: the
    scatter-add's VJP w.r.t. embeddings is a plain gather of the output
    cotangent at the same indices (XLA backward).

    Out-of-range indices are CLIPPED to [0, hw-1] here, in the public
    wrapper — identical semantics to ``scatter_add_onehot`` by construction,
    so switching ``impl`` strings can never silently change forward or
    gradient behaviour (the kernels themselves used to disagree: ``pl.ds``
    clamped where the one-hot matmul dropped)."""
    flat_idx = jnp.clip(flat_idx.astype(jnp.int32), 0, hw - 1)
    return _scatter_add_connection_core(embeddings, flat_idx, hw, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _scatter_add_connection_core(embeddings, flat_idx, hw, interpret):
    return _scatter_add_fwd_kernel(embeddings, flat_idx, hw, interpret)


def _scatter_add_fwd_kernel(embeddings, flat_idx, hw, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, N, D = embeddings.shape

    return pl.pallas_call(
        functools.partial(_scatter_kernel, n_entities=N),
        out_shape=jax.ShapeDtypeStruct((B, hw, D), embeddings.dtype),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, N, D), lambda b: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N), lambda b: (b, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, hw, D), lambda b: (b, 0, 0), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(embeddings, flat_idx.astype(jnp.int32))


def _scatter_add_vjp_fwd(embeddings, flat_idx, hw, interpret):
    return _scatter_add_fwd_kernel(embeddings, flat_idx, hw, interpret), flat_idx


def _scatter_add_vjp_bwd(hw, interpret, flat_idx, dout):
    # d(embeddings)[b, n] = dout[b, idx[b, n]] (idx pre-clipped by the wrapper)
    demb = jnp.take_along_axis(
        dout, flat_idx.astype(jnp.int32)[..., None].clip(0, hw - 1), axis=1
    )
    return demb, None


_scatter_add_connection_core.defvjp(_scatter_add_vjp_fwd, _scatter_add_vjp_bwd)


# ------------------------------------------------- scatter via one-hot matmul
def _scatter_onehot_kernel(emb_ref, idx_ref, out_ref, *, chunk: int):
    # out[cells] = onehot(idx)^T @ emb for this (batch, cell-chunk) tile.
    # The one-hot tile is BUILT IN VMEM (iota-compare) and immediately
    # consumed by the MXU — it never touches HBM, which is what makes this
    # formulation beat a serial row-update loop on TPU.
    c = pl.program_id(1)
    idx = idx_ref[0, 0, :]  # [N] int32
    emb = emb_ref[0]  # [N, D]
    n = idx.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (n, chunk), 1) + c * chunk
    onehot = (idx[:, None] == col).astype(emb.dtype)  # [N, chunk]
    out_ref[0] = jax.lax.dot_general(
        onehot, emb, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(out_ref.dtype)


def scatter_add_onehot(
    embeddings: jnp.ndarray,  # [B, N, D] (invalid entities must be zeroed)
    flat_idx: jnp.ndarray,  # [B, N] int cell index (clipped to [0, H*W))
    hw: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Per-batch scatter-add as a chunked one-hot matmul ([B, hw, D]).
    Out-of-range indices are CLIPPED to [0, hw-1] in this public wrapper —
    the same clamp as ``scatter_add_connection``, so forward AND gradient
    semantics are identical across ``impl`` strings (the raw one-hot kernel
    would otherwise DROP out-of-range rows where the loop kernel clamps).
    Trades `2*N*hw*D` MXU FLOPs for the serial dynamic-row updates of the
    loop kernel; gather backward."""
    flat_idx = jnp.clip(flat_idx.astype(jnp.int32), 0, hw - 1)
    return _scatter_add_onehot_core(embeddings, flat_idx, hw, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _scatter_add_onehot_core(embeddings, flat_idx, hw, interpret):
    return _scatter_onehot_fwd_kernel(embeddings, flat_idx, hw, interpret)


def _scatter_onehot_fwd_kernel(embeddings, flat_idx, hw, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, N, D = embeddings.shape
    # cell chunk per program: big enough to amortise the emb reload, small
    # enough that the [N, chunk] one-hot tile stays comfortably in VMEM
    # (512x2048 bf16 = 2 MiB). Lane-dim tiles want multiples of 128.
    chunk = min(hw, 2048)
    if chunk % 128:
        chunk = -(-chunk // 128) * 128  # round up: one partially-used tile
    # ...but never past hw itself: a small unaligned grid (hw=63 -> 128)
    # would otherwise hand the kernel an out-of-bounds output block and rely
    # on the backend's block padding for correctness
    chunk = min(chunk, hw)
    grid = (B, -(-hw // chunk))

    return pl.pallas_call(
        functools.partial(_scatter_onehot_kernel, chunk=chunk),
        out_shape=jax.ShapeDtypeStruct((B, hw, D), embeddings.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, N, D), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, N), lambda b, c: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, chunk, D), lambda b, c: (b, c, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(embeddings, flat_idx.astype(jnp.int32)[:, None, :])


def _scatter_onehot_vjp_fwd(embeddings, flat_idx, hw, interpret):
    return _scatter_onehot_fwd_kernel(embeddings, flat_idx, hw, interpret), flat_idx


def _scatter_onehot_vjp_bwd(hw, interpret, flat_idx, dout):
    # indices reach the core pre-clipped by the public wrapper, so the
    # gather backward matches the loop kernel's exactly; the in_range guard
    # stays for direct core callers
    idx = flat_idx.astype(jnp.int32)
    in_range = (idx >= 0) & (idx < hw)
    demb = jnp.take_along_axis(dout, idx[..., None].clip(0, hw - 1), axis=1)
    return jnp.where(in_range[..., None], demb, 0), None


_scatter_add_onehot_core.defvjp(_scatter_onehot_vjp_fwd, _scatter_onehot_vjp_bwd)
