"""Masked set-attention transformer and learned-query attention pooling.

Fills the role of the reference's entity transformer
(reference: distar/agent/default/model/module_utils.py:71-199,37-69). The
attention here is over *sets of <=512 entities*, not long sequences — one
fused softmax(QK^T)V per layer maps cleanly onto the MXU at these sizes, so
the default path is plain XLA (which fuses mask+softmax well). The mask is a
key-validity vector broadcast over queries.

For genuinely long sequences the natural extension point is a sequence-
parallel mesh axis (ring attention over shards); `Attention` takes logical
axis names so heads/features can be sharded via pjit when that axis exists.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .blocks import FCBlock, build_activation

Dtype = Any

NEG_INF = -1e9


class Attention(nn.Module):
    """Multi-head self-attention over a set, with key-validity masking.

    ``impl='pallas'`` routes the fused mask+softmax+PV kernel
    (ops.pallas_kernels.masked_attention) — TPU only; the default XLA path
    runs everywhere and fuses well at trainer batch sizes.

    ``impl='ring'`` shards the set/sequence axis over the context mesh's
    ``sp`` axis and runs exact ring attention (parallel.ring_attention:
    K/V blocks rotate via ppermute, online softmax) — the context-parallel
    path for sequences beyond one chip's HBM. Falls back to the XLA path
    when no sp>1 mesh is declared (parallel.set_context_mesh)."""

    head_dim: int
    head_num: int
    output_dim: int
    dtype: Dtype = jnp.float32
    impl: str = "xla"  # 'xla' | 'pallas' | 'ring'

    @nn.compact
    def __call__(self, x, mask: Optional[jnp.ndarray] = None):
        B, N, _ = x.shape
        qkv = nn.Dense(3 * self.head_dim * self.head_num, dtype=self.dtype)(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, N, self.head_num, self.head_dim).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if mask is None:
            mask_b = jnp.ones((B, N), bool)
        else:
            mask_b = mask
        impl = self.impl
        ring_mesh = None
        if impl == "ring":
            from ..parallel.mesh import get_context_mesh

            ring_mesh = get_context_mesh()
            if ring_mesh is None or ring_mesh.shape.get("sp", 1) <= 1 or N % ring_mesh.shape["sp"]:
                impl = "xla"
        if impl == "pallas":
            from .pallas_kernels import masked_attention

            out = masked_attention(q, k, v, mask_b)
        elif impl == "ring":
            from ..parallel.ring_attention import ring_self_attention

            out = ring_self_attention(q, k, v, mask_b.astype(bool), ring_mesh)
        else:
            score = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(float(self.head_dim))
            score = jnp.where(mask_b[:, None, None, :], score, NEG_INF)
            score = jax.nn.softmax(score, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", score, v)
        out = out.transpose(0, 2, 1, 3).reshape(B, N, self.head_num * self.head_dim)
        return nn.Dense(self.output_dim, dtype=self.dtype)(out)


class TransformerLayer(nn.Module):
    head_dim: int
    hidden_dim: int
    output_dim: int
    head_num: int
    mlp_num: int
    activation: str = "relu"
    ln_type: str = "post"
    dtype: Dtype = jnp.float32
    attn_impl: str = "xla"

    @nn.compact
    def __call__(self, x, mask: Optional[jnp.ndarray] = None):
        attn = Attention(
            self.head_dim, self.head_num, self.output_dim, self.dtype, impl=self.attn_impl
        )
        dims = [self.hidden_dim] * (self.mlp_num - 1) + [self.output_dim]

        def mlp(h):
            for d in dims:
                h = FCBlock(d, self.activation, dtype=self.dtype)(h)
            return h

        if self.ln_type == "post":
            x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)(x + attn(x, mask))
            x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)(x + mlp(x))
        elif self.ln_type == "pre":
            x = x + attn(nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)(x), mask)
            x = x + mlp(nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)(x))
        else:
            raise NotImplementedError(self.ln_type)
        return x


class Transformer(nn.Module):
    """Embedding fc + N transformer layers, masked over invalid set slots."""

    head_dim: int = 128
    hidden_dim: int = 1024
    output_dim: int = 256
    head_num: int = 2
    mlp_num: int = 2
    layer_num: int = 3
    activation: str = "relu"
    ln_type: str = "pre"
    dtype: Dtype = jnp.float32
    attn_impl: str = "xla"

    @nn.compact
    def __call__(self, x, mask: Optional[jnp.ndarray] = None):
        x = FCBlock(self.output_dim, self.activation, dtype=self.dtype)(x)
        for _ in range(self.layer_num):
            x = TransformerLayer(
                self.head_dim,
                self.hidden_dim,
                self.output_dim,
                self.head_num,
                self.mlp_num,
                self.activation,
                self.ln_type,
                self.dtype,
                attn_impl=self.attn_impl,
            )(x, mask)
        return x


class AttentionPool(nn.Module):
    """Learned-query pooling over a masked set, optional count embedding
    (role of reference module_utils.py:37-69)."""

    head_num: int
    output_dim: int
    max_num: Optional[int] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, num: Optional[jnp.ndarray] = None, mask: Optional[jnp.ndarray] = None):
        B, N, C = x.shape
        queries = self.param("queries", nn.initializers.xavier_uniform(), (1, 1, self.head_num, C))
        score = (x[:, :, None, :] * queries).sum(-1)  # B, N, H
        if mask is not None:
            if mask.ndim == 3:
                mask = mask[..., 0]
            score = jnp.where(mask[:, :, None].astype(bool), score, NEG_INF)
        score = jax.nn.softmax(score, axis=1)
        pooled = jnp.einsum("bnc,bnh->bhc", x, score).reshape(B, self.head_num * C)
        pooled = nn.Dense(self.output_dim, dtype=self.dtype)(pooled)
        if self.max_num is not None:
            assert num is not None
            count = nn.Embed(self.max_num, self.output_dim, dtype=self.dtype)(
                jnp.clip(num.astype(jnp.int32), 0, self.max_num - 1)
            )
            pooled = pooled + jax.nn.relu(count)
        return jax.nn.relu(pooled)
