"""Core NN building blocks (Flax linen), TPU-first.

These fill the roles of the reference's fc/conv/res/GLU block zoo
(reference: distar/ctools/torch_utils/network/nn_module.py, res_block.py,
module_utils.py:204-353,508-525) but are designed for XLA: channels-last
convolutions (NHWC maps onto TPU conv layouts), optional bfloat16 compute
dtype on every matmul/conv, and one-hot/binary encodings expressed as
gathers so the compiler fuses them into the consuming matmul.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any

ACTIVATIONS: dict = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    None: lambda x: x,
    "none": lambda x: x,
}


def build_activation(name: Optional[str]) -> Callable:
    if callable(name):
        return name
    return ACTIVATIONS[name]


def one_hot(x: jnp.ndarray, num_classes: int, clamp: bool = True) -> jnp.ndarray:
    """One-hot with the reference's clamp-don't-crash semantics
    (entity_encoder.py:72): out-of-range ids clip to the last class."""
    x = x.astype(jnp.int32)
    if clamp:
        x = jnp.clip(x, 0, num_classes - 1)
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def binary_encode(x: jnp.ndarray, bit_num: int) -> jnp.ndarray:
    """Fixed-width binary expansion of non-negative ints (low bit last,
    matching the reference's get_binary_embed_mat big-endian bit order)."""
    x = x.astype(jnp.int32)
    shifts = jnp.arange(bit_num - 1, -1, -1, dtype=jnp.int32)
    return ((x[..., None] >> shifts) & 1).astype(jnp.float32)


def sequence_mask(lengths: jnp.ndarray, max_len: int) -> jnp.ndarray:
    """[..., max_len] boolean mask: position i valid iff i < length."""
    return jnp.arange(max_len)[None, :] < lengths[..., None]


class FCBlock(nn.Module):
    """Dense + optional LayerNorm + activation."""

    features: int
    activation: Optional[str] = "relu"
    norm: Optional[str] = None
    dtype: Dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_uniform()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(
            self.features, dtype=self.dtype, kernel_init=self.kernel_init, bias_init=self.bias_init
        )(x)
        if self.norm == "LN":
            x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)(x)
        return build_activation(self.activation)(x)


class Conv2DBlock(nn.Module):
    """NHWC conv + optional norm + activation."""

    features: int
    kernel_size: int = 3
    strides: int = 1
    padding: Any = "SAME"
    activation: Optional[str] = "relu"
    norm: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        pad = self.padding
        if isinstance(pad, int):
            pad = [(pad, pad), (pad, pad)]
        x = nn.Conv(
            self.features,
            (self.kernel_size, self.kernel_size),
            strides=(self.strides, self.strides),
            padding=pad,
            dtype=self.dtype,
        )(x)
        if self.norm == "LN":
            x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)(x)
        return build_activation(self.activation)(x)


class ResBlock(nn.Module):
    """Two 3x3 convs with a skip: act(x + conv(conv(x)))."""

    features: int
    activation: str = "relu"
    norm: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        act = build_activation(self.activation)
        y = Conv2DBlock(self.features, 3, 1, "SAME", self.activation, self.norm, self.dtype)(x)
        y = Conv2DBlock(self.features, 3, 1, "SAME", None, self.norm, self.dtype)(y)
        return act(x + y)


class ResFCBlock(nn.Module):
    """Residual fc block: act(x + fc(fc(x))), norm per fc as configured."""

    features: int
    activation: str = "relu"
    norm: Optional[str] = "LN"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        act = build_activation(self.activation)
        y = FCBlock(self.features, self.activation, self.norm, self.dtype)(x)
        y = FCBlock(self.features, None, self.norm, self.dtype)(y)
        return act(x + y)


class ResFCBlock2(nn.Module):
    """Post-norm residual fc block: LN(x + fc(fc_act(x))), no outer
    activation (the reference's value-tower block, res_block.py:110-139)."""

    features: int
    activation: str = "relu"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = FCBlock(self.features, self.activation, None, self.dtype)(x)
        y = FCBlock(self.features, None, None, self.dtype)(y)
        return nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)(x + y)


class GLU(nn.Module):
    """Gated linear unit conditioned on a context vector
    (role of reference module_utils.py:508-525): out = (sigmoid(W_c ctx) * x) W."""

    features: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context):
        gate = nn.Dense(x.shape[-1], dtype=self.dtype)(context)
        gate = jax.nn.sigmoid(gate)
        return nn.Dense(self.features, dtype=self.dtype)(gate * x)


class GatedResBlock(nn.Module):
    """Conv res block whose residual is gated by a noise/context map
    (role of reference module_utils.py:204-231)."""

    features: int
    activation: str = "relu"
    norm: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, gate_map):
        act = build_activation(self.activation)
        y = Conv2DBlock(self.features, 3, 1, "SAME", self.activation, self.norm, self.dtype)(x)
        y = Conv2DBlock(self.features, 3, 1, "SAME", None, self.norm, self.dtype)(y)
        g = gate_map
        for a in (self.activation, self.activation, self.activation, None):
            g = Conv2DBlock(self.features, 1, 1, "SAME", a, None, self.dtype)(g)
        scale = self.param("update_sp", nn.initializers.constant(0.1), (1,))
        y = jnp.tanh(y * jax.nn.sigmoid(g)) * scale
        return act(x + y)


class FiLM(nn.Module):
    """Feature-wise linear modulation over NHWC maps."""

    @nn.compact
    def __call__(self, x, gammas, betas):
        gammas = gammas[:, None, None, :]
        betas = betas[:, None, None, :]
        return gammas * x + betas
