"""RL return/advantage primitives as pure jnp functions.

Functional equivalents of the reference's return computations
(reference: distar/agent/default/rl_training/as_rl_utils.py:157-312), with
the reverse time recursions expressed as `jax.lax.scan` over the reversed
time axis instead of Python loops — one compiled kernel for any T.

Shape convention matches the reference: time-major [T, B] rewards and
[T+1, B] bootstrap values.
"""
from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp

Scalar = Union[float, jnp.ndarray]


def _as_tb(x: Scalar, like: jnp.ndarray) -> jnp.ndarray:
    return x * jnp.ones_like(like) if not isinstance(x, jnp.ndarray) or x.ndim == 0 else x


def multistep_forward_view(
    rewards: jnp.ndarray,  # [T, B]
    gammas: jnp.ndarray,  # [T, B]
    bootstrap_values: jnp.ndarray,  # [T, B] = V[1..T]
    lambda_: jnp.ndarray,  # [T, B]
) -> jnp.ndarray:
    """Sutton & Barto (12.18) lambda-return recursion:
    result[T-1] = r[T-1] + g[T-1] V[T];
    result[t] = r[t] + g[t] (l[t] result[t+1] + (1-l[t]) V[t+1])."""
    discounts = gammas * lambda_

    def step(carry, xs):
        r, g, d, v = xs
        ret = r + d * carry + (g - d) * v
        return ret, ret

    last = rewards[-1] + gammas[-1] * bootstrap_values[-1]
    xs = (rewards[:-1], gammas[:-1], discounts[:-1], bootstrap_values[:-1])
    _, rest = jax.lax.scan(step, last, xs, reverse=True)
    return jnp.concatenate([rest, last[None]], axis=0)


def generalized_lambda_returns(
    rewards: jnp.ndarray,  # [T, B]
    gammas: Scalar,
    bootstrap_values: jnp.ndarray,  # [T+1, B]
    lambda_: Scalar,
) -> jnp.ndarray:
    gammas = _as_tb(gammas, rewards)
    lambda_ = _as_tb(lambda_, rewards)
    return multistep_forward_view(rewards, gammas, bootstrap_values[1:], lambda_)


def td_lambda_loss(
    values: jnp.ndarray,  # [T+1, B]
    rewards: jnp.ndarray,  # [T, B]
    gamma: Scalar = 1.0,
    lambda_: Scalar = 0.8,
    mask: jnp.ndarray = None,  # [T, B] optional
) -> jnp.ndarray:
    """0.5 * (G_lambda - V)^2 with targets stop-gradiented, mean-reduced."""
    returns = jax.lax.stop_gradient(
        generalized_lambda_returns(rewards, gamma, values, lambda_)
    )
    loss = 0.5 * jnp.square(returns - values[:-1])
    if mask is not None:
        loss = loss * mask
    return loss.mean()


def upgo_returns(rewards: jnp.ndarray, bootstrap_values: jnp.ndarray) -> jnp.ndarray:
    """UPGO targets: lambda-returns where the trace continues (lambda=1)
    iff r_{t+1} + V_{t+2} >= V_{t+1} (shifted as in the reference)."""
    lambdas = (rewards + bootstrap_values[1:]) >= bootstrap_values[:-1]
    lambdas = jnp.concatenate([lambdas[1:], jnp.ones_like(lambdas[-1:])], axis=0)
    return generalized_lambda_returns(rewards, 1.0, bootstrap_values, lambdas.astype(rewards.dtype))


def vtrace_advantages(
    clipped_rhos: jnp.ndarray,  # [T, B]
    clipped_cs: jnp.ndarray,  # [T, B]
    rewards: jnp.ndarray,  # [T, B]
    bootstrap_values: jnp.ndarray,  # [T+1, B]
    clipped_pg_rhos: jnp.ndarray = None,
    gammas: Scalar = 1.0,
    lambda_: Scalar = 0.8,
) -> jnp.ndarray:
    """IMPALA V-trace advantages (Espeholt et al. 2018), lambda-weighted as
    in the reference: vs_t = V_t + delta_t + g l c_t (vs_{t+1} - V_{t+1});
    adv = pg_rho * (r + g vs_{t+1} - V_t)."""
    gammas = _as_tb(gammas, rewards)
    lambda_ = _as_tb(lambda_, rewards)
    deltas = clipped_rhos * (rewards + gammas * bootstrap_values[1:] - bootstrap_values[:-1])

    def step(carry, xs):
        delta, g, lam, c = xs
        # carry = vs_{t+1} - V_{t+1}
        diff = delta + g * lam * c * carry
        return diff, diff

    _, diffs = jax.lax.scan(
        step,
        jnp.zeros_like(bootstrap_values[-1]),
        (deltas, gammas, lambda_, clipped_cs),
        reverse=True,
    )
    vs = bootstrap_values[:-1] + diffs  # [T, B]
    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_values[-1:]], axis=0)
    if clipped_pg_rhos is None:
        clipped_pg_rhos = clipped_rhos
    return clipped_pg_rhos * (rewards + gammas * vs_tp1 - bootstrap_values[:-1])
