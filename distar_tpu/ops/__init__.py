from .blocks import (
    GLU,
    FCBlock,
    Conv2DBlock,
    ResBlock,
    ResFCBlock,
    GatedResBlock,
    FiLM,
    binary_encode,
    one_hot,
    sequence_mask,
)
from .transformer import Attention, Transformer, AttentionPool
from .lstm import LayerNormLSTMCell, PlainLSTMCell, StackedLSTM
from .scatter import scatter_connection
from .rl import (
    generalized_lambda_returns,
    vtrace_advantages,
    upgo_returns,
    td_lambda_loss,
)

__all__ = [
    "GLU",
    "FCBlock",
    "Conv2DBlock",
    "ResBlock",
    "ResFCBlock",
    "GatedResBlock",
    "FiLM",
    "binary_encode",
    "one_hot",
    "sequence_mask",
    "Attention",
    "Transformer",
    "AttentionPool",
    "LayerNormLSTMCell",
    "PlainLSTMCell",
    "StackedLSTM",
    "scatter_connection",
    "generalized_lambda_returns",
    "vtrace_advantages",
    "upgo_returns",
    "td_lambda_loss",
]
