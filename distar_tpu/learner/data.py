"""Learner-facing trajectory batch schema + fake dataloaders.

The RL trajectory batch layout (time-major, mirroring the reference's
(T+1, B)-flattened learner batches, rl_dataloader.py:45-76):

  obs fields                [T+1, B, ...]   (T+1: the last step bootstraps)
  hidden_state              tuple of (h, c), each [B, H]
  action_info[head]         [T, B(, S)]
  selected_units_num        [T, B]
  behaviour_logp[head]      [T, B(, S)]
  teacher_logit[head]       [T, B, ...]
  reward[field]             [T, B]
  step                      [T, B]
  done                      [T, B]  (1 from the terminal step onward)
  mask                      dict (see losses.rl_loss)
  model_last_iter           [B]

Fake dataloaders (role of the reference FakeDataloader, rl_learner.py:196)
produce schema-complete random batches for learner job_type 'train_test' and
for bench.py.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from ..lib import actions as A
from ..lib import features as F

RL_REWARD_FIELDS = ("winloss", "build_order", "built_unit", "effect", "upgrade", "battle")


def fake_rl_batch(
    batch_size: int,
    unroll_len: int,
    rng: Optional[np.random.Generator] = None,
    hidden_size: int = 384,
    hidden_layers: int = 3,
    use_value_feature: bool = False,
) -> Dict:
    """Schema-complete random RL trajectory batch (numpy, host-side)."""
    rng = rng or np.random.default_rng(0)
    T, B, S, N = unroll_len, batch_size, F.MAX_SELECTED_UNITS_NUM, F.MAX_ENTITY_NUM

    obs = F.batch_tree(
        [
            F.batch_tree([F.fake_step_data(train=False, rng=rng) for _ in range(B)])
            for _ in range(T + 1)
        ],
        stack=np.stack,
    )
    entity_num = np.maximum(obs["entity_num"], 8)

    sun = rng.integers(2, 7, (T, B))

    def head_actions():
        # selected-units rows must be DISTINCT units followed by the end
        # token (== entity_num) — the pointer mask forbids re-selecting a
        # unit, so repeated fake labels would sit on -1e9 logits
        su = np.zeros((T, B, S), np.int64)
        for t in range(T):
            for b in range(B):
                n = sun[t, b]
                su[t, b, : n - 1] = rng.permutation(8)[: n - 1]
                su[t, b, n - 1] = entity_num[t, b]  # end flag
        return {
            "action_type": rng.integers(0, A.NUM_ACTIONS, (T, B)),
            "delay": rng.integers(0, F.MAX_DELAY + 1, (T, B)),
            "queued": rng.integers(0, 2, (T, B)),
            "selected_units": su,
            "target_unit": rng.integers(0, 8, (T, B)),
            "target_location": rng.integers(0, F.SPATIAL_SIZE[0] * F.SPATIAL_SIZE[1], (T, B)),
        }

    logit_shapes = dict(F.LOGIT_SHAPES)
    teacher_logit = {
        k: rng.standard_normal((T, B) + shape).astype(np.float32)
        for k, shape in logit_shapes.items()
    }
    actions = head_actions()
    # a real teacher runs the same teacher-forced masking as the learner, so
    # its mass sits on positions the target keeps finite. Random fake logits
    # on target-masked slots make the KL explode (p_teacher * 1e9), so make
    # the fake teacher near-deterministic on the label positions.
    su_onehot = np.eye(N + 1, dtype=np.float32)[actions["selected_units"]]
    teacher_logit["selected_units"] = (40.0 * su_onehot - 20.0).astype(np.float32)
    tu_onehot = np.eye(N, dtype=np.float32)[actions["target_unit"]]
    teacher_logit["target_unit"] = (40.0 * tu_onehot - 20.0).astype(np.float32)
    behaviour_logp = {
        k: -np.abs(rng.standard_normal((T, B) + ((S,) if k == "selected_units" else ()))).astype(
            np.float32
        )
        for k in F.ACTION_HEADS
    }
    masks = {
        "actions_mask": {k: np.ones((T, B), np.float32) for k in F.ACTION_HEADS},
        "selected_units_mask": (np.arange(S)[None, None] < sun[..., None]),
        "build_order_mask": np.ones((T, B), np.float32),
        "built_unit_mask": np.ones((T, B), np.float32),
        "effect_mask": np.ones((T, B), np.float32),
        "cum_action_mask": np.ones((T, B), np.float32),
        "step_mask": np.ones((T, B), np.float32),
    }
    rewards = {
        f: rng.integers(-1, 2, (T, B)).astype(np.float32) for f in RL_REWARD_FIELDS
    }
    extra = {}
    if use_value_feature:
        extra["value_feature"] = F.batch_tree(
            [
                F.batch_tree([F.fake_value_feature(rng) for _ in range(B)])
                for _ in range(T + 1)
            ]
        )
    return {
        **extra,
        "spatial_info": obs["spatial_info"],
        "entity_info": obs["entity_info"],
        "scalar_info": obs["scalar_info"],
        "entity_num": entity_num,
        "hidden_state": tuple(
            (
                np.zeros((B, hidden_size), np.float32),
                np.zeros((B, hidden_size), np.float32),
            )
            for _ in range(hidden_layers)
        ),
        "action_info": actions,
        "selected_units_num": sun,
        "behaviour_logp": behaviour_logp,
        "teacher_logit": teacher_logit,
        "reward": rewards,
        "step": rng.integers(0, 10000, (T, B)).astype(np.float32),
        "done": np.zeros((T, B), np.float32),
        "mask": masks,
        "model_last_iter": np.zeros((B,), np.float32),
    }


def fake_sl_batch(
    batch_size: int,
    unroll_len: int,
    rng: Optional[np.random.Generator] = None,
) -> Dict:
    """SL batch: [B*T] flat obs + labels, batch-major trajectories."""
    rng = rng or np.random.default_rng(0)
    B, T, S = batch_size, unroll_len, F.MAX_SELECTED_UNITS_NUM
    n = B * T
    obs = F.batch_tree([F.fake_step_data(train=False, rng=rng) for _ in range(n)])
    entity_num = np.maximum(obs["entity_num"], 8)
    sun = rng.integers(2, 7, (n,))
    su = np.zeros((n, S), np.int64)
    for i in range(n):
        # distinct units then the end token (see fake_rl_batch)
        su[i, : sun[i] - 1] = rng.permutation(8)[: sun[i] - 1]
        su[i, sun[i] - 1] = entity_num[i]
    return {
        "spatial_info": obs["spatial_info"],
        "entity_info": obs["entity_info"],
        "scalar_info": obs["scalar_info"],
        "entity_num": entity_num,
        "action_info": {
            "action_type": rng.integers(0, A.NUM_ACTIONS, (n,)),
            "delay": rng.integers(0, F.MAX_DELAY + 1, (n,)),
            "queued": rng.integers(0, 2, (n,)),
            "selected_units": su,
            "target_unit": rng.integers(0, 8, (n,)),
            "target_location": rng.integers(0, F.SPATIAL_SIZE[0] * F.SPATIAL_SIZE[1], (n,)),
        },
        "action_mask": {k: np.ones((n,), np.float32) for k in F.ACTION_HEADS},
        "selected_units_num": sun,
        "new_episodes": np.zeros((B,), bool),
        "traj_lens": np.full((B,), T, np.int64),
    }


class FakeRLDataloader:
    """Infinite iterator of fake RL batches (learner job_type 'train_test')."""

    def __init__(self, batch_size: int, unroll_len: int, hidden_size: int = 384,
                 hidden_layers: int = 3, seed: int = 0, use_value_feature: bool = False):
        self._rng = np.random.default_rng(seed)
        self._kwargs = dict(
            batch_size=batch_size, unroll_len=unroll_len,
            hidden_size=hidden_size, hidden_layers=hidden_layers,
            use_value_feature=use_value_feature,
        )

    def __iter__(self) -> Iterator[Dict]:
        return self

    def __next__(self) -> Dict:
        return fake_rl_batch(rng=self._rng, **self._kwargs)


class FakeSLDataloader:
    def __init__(self, batch_size: int, unroll_len: int, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._batch_size = batch_size
        self._unroll_len = unroll_len

    def __iter__(self) -> Iterator[Dict]:
        return self

    def __next__(self) -> Dict:
        return fake_sl_batch(self._batch_size, self._unroll_len, rng=self._rng)


def cap_entities(batch: Dict, n: int) -> Dict:
    """Slice a (host) SL batch's entity axis to the first ``n`` slots.

    The pad-to-bucket throughput lever (SURVEY §7 hard part 5): the entity
    transformer and pointer decode are O(N^2)/O(N) in the PADDED entity
    count, and real decoded frames rarely exceed ~300 entities, so training
    at the reference's MAX_ENTITY_NUM=512 pad wastes most of the set-
    attention FLOPs. Every model shape derives from the input, and padded
    rows are masked out of every reduction, so for samples with
    entity_num <= n the sliced batch is numerically EXACT (tested).

    Samples above the cap follow the reference's own cap semantics
    (truncate at the ceiling), with affected heads masked out of the loss
    rather than mislabeled: entity_num clamps to n, end-token labels remap
    to the new end slot, and any selected_units/target_unit label that
    referenced a dropped entity zeroes that head's action_mask for the
    step (no loss contribution).
    """
    entity_info = {k: v[:, :n] for k, v in batch["entity_info"].items()}
    old_num = np.asarray(batch["entity_num"])
    new_num = np.minimum(old_num, n)

    ai = dict(batch["action_info"])
    am = dict(batch["action_mask"])
    su = np.asarray(ai["selected_units"])
    was_end = su == old_num[..., None]
    dropped = (su >= new_num[..., None]) & ~was_end
    ai["selected_units"] = np.where(was_end | dropped, new_num[..., None], su)
    su_mask = np.asarray(am["selected_units"])
    am["selected_units"] = np.where(dropped.any(-1), 0.0, su_mask).astype(su_mask.dtype)

    tu = np.asarray(ai["target_unit"])
    tu_bad = tu >= new_num
    ai["target_unit"] = np.where(tu_bad, 0, tu)
    tu_mask = np.asarray(am["target_unit"])
    am["target_unit"] = np.where(tu_bad, 0.0, tu_mask).astype(tu_mask.dtype)

    return dict(
        batch,
        entity_info=entity_info,
        entity_num=new_num,
        action_info=ai,
        action_mask=am,
    )


def cap_entities_rl(batch: Dict, n: int) -> Dict:
    """RL-layout counterpart of :func:`cap_entities` (time-major batches:
    obs [T+1, B, N, ...], actions/teacher logits [T, B, ...]).

    Same contract: numerically exact for samples with entity_num <= n —
    model shapes derive from inputs, masked rows vanish from every
    reduction, and within the cap a teacher's sliced logit tail carries
    ~zero mass. ABOVE the cap the teacher's sliced distribution would
    renormalize over a truncated candidate set (a biased KL), so overflow
    steps zero their selected_units/target_unit action masks entirely —
    no loss contribution rather than a distorted one.
    """
    entity_info = {k: v[:, :, :n] for k, v in batch["entity_info"].items()}
    old_num = np.asarray(batch["entity_num"])          # [T+1, B]
    new_num = np.minimum(old_num, n)
    act_num_old = old_num[:-1]                         # the acted steps
    act_num_new = new_num[:-1]
    overflow = act_num_old > n                         # [T, B]

    ai = dict(batch["action_info"])
    su = np.asarray(ai["selected_units"])              # [T, B, S]
    was_end = su == act_num_old[..., None]
    # clamp EVERY out-of-range lane (post-end sampled junk included: left
    # >= n it would gather out of bounds in the sliced pointer decode)
    oob = (su >= act_num_new[..., None]) & ~was_end
    ai["selected_units"] = np.where(was_end | oob, act_num_new[..., None], su)
    tu = np.asarray(ai["target_unit"])                 # [T, B]
    tu_bad = tu >= act_num_new
    ai["target_unit"] = np.where(tu_bad, 0, tu)

    mask = {k: (dict(v) if isinstance(v, dict) else v) for k, v in batch["mask"].items()}
    am = mask["actions_mask"]
    su_mask = np.asarray(am["selected_units"])
    am["selected_units"] = np.where(overflow, 0.0, su_mask).astype(su_mask.dtype)
    tu_mask = np.asarray(am["target_unit"])
    am["target_unit"] = np.where(overflow | tu_bad, 0.0, tu_mask).astype(tu_mask.dtype)

    teacher = dict(batch["teacher_logit"])
    teacher["selected_units"] = np.asarray(teacher["selected_units"])[..., : n + 1]
    teacher["target_unit"] = np.asarray(teacher["target_unit"])[..., :n]

    out = dict(
        batch,
        entity_info=entity_info,
        entity_num=new_num,
        action_info=ai,
        mask=mask,
        teacher_logit=teacher,
    )
    if "successive_logit" in batch:  # DAPO carries the same logit layout
        succ = dict(batch["successive_logit"])
        succ["selected_units"] = np.asarray(succ["selected_units"])[..., : n + 1]
        succ["target_unit"] = np.asarray(succ["target_unit"])[..., :n]
        out["successive_logit"] = succ
    return out
