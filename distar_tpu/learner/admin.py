"""Live learner admin API.

Role parity with the reference RL learner's runtime HTTP endpoints
(reference: distar/agent/default/rl_learner.py:203-287 — re-read user config,
reset value networks, rebuild comm, all applied between train iterations):
the server only sets flags/payloads; the learner applies them at the next
iteration boundary (jit caches and donated buffers make mid-step mutation
unsafe, so the boundary is the only correct application point).

POST /learner/<update_config|reset_value|save_ckpt|status|profile>

``POST /profile?steps=N`` is the exception to fire-and-forget: it arms a
bounded ``jax.profiler`` capture that the run loop starts/stops at
iteration boundaries, BLOCKS until the trace is analyzed
(obs/traceview.py), and returns the ranked per-bucket report — the
`opsctl profile` surface.
"""
from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class LearnerAdminServer:
    def __init__(self, learner, host: str = "127.0.0.1", port: int = 0):
        self.learner = learner

        def routes(name: str, body: dict, query: dict):
            if name == "update_config":
                if not hasattr(learner, "request_update_config"):
                    return None  # SL learners don't serve config patches
                learner.request_update_config(body.get("config", {}))
                return "queued"
            if name == "reset_value":
                if not hasattr(learner, "request_value_reset"):
                    return None
                learner.request_value_reset()
                return "queued"
            if name == "save_ckpt":
                # deferred like the rest: saving mid-iteration races the
                # donated train-step buffers
                learner.request_save()
                return "queued"
            if name == "status":
                return {
                    "last_iter": learner.last_iter.val,
                    "meters": {
                        k: m.avg for k, m in learner.variable_record.vars().items()
                    },
                    "perf": learner._perf.snapshot(),
                }
            if name == "profile":
                steps = int(query.get("steps", body.get("steps", 2)))
                timeout_s = float(
                    query.get("timeout_s", body.get("timeout_s", 600.0))
                )
                # blocks this request thread until the run loop captured the
                # trace and the analyzer ranked it
                return learner.request_profile(steps=steps, timeout_s=timeout_s)
            return None

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_POST(self):
                parsed = urllib.parse.urlsplit(self.path)
                name = parsed.path.strip("/").split("/")[-1]
                query = {
                    k: v[-1]
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    info = routes(name, body, query)
                    payload = (
                        {"code": 404, "info": f"no route {name}"}
                        if info is None
                        else {"code": 0, "info": info}
                    )
                except Exception as e:
                    payload = {"code": 1, "info": repr(e)}
                data = json.dumps(payload, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        # reap the serve loop before closing its socket under it: stop()
        # returning with the thread still running races server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._server.server_close()
