"""Live learner admin API.

Role parity with the reference RL learner's runtime HTTP endpoints
(reference: distar/agent/default/rl_learner.py:203-287 — re-read user config,
reset value networks, rebuild comm, all applied between train iterations):
the server only sets flags/payloads; the learner applies them at the next
iteration boundary (jit caches and donated buffers make mid-step mutation
unsafe, so the boundary is the only correct application point).

POST /learner/<update_config|reset_value|save_ckpt|status>
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class LearnerAdminServer:
    def __init__(self, learner, host: str = "127.0.0.1", port: int = 0):
        self.learner = learner

        def routes(name: str, body: dict):
            if name == "update_config":
                learner.request_update_config(body.get("config", {}))
                return "queued"
            if name == "reset_value":
                learner.request_value_reset()
                return "queued"
            if name == "save_ckpt":
                # deferred like the rest: saving mid-iteration races the
                # donated train-step buffers
                learner.request_save()
                return "queued"
            if name == "status":
                return {
                    "last_iter": learner.last_iter.val,
                    "meters": {
                        k: m.avg for k, m in learner.variable_record.vars().items()
                    },
                }
            return None

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_POST(self):
                name = self.path.strip("/").split("/")[-1]
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    info = routes(name, body)
                    payload = (
                        {"code": 404, "info": f"no route {name}"}
                        if info is None
                        else {"code": 0, "info": info}
                    )
                except Exception as e:
                    payload = {"code": 1, "info": repr(e)}
                data = json.dumps(payload, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
