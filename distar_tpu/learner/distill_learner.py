"""Actor-learner distillation: the student tier's learner role.

Trains the small student policy (``model.student_model_config``) on the RL
learner's OWN trajectory batches: the teacher logits already ride every
rollout flush (PR 8 ``want_teacher``), so distillation adds zero teacher
forwards to the hot path — the student consumes ``batch["teacher_logit"]``
exactly as the RL loss's KL term does, through the masked per-head KL in
:mod:`losses.distill_loss`.

Two contracts distinguish this learner from the RL one:

  * **Hidden state**: the batch's ``hidden_state`` carries the TEACHER's
    LSTM dims (the actor's carry). The student has its own, smaller carry,
    so every window trains from a zero initial state (the standard
    actor-learner-distillation treatment; the [T+1] window is its own
    burn-in).
  * **Checkpoint role**: student checkpoints publish through
    ``CheckpointManager`` under the ``student`` role key (their own
    ``latest_student.json`` pointer + role-stamped generations), so a
    teacher's crash-resume can never pick a student generation and vice
    versa — even inside one shared experiment directory.

Live drift surfaces through ``distar_distill_*`` gauges (divergence total
and per head, student vs teacher generation, FLOPs-derived step-cost
ratio); the ``distill_divergence_runaway`` rule in the default rulebook
watches the KL gauge's trend.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..losses import DistillLossConfig, compute_distill_loss
from ..model import Model, student_model_config
from ..utils import deep_merge_dicts
from .base_learner import DEFAULT_LEARNER_CONFIG, BaseLearner
from .data import FakeRLDataloader, cap_entities_rl

DISTILL_LEARNER_DEFAULTS = deep_merge_dicts(
    DEFAULT_LEARNER_CONFIG,
    {
        "learner": {
            "player_id": "MP0",
            "batch_size": 4,
            "unroll_len": 16,
            # distillation is supervised: a larger LR than the RL
            # learner's 1e-5 converges the student orders faster
            "learning_rate": 1e-3,
            "betas": [0.9, 0.99],
            "eps": 1e-5,
            "grad_clip": {"type": "norm", "threshold": 10.0},
            "max_entities": None,
            # cascades into DistillLossConfig (temperature, head weights)
            "distill": {},
            # when set (e.g. from the DISTILL_r* bench artifact), the
            # learner publishes its FLOPs-derived step-cost ratio gauge
            "teacher_flops_per_step": 0,
        },
        "model": {},
    },
)


def make_distill_loss_config(learner_cfg) -> DistillLossConfig:
    overrides = dict(learner_cfg.get("distill", {}) or {})
    return DistillLossConfig(**overrides)


def _flatten_time(tree):
    return jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), tree)


def make_distill_train_step(model: Model, loss_cfg: DistillLossConfig,
                            optimizer, batch_size: int, unroll_len: int,
                            hidden_size: int, hidden_layers: int,
                            dynamics=None):
    """(params, opt_state, batch) -> (params, opt_state, info). The student's
    zero initial carry is built inside the jitted step (its dims are the
    STUDENT's, not the batch's — see the module docstring)."""

    def loss_fn(params, batch):
        hidden = tuple(
            (jnp.zeros((batch_size, hidden_size), jnp.float32),
             jnp.zeros((batch_size, hidden_size), jnp.float32))
            for _ in range(hidden_layers)
        )
        out = model.apply(
            params,
            _flatten_time(batch["spatial_info"]),
            _flatten_time(batch["entity_info"]),
            _flatten_time(batch["scalar_info"]),
            batch["entity_num"].reshape(-1),
            hidden, batch["action_info"], batch["selected_units_num"],
            batch_size, unroll_len,
            method=model.policy_forward,
        )
        inputs = {
            "student_logit": out["target_logit"],
            "teacher_logit": batch["teacher_logit"],
            "mask": batch["mask"],
        }
        return compute_distill_loss(inputs, loss_cfg)

    def train_step(params, opt_state, batch):
        (_, info), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
        info["grad_norm"] = optax.global_norm(grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if dynamics is not None:
            from ..obs import dynamics_tree

            info.update(dynamics_tree(
                params, grads, updates=updates, batch=batch, spec=dynamics
            ))
        params = optax.apply_updates(params, updates)
        return params, opt_state, info

    return train_step


class DistillLearner(BaseLearner):
    """Student-tier learner: masked-KL distillation on RL batches."""

    _CAP_FN = staticmethod(cap_entities_rl)
    CKPT_ROLE = "student"

    def __init__(self, cfg: Optional[dict] = None, mesh=None):
        # ``mesh`` accepted for launcher symmetry with RLLearner; the
        # student is small enough that the step runs un-sharded
        cfg = deep_merge_dicts(DISTILL_LEARNER_DEFAULTS, cfg or {})
        self.model_cfg = student_model_config(cfg.get("model", {}))
        self.model_cfg.use_value_network = False
        self.model = Model(self.model_cfg)
        self.loss_cfg = make_distill_loss_config(cfg.learner)
        super().__init__(cfg)

    # ------------------------------------------------------------ state init
    def _setup_dataloader(self) -> None:
        lc = self.cfg.learner if hasattr(self, "cfg") else DISTILL_LEARNER_DEFAULTS.learner
        self._dataloader = iter(
            FakeRLDataloader(
                batch_size=lc.batch_size,
                unroll_len=lc.unroll_len,
                hidden_size=self.model_cfg.encoder.core_lstm.hidden_size,
                hidden_layers=self.model_cfg.encoder.core_lstm.num_layers,
            )
        )

    def set_dataloader(self, it) -> None:
        self._dataloader = iter(it)

    def _student_zero_hidden(self, batch_size: int):
        core = self.model_cfg.encoder.core_lstm
        return tuple(
            (np.zeros((batch_size, core.hidden_size), np.float32),
             np.zeros((batch_size, core.hidden_size), np.float32))
            for _ in range(core.num_layers)
        )

    def _setup_state(self) -> None:
        lc = self.cfg.learner
        B, T = lc.batch_size, lc.unroll_len
        data = dict(next(self._dataloader))
        data.pop("model_last_iter", None)  # host-side; _train pops it too
        batch = jax.tree.map(jnp.asarray, self._strip_batch(self._cap(data)))
        self.optimizer = self._build_optimizer()

        def init_fn(rng, spatial, entity, scalar, entity_num, hidden, action, sun):
            return self.model.init(
                rng, spatial, entity, scalar, entity_num, hidden, action, sun,
                B, T, method=self.model.policy_forward,
            )

        init_args = (
            *(_flatten_time(batch[k]) for k in ("spatial_info", "entity_info", "scalar_info")),
            batch["entity_num"].reshape(-1),
            jax.tree.map(jnp.asarray, self._student_zero_hidden(B)),
            batch["action_info"],
            batch["selected_units_num"],
        )
        params = jax.jit(init_fn)(jax.random.PRNGKey(self.init_prng_seed), *init_args)
        self._state = {
            "params": params,
            "opt_state": jax.jit(self.optimizer.init)(params),
        }
        core = self.model_cfg.encoder.core_lstm
        step_fn = make_distill_train_step(
            self.model, self.loss_cfg, self.optimizer, B, T,
            hidden_size=core.hidden_size, hidden_layers=core.num_layers,
            dynamics=self._dynamics_spec(),
        )
        self._train_step = jax.jit(step_fn, donate_argnums=(0, 1))
        reg = self.metrics
        self._g_kl = reg.gauge(
            "distar_distill_kl",
            "student-vs-teacher masked KL (unweighted sum over heads) at the "
            "last distill step — the distill_divergence_runaway input",
        )
        self._g_head_kl = {}
        self._g_student_gen = reg.gauge(
            "distar_distill_student_generation",
            "learner iteration of the newest published student checkpoint",
        )
        self._g_teacher_gen = reg.gauge(
            "distar_distill_teacher_generation",
            "newest teacher iteration observed in the training batches",
        )
        teacher_flops = float(lc.get("teacher_flops_per_step") or 0)
        if teacher_flops > 0:
            from ..obs.perf import flops_of_lowered

            lowered = self._train_step.lower(
                self._state["params"], self._state["opt_state"], batch)
            student_flops = flops_of_lowered(lowered)
            if student_flops:
                reg.gauge(
                    "distar_distill_step_cost_ratio",
                    "student/teacher per-step cost ratio (FLOPs-derived; "
                    "teacher side from learner.teacher_flops_per_step)",
                ).set(student_flops / teacher_flops)

    # ---------------------------------------------------------------- saving
    def checkpoint_path(self) -> str:
        import os

        return os.path.join(self.save_dir, "checkpoints",
                            f"student_iteration_{self.last_iter.val}.ckpt")

    def save(self, path: str, sync: bool = False) -> None:
        super().save(path, sync=sync)
        self._g_student_gen.set(float(self.last_iter.val))

    # -------------------------------------------------------------- training
    def _strip_batch(self, data: Dict) -> Dict:
        """Drop the RL-batch fields distillation does not consume: the
        TEACHER-shaped carry, rewards/values inputs, and host-side
        bookkeeping the caller pops separately."""
        data = dict(data)
        for k in ("hidden_state", "reward", "step", "done", "behaviour_logp",
                  "value_feature", "successive_logit"):
            data.pop(k, None)
        return data

    def _train(self, data) -> Dict[str, Any]:
        data = dict(data)
        data.pop("_on_device", None)
        model_last_iter = np.asarray(data.pop("model_last_iter", 0.0))
        data.pop("trace_span_ids", None)
        data.pop("trace_age_s", None)
        data = self._strip_batch(self._cap(data))
        batch = jax.tree.map(jnp.asarray, data)
        self._perf_note_step_args(
            self._train_step, self._state["params"], self._state["opt_state"], batch)
        params, opt_state, info = self._train_step(
            self._state["params"], self._state["opt_state"], batch)
        self._state = {"params": params, "opt_state": opt_state}
        log = {k: float(v) for k, v in jax.device_get(info).items()}
        self._g_kl.set(log["divergence"])
        for head in ("action_type", "delay", "queued", "selected_units",
                     "target_unit", "target_location"):
            g = self._g_head_kl.get(head)
            if g is None:
                g = self._g_head_kl[head] = self.metrics.gauge(
                    "distar_distill_head_kl",
                    "per-action-head masked KL vs the teacher", head=head)
            g.set(log[f"kl/{head}"])
        self._g_teacher_gen.set(float(np.max(model_last_iter)))
        if getattr(self, "_pending_save", False):
            self._pending_save = False
            self.save(self.checkpoint_path(), sync=True)
            self.logger.info(f"admin checkpoint saved: {self.checkpoint_path()}")
        return log
