"""SL replay actor: sharded replay decode feeding remote SL learners.

Role parity with the reference ReplayActor (reference: distar/ctools/worker/
actor/replay_actor.py:10-72): the replay list — a file of paths or a
directory — is expanded over shuffled epochs, sharded across cluster tasks
(SLURM_NTASKS × SLURM_PROCID env discovery, :41-45) and across local
workers; each worker decodes both players of each replay through the
two-pass ReplayDecoder and pushes the trajectory step-lists over the
Adapter data plane with backpressure (:31-33). The learner side pulls them
via RemoteSLDataloader.

Workers are threads, not processes: the decode hot path lives inside the
SC2 binary (a separate process per worker already) and the websocket client
releases the GIL on IO, so threads shard as well as the reference's forks
while keeping the Adapter in-process.
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Callable, List, Optional, Sequence, Union

from .sl_dataloader import SLDataloader


def expand_replay_list(
    source: Union[str, Sequence[str]],
    epochs: int = 1,
    seed: int = 233,
    ntasks: Optional[int] = None,
    proc_id: Optional[int] = None,
) -> List[str]:
    """Resolve, epoch-expand, and shard the replay list (reference
    replay_actor.py:44-61)."""
    if isinstance(source, str):
        if os.path.isfile(source):
            with open(source) as f:
                paths = [l.strip() for l in f if l.strip()]
        elif os.path.isdir(source):
            paths = [
                os.path.join(source, p)
                for p in sorted(os.listdir(source))
                if p.lower().endswith(".sc2replay")
            ]
        else:
            raise FileNotFoundError(source)
    else:
        paths = list(source)
    rng = random.Random(seed)
    expanded: List[str] = []
    for _ in range(max(epochs, 1)):
        shuffled = list(paths)
        rng.shuffle(shuffled)
        expanded += shuffled
    ntasks = ntasks if ntasks is not None else int(os.environ.get("SLURM_NTASKS", 1))
    proc_id = proc_id if proc_id is not None else int(os.environ.get("SLURM_PROCID", 0))
    ntasks = max(ntasks, 1)
    per = len(expanded) // ntasks
    if per == 0:
        return expanded if proc_id == 0 else []
    # the last task takes the division remainder — no replay is dropped
    end = (proc_id + 1) * per if proc_id < ntasks - 1 else len(expanded)
    return expanded[proc_id * per: end]


class ReplayActor:
    """Decode a replay shard with N workers, pushing trajectories to the
    data plane."""

    def __init__(
        self,
        replays: Union[str, Sequence[str]],
        adapter_factory: Callable[[], object],
        decoder_factory: Callable[[], object],
        num_workers: int = 1,
        epochs: int = 1,
        token: str = "sltraj",
        seed: int = 233,
        ntasks: Optional[int] = None,
        proc_id: Optional[int] = None,
    ):
        self._paths = expand_replay_list(replays, epochs, seed, ntasks, proc_id)
        self._adapter_factory = adapter_factory
        self._decoder_factory = decoder_factory
        self._num_workers = max(num_workers, 1)
        self._token = token
        self.pushed = 0
        self.failed = 0  # decode attempts that raised
        self.empty = 0   # decodes that produced no steps (e.g. race-filtered)
        self._lock = threading.Lock()
        per = len(self._paths) // self._num_workers
        self._shards = [
            self._paths[i * per: (i + 1) * per] if i < self._num_workers - 1
            else self._paths[i * per:]
            for i in range(self._num_workers)
        ]
        logging.info(
            "replay actor: %d replays, %d workers (%d per worker)",
            len(self._paths), self._num_workers, per,
        )

    def _decode_loop(self, shard: List[str]) -> None:
        adapter = self._adapter_factory()
        decoder = self._decoder_factory()
        try:
            for i, path in enumerate(shard):
                # both players of every replay (reference decode_loop
                # alternates player_idx 0/1)
                for player_idx in (0, 1):
                    try:
                        steps = decoder.run(path, player_idx)
                    except Exception:
                        logging.exception("decode failed: %s p%d", path, player_idx)
                        with self._lock:
                            self.failed += 1
                        continue
                    if not steps:
                        with self._lock:
                            self.empty += 1
                        continue
                    adapter.push(self._token, steps)
                    with self._lock:
                        self.pushed += 1
                if (i + 1) % 100 == 0:
                    logging.info("replay worker: %d/%d decoded", i + 1, len(shard))
        finally:
            if hasattr(decoder, "close"):
                decoder.close()

    def run(self) -> None:
        threads = [
            threading.Thread(target=self._decode_loop, args=(shard,), daemon=True)
            for shard in self._shards if shard
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        logging.info("replay actor job done (%d trajectories pushed)", self.pushed)


class RemoteSLDataloader(SLDataloader):
    """SLDataloader whose trajectories arrive over the Adapter data plane
    instead of a local disk dataset (the reference's remote SLDataloader
    mode, sl_dataloader.py remote branch)."""

    def __init__(
        self,
        adapter,
        batch_size: int,
        unroll_len: int,
        token: str = "sltraj",
        pull_timeout: float = 300.0,
    ):
        self.adapter = adapter
        self.batch_size = batch_size
        self.unroll_len = unroll_len
        self._token = token
        self._pull_timeout = pull_timeout
        self._slots = [[] for _ in range(batch_size)]
        self._fresh = [True] * batch_size

    def _refill(self, slot: int) -> None:
        deadline = time.time() + self._pull_timeout
        while True:
            traj = self.adapter.pull(
                self._token, block=True,
                timeout=max(min(self._pull_timeout, deadline - time.time()), 0.1),
            )
            if traj:
                break
            if time.time() >= deadline:
                raise TimeoutError(
                    f"no SL trajectory arrived on '{self._token}' within "
                    f"{self._pull_timeout}s"
                )
        self._slots[slot] = list(traj)
        self._fresh[slot] = True
