"""Host->device prefetch: overlap batch assembly + transfer with compute.

Role of the reference's async CUDA-copy process (reference: distar/agent/
default/rl_training/rl_dataloader.py:113-127 — a worker that copies the next
collated batch to the GPU while the current step trains). TPU-first shape:
``jax.device_put`` is asynchronous (it returns device buffers immediately and
streams over PCIe/ICI in the background), so a single thread that PULLS the
next host batch and ISSUES its placement is enough — the XLA runtime
overlaps the copy with the in-flight train step, and the bounded queue
double-buffers without pinning more than ``depth`` batches in HBM.

NOTE: the learner run loop now wraps dataloaders in
``parallel.feeder.ShardFeeder`` — the mesh-aware superset of this class
(same double-buffer semantics + per-host global-array assembly +
``distar_feeder_*`` instrumentation). ``DevicePrefetcher`` stays as the
dependency-free primitive for host-only pipelines.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator


class DevicePrefetcher:
    """Wraps a host-batch iterator; yields device-placed batches."""

    def __init__(self, dataloader, place_fn: Callable, depth: int = 2):
        assert depth >= 1
        self._it = iter(dataloader)
        self._place = place_fn
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._err = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="device-prefetch"
        )
        self._thread.start()

    def _loop(self) -> None:
        try:
            for batch in self._it:
                if self._stop.is_set():
                    return
                placed = self._place(batch)
                while not self._stop.is_set():
                    try:
                        self._q.put(placed, timeout=0.2)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on the consumer side
            self._err = e
        finally:
            self._q.put(_SENTINEL)

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        item = self._q.get()
        if item is _SENTINEL:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()
        # drain so the producer unblocks
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        # reap the producer: close() returning while it may still be mid
        # place_fn races learner teardown. Best-effort with a SHORT bound:
        # a live producer exits within ms of the stop flag, while one
        # blocked in next(self._it) can't be interrupted at all — waiting
        # longer buys nothing (it dies with the process as before)
        self._thread.join(timeout=0.5)


_SENTINEL = object()
