"""RL learner: pjit data-parallel V-trace/UPGO training on a device mesh.

Role of the reference RLLearner (reference: distar/agent/default/
rl_learner.py:23-160): model with value towers, Adam(0, 0.99) + grad clip,
staleness tracking, value-pretrain gate, weight publication hooks.

TPU-first train step: ONE jitted function carries forward + loss + backward
+ optimizer update; inputs arrive sharded [*, B/dp, ...] over the mesh's dp
axis, params/opt-state replicated, and XLA inserts the gradient psum over
ICI (replacing DistModule.sync_gradients' per-param NCCL loop,
dist_helper.py:421-431). Params and opt state are donated, so the update is
in-place in HBM.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..losses import ReinforcementLossConfig, compute_rl_loss
from ..model import Model, default_model_config
from ..parallel import MeshSpec, make_mesh
from ..parallel.grad_clip import leaf_norms
from ..utils import Config, deep_merge_dicts
from .base_learner import DEFAULT_LEARNER_CONFIG, BaseLearner
from .data import FakeRLDataloader, cap_entities_rl

RL_LEARNER_DEFAULTS = deep_merge_dicts(
    DEFAULT_LEARNER_CONFIG,
    {
        "learner": {
            "player_id": "MP0",
            "batch_size": 4,
            "unroll_len": 16,
            "learning_rate": 1e-5,
            "betas": [0.0, 0.99],
            "eps": 1e-5,
            "grad_clip": {"type": "norm", "threshold": 10.0},
            "value_pretrain_iters": -1,
            "use_dapo": False,
            # per-parameter grad/param-norm logging (reference save_grad)
            "save_grad": False,
            # pad-to-bucket entity cap (throughput; see data.cap_entities_rl)
            "max_entities": None,
        },
        "model": {},
    },
)


def make_loss_config(learner_cfg) -> ReinforcementLossConfig:
    """Loss weights are yaml-surface config like the reference's
    default_reinforcement_loss.yaml: any ReinforcementLossConfig field can
    be overridden via ``learner.loss`` (e.g. kl_weight, entropy_weight,
    pg_weights). List-valued fields arriving from yaml are normalised to
    the dataclass's tuple-of-tuples form."""
    overrides = {
        k: (tuple(tuple(x) for x in v) if isinstance(v, (list, tuple)) else v)
        for k, v in dict(learner_cfg.get("loss", {}) or {}).items()
    }
    # an explicit loss.use_dapo wins over the top-level learner.use_dapo
    overrides.setdefault("use_dapo", learner_cfg.use_dapo)
    return ReinforcementLossConfig(**overrides)


def _flatten_time(tree):
    return jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), tree)


def make_rl_train_step(model: Model, loss_cfg: ReinforcementLossConfig, optimizer,
                       batch_size: int, unroll_len: int, save_grad: bool = False,
                       dynamics=None):
    """Build the pure train-step fn (params, opt_state, batch) -> updated.

    With ``save_grad`` the info dict additionally carries per-parameter
    grad/param L2 norms (reference save_grad TB dumps,
    rl_learner.py:35-47,118-130) — static at trace time, so the toggle
    never mixes compiled variants. ``dynamics`` (an obs.DynamicsSpec, or
    None) statically folds the training-dynamics diagnostics tree into the
    info dict — computed against pre-step params and post-clip updates, so
    the update-to-weight ratios and non-finite censuses describe exactly
    this step."""

    def loss_fn(params, batch, only_update_value):
        obs = {
            "spatial_info": _flatten_time(batch["spatial_info"]),
            "entity_info": _flatten_time(batch["entity_info"]),
            "scalar_info": _flatten_time(batch["scalar_info"]),
            "entity_num": batch["entity_num"].reshape(-1),
        }
        value_feature = batch.get("value_feature")
        if value_feature is not None:
            value_feature = _flatten_time(value_feature)
        out = model.apply(
            params,
            obs["spatial_info"], obs["entity_info"], obs["scalar_info"], obs["entity_num"],
            batch["hidden_state"], batch["action_info"], batch["selected_units_num"],
            batch_size, unroll_len,
            value_feature=value_feature,
            method=model.rl_forward,
        )
        inputs = {
            "target_logit": out["target_logit"],
            "value": out["value"],
            "action_log_prob": batch["behaviour_logp"],
            "teacher_logit": batch["teacher_logit"],
            "action": batch["action_info"],
            "reward": batch["reward"],
            "step": batch["step"],
            "done": batch.get("done"),
            "mask": batch["mask"],
            "entity_num": batch["entity_num"].reshape(-1, batch_size)[:unroll_len],
            "selected_units_num": batch["selected_units_num"],
        }
        if loss_cfg.use_dapo:
            inputs["successive_logit"] = batch["successive_logit"]
        import dataclasses

        cfg = dataclasses.replace(loss_cfg, only_update_value=False)
        total, info = compute_rl_loss(inputs, cfg)
        total_value_only = info["td/total"]
        total = jnp.where(only_update_value, total_value_only, total)
        return total, info

    def train_step(params, opt_state, batch, only_update_value):
        (_, info), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, only_update_value
        )
        info["grad_norm"] = optax.global_norm(grads)
        if save_grad:
            info.update(leaf_norms(grads, "grad_norm"))
            info.update(leaf_norms(params, "param_norm"))
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if dynamics is not None:
            from ..obs import dynamics_tree

            info.update(dynamics_tree(
                params, grads, updates=updates, batch=batch, spec=dynamics
            ))
        params = optax.apply_updates(params, updates)
        return params, opt_state, info

    return train_step


class RLLearner(BaseLearner):
    """Data-parallel league-RL learner."""

    _CAP_FN = staticmethod(cap_entities_rl)

    def __init__(self, cfg: Optional[dict] = None, mesh=None):
        cfg = deep_merge_dicts(RL_LEARNER_DEFAULTS, cfg or {})
        self.mesh = mesh if mesh is not None else make_mesh(MeshSpec())
        self.model_cfg = deep_merge_dicts(default_model_config(), cfg.get("model", {}))
        self.model_cfg.use_value_network = True
        self.model = Model(self.model_cfg)
        self.loss_cfg = make_loss_config(cfg.learner)
        self._remaining_value_pretrain = cfg.learner.get("value_pretrain_iters", -1)
        super().__init__(cfg)

    # ------------------------------------------------------------ state init
    def _setup_dataloader(self) -> None:
        lc = self.cfg.learner if hasattr(self, "cfg") else RL_LEARNER_DEFAULTS.learner
        self._dataloader = iter(
            FakeRLDataloader(
                batch_size=lc.batch_size,
                unroll_len=lc.unroll_len,
                hidden_size=self.model_cfg.encoder.core_lstm.hidden_size,
                hidden_layers=self.model_cfg.encoder.core_lstm.num_layers,
                use_value_feature=self.model_cfg.use_value_feature,
            )
        )

    def set_dataloader(self, it) -> None:
        self._dataloader = iter(it)

    def _setup_state(self) -> None:
        lc = self.cfg.learner
        B, T = lc.batch_size, lc.unroll_len
        from ..parallel.mesh import shrink_dp

        new_mesh = shrink_dp(self.mesh, B)
        if new_mesh is not self.mesh:
            self.logger.info(
                f"batch {B} not divisible by mesh dp={self.mesh.shape['dp']}; "
                f"shrunk to dp={new_mesh.shape['dp']} (other axes preserved)"
            )
            self.mesh = new_mesh
        from ..parallel.mesh import set_context_mesh

        set_context_mesh(self.mesh)  # ring attention resolves sp at trace time
        batch = self._cap(next(self._dataloader))
        self.optimizer = self._build_optimizer()
        # jit the init: eager init dispatches thousands of tiny ops, which is
        # painfully slow on a remote/tunneled device
        def init_fn(rng, spatial, entity, scalar, entity_num, hidden, action, sun, vf):
            return self.model.init(
                rng, spatial, entity, scalar, entity_num, hidden, action, sun, B, T,
                value_feature=vf,
                method=self.model.rl_forward,
            )

        batch = jax.tree.map(jnp.asarray, batch)
        vf = batch.get("value_feature")
        init_args = (
            *(_flatten_time(batch[k]) for k in ("spatial_info", "entity_info", "scalar_info")),
            batch["entity_num"].reshape(-1),
            batch["hidden_state"],
            batch["action_info"],
            batch["selected_units_num"],
            _flatten_time(vf) if vf is not None else None,
        )
        jitted_init = jax.jit(init_fn)
        # for admin-triggered value resets: keep only shape/dtype specs (not
        # the batch itself — that would pin it in HBM for the whole run)
        init_specs = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), init_args
        )

        def _reinit(rng):
            dummy = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), init_specs)
            return jitted_init(rng, *dummy)

        self._init_params = _reinit
        params = jitted_init(jax.random.PRNGKey(self.init_prng_seed), *init_args)
        del init_args
        from ..parallel.mesh import batch_sharding, fsdp_param_sharding, time_batch_sharding

        repl = NamedSharding(self.mesh, P())
        # params sharded over the fsdp axis (replicated when fsdp == 1);
        # Adam moments follow the same shardings, so optimizer state is
        # 1/fsdp-sized per device
        param_sh = fsdp_param_sharding(self.mesh, params)
        params = jax.device_put(params, param_sh)
        opt_sh = fsdp_param_sharding(self.mesh, jax.eval_shape(self.optimizer.init, params))
        self._state = {
            "params": params,
            "opt_state": jax.jit(self.optimizer.init, out_shardings=opt_sh)(params),
        }
        step_fn = make_rl_train_step(
            self.model, self.loss_cfg, self.optimizer, B, T,
            save_grad=self.cfg.learner.get("save_grad", False),
            dynamics=self._dynamics_spec(),
        )
        from ..parallel.mesh import dp_axes

        self._shardings = dict(
            repl=repl,
            param=param_sh,
            opt=opt_sh,  # restore() re-places host state onto param/opt
            batch=time_batch_sharding(self.mesh),  # [T(,+1), B, ...]
            batch_nosp=NamedSharding(self.mesh, P(None, dp_axes(self.mesh))),
            # batch_size validates here: typed MeshConfigError at compile
            # time, not an opaque XLA sharding error on the first step
            flat=batch_sharding(self.mesh, batch_size=B),  # [B]-leading leaves
        )
        self._train_step = jax.jit(
            step_fn,
            donate_argnums=(0, 1),
            # pin params/opt outputs to their fsdp shardings; the loss-info
            # scalars replicate
            out_shardings=(param_sh, opt_sh, repl),
        )
        # analytic per-step collective estimate from the live mesh + params
        # (obs/perf.py) — the sanity bar a trace's collective bucket is read
        # against
        self._perf.set_collectives(self.mesh, self._state["params"])

    def shard_batch(self, batch):
        """Place a host batch onto the mesh: B sharded over dp everywhere
        (axis 1 for time-major leaves, axis 0 for hidden_state). On an sp>1
        mesh the time axis additionally shards over sp — per leaf, because
        the batch mixes T+1 (obs/values) and T (reward/mask) leading dims
        and only sp-divisible ones can shard.

        Placement goes through ``parallel.feeder.assemble_global``: on one
        host that is an async ``device_put``; on a pod every host
        contributes its own batch shard and jax assembles the global
        array (``make_array_from_process_local_data``)."""
        from ..parallel.feeder import assemble_global

        hidden = batch.pop("hidden_state")
        sp = self.mesh.shape["sp"]
        dp_prod = self.mesh.shape["dp"] * self.mesh.shape["fsdp"]

        def put(x):
            x = jnp.asarray(x)
            if x.ndim >= 2:
                sh = self._shardings["batch"]
                if sp > 1 and x.shape[0] % sp:
                    sh = self._shardings["batch_nosp"]
            elif x.ndim == 1 and x.shape[0] % dp_prod == 0:
                sh = self._shardings["flat"]
            else:
                sh = self._shardings["repl"]
            return assemble_global(x, sh)

        out = jax.tree.map(put, batch)
        out["hidden_state"] = jax.tree.map(
            lambda x: assemble_global(jnp.asarray(x), self._shardings["flat"]), hidden
        )
        batch["hidden_state"] = hidden
        return out

    def _place_batch(self, batch):
        """Prefetch placement: everything device-put ahead of time except the
        host-side staleness/trace fields."""
        batch = self._cap(dict(batch))
        model_last_iter = np.asarray(batch.pop("model_last_iter"))
        span_ids = batch.pop("trace_span_ids", None)
        trace_age = batch.pop("trace_age_s", None)
        out = self.shard_batch(batch)
        out["model_last_iter"] = model_last_iter
        if span_ids is not None:
            out["trace_span_ids"] = span_ids
            out["trace_age_s"] = trace_age
        out["_on_device"] = True
        return out

    # ----------------------------------------------------------------- comm
    def attach_comm(self, adapter, player_id: str, league=None, send_model_freq: int = 4,
                    send_train_info_freq: int = 4, model_accept_count: int = 8) -> None:
        """Wire weight publication + league train-info (roles of the
        reference LearnerComm: _send_model_loop learner_comm.py:83-99 and
        send_train_info :101-137 incl. the remote-triggered checkpoint
        reset)."""
        from .hooks import LambdaHook

        lc = self.cfg.learner
        frames_per_iter = lc.batch_size * lc.unroll_len

        self._pending_reset_flag = False

        def send_model(learner):
            params_host = jax.tree.map(np.asarray, learner.state["params"])
            adapter.push(
                f"{player_id}model",
                {
                    "params": params_host,
                    "iter": learner.last_iter.val,
                    # actors restart episodes when a league reset swapped the
                    # checkpoint (reference actor_comm.py:191-196)
                    "reset_flag": learner._pending_reset_flag,
                },
                accept_count=model_accept_count,
                timeout_ms=120_000,
            )
            learner._pending_reset_flag = False

        def send_train_info(learner):
            if league is None:
                return
            reply = league.learner_send_train_info(
                player_id, train_steps=frames_per_iter * send_train_info_freq
            )
            reset_path = (reply or {}).get("reset_checkpoint_path")
            if reset_path:
                import os

                if os.path.exists(reset_path):
                    learner.restore(reset_path)
                    # only a real checkpoint swap makes actors restart
                    learner._pending_reset_flag = True
                    learner.logger.info(f"league reset: restored {reset_path}")
                else:
                    learner.logger.info(
                        f"league reset requested ({reset_path}); checkpoint absent, keeping weights"
                    )

        self.hooks.add(LambdaHook("send_model", "after_iter", send_model, freq=send_model_freq))
        self.hooks.add(LambdaHook("send_model_init", "before_run", send_model))
        self.hooks.add(
            LambdaHook("send_train_info", "after_iter", send_train_info, freq=send_train_info_freq)
        )

    # ----------------------------------------------------------------- admin
    # (start_admin / request_save / request_profile live on BaseLearner; the
    # RL learner adds the config-patch and value-reset surfaces)
    def request_update_config(self, cfg_patch: dict) -> None:
        self._pending_config_patch = cfg_patch

    def request_value_reset(self) -> None:
        self._pending_value_reset = True

    def _apply_admin_requests(self) -> None:
        patch = getattr(self, "_pending_config_patch", None)
        if patch:
            self._pending_config_patch = None
            self.cfg = deep_merge_dicts(self.cfg, patch)
            lc = self.cfg.learner
            # hyperparameter changes rebuild the optax chain; opt state resets
            # (the reference rebuilds the optimizer on update_config too)
            self.optimizer = self._build_optimizer()
            from ..parallel.mesh import fsdp_param_sharding

            opt_sh = fsdp_param_sharding(
                self.mesh, jax.eval_shape(self.optimizer.init, self._state["params"])
            )
            self._shardings["opt"] = opt_sh
            self._state["opt_state"] = jax.jit(self.optimizer.init, out_shardings=opt_sh)(
                self._state["params"]
            )
            self._train_step = jax.jit(
                make_rl_train_step(
                    self.model, self.loss_cfg, self.optimizer,
                    lc.batch_size, lc.unroll_len,
                    save_grad=lc.get("save_grad", False),
                    dynamics=self._dynamics_spec(),
                ),
                donate_argnums=(0, 1),
                out_shardings=(self._shardings["param"], opt_sh, self._shardings["repl"]),
            )
            self.logger.info(f"applied config patch: {patch}")
        if getattr(self, "_pending_save", False):
            self._pending_save = False
            path = self.checkpoint_path()
            # an operator asked for this one: durable before we log "saved"
            self.save(path, sync=True)
            self.logger.info(f"admin checkpoint saved: {path}")
        if getattr(self, "_pending_value_reset", False):
            self._pending_value_reset = False
            # re-init ONLY the value towers (reference reset_value,
            # rl_learner.py:233-247)
            fresh = self._init_params(jax.random.PRNGKey(self.last_iter.val + 1))
            params = self._state["params"]
            new_params = {"params": dict(params["params"])}
            for k in params["params"]:
                if k.startswith("value_") or k == "value_encoder":
                    new_params["params"][k] = fresh["params"][k]
            self._state["params"] = jax.device_put(
                new_params, self._shardings["param"]
            )
            self.logger.info("value networks reset")

    # ------------------------------------------------------------- training
    def step_value_pretrain(self) -> bool:
        """Value-pretrain gate (reference rl_learner.py:160-180): during the
        first value_pretrain_iters only the critics train."""
        if self._remaining_value_pretrain > 0:
            self._remaining_value_pretrain -= 1
            return True
        return False

    def _dynamics_aux(self) -> Dict[str, Any]:
        """Pre-step extras for a black-box bundle: the value-pretrain gate
        the step is ABOUT to use (read before _train decrements it) — host
        scalars only, so before_step stays free on the healthy path."""
        return {"only_update_value": self._remaining_value_pretrain > 0}

    def _train(self, data) -> Dict[str, Any]:
        only_value = self.step_value_pretrain()
        data = dict(data)  # callers may reuse the batch dict
        on_device = data.pop("_on_device", False)
        model_last_iter = np.asarray(data.pop("model_last_iter"))
        staleness = self.last_iter.val - model_last_iter
        # pipeline-span fields minted in the actor (host-side: never sharded)
        span_ids = data.pop("trace_span_ids", None)
        trace_age = data.pop("trace_age_s", None)
        if not on_device:
            data = self.shard_batch(self._cap(data))
        self._perf_note_step_args(
            self._train_step,
            self._state["params"], self._state["opt_state"], data,
            jnp.asarray(only_value),
        )
        params, opt_state, info = self._train_step(
            self._state["params"], self._state["opt_state"], data,
            jnp.asarray(only_value),
        )
        self._state = {"params": params, "opt_state": opt_state}
        # one batched D2H transfer — per-scalar float() would round-trip
        # once per metric across the ~60-entry loss grid every iteration
        log = {k: float(v) for k, v in jax.device_get(info).items()}
        log["staleness/mean"] = float(staleness.mean())
        log["staleness/max"] = float(staleness.max())
        log["staleness/std"] = float(staleness.std())
        if trace_age is not None and len(trace_age):
            # wall-clock counterpart of iteration staleness: seconds from the
            # trajectory's birth in the actor to this train step (span ids in
            # trace_span_ids attribute outliers to specific trajectories)
            log["trace/age_s_mean"] = float(np.mean(trace_age))
            log["trace/age_s_max"] = float(np.max(trace_age))
            self._last_span_ids = list(span_ids or [])
        self._apply_admin_requests()
        return log
