"""RL dataloader: trajectory collation + adapter-fed batching.

Role parity with the reference RLDataLoader (reference: distar/agent/default/
rl_training/rl_dataloader.py:45-167): worker pull-loops fetch trajectories
over the Adapter, `collate_trajectories` assembles the time-major learner
batch. Divergence by design: the reference pads entities per-batch to the
max entity count (:206-245); here every trajectory already carries the fixed
MAX_ENTITY_NUM padding (XLA static shapes), so collation is pure stacking.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional

import numpy as np

from ..comm import Adapter
from ..lib import features as F
from ..obs import finish_trace, get_registry, is_trace


class CollationError(ValueError):
    """A batch of trajectories cannot be collated (ragged lengths / empty
    batch). Carries the offending per-trajectory lengths so the fault is
    attributable to a producer without re-deriving anything — and unlike a
    bare ``assert``, survives ``python -O``."""

    def __init__(self, message: str, lengths: List[int]):
        super().__init__(f"{message} (per-trajectory lengths: {lengths})")
        self.lengths = list(lengths)


def collate_trajectories(trajs: List[list]) -> Dict:
    """[B] trajectories (each T steps + 1 bootstrap step) -> learner batch.

    Output layout matches distar_tpu.learner.data (obs [T+1, B, ...],
    actions/logps/teacher/rewards [T, B, ...], hidden_state per layer [B, H]).
    Raises ``CollationError`` on an empty batch or ragged trajectory lengths.
    """
    lengths = [len(t) for t in trajs]
    if not trajs:
        raise CollationError("empty trajectory batch", lengths)
    T = lengths[0] - 1
    if T < 1:
        raise CollationError("trajectories need >= 1 step + bootstrap", lengths)
    if any(n != T + 1 for n in lengths):
        raise CollationError("trajectories must share T", lengths)
    steps = [t[:T] for t in trajs]

    def stack_obs(key):
        # [T+1, B, ...]: bootstrap step supplies index T
        return F.batch_tree(
            [
                F.batch_tree([traj[t][key] for traj in trajs])
                for t in range(T + 1)
            ]
        )

    def stack_tb(get):
        return F.batch_tree([F.batch_tree([get(traj[t]) for traj in trajs]) for t in range(T)])

    batch = {
        "spatial_info": stack_obs("spatial_info"),
        "entity_info": stack_obs("entity_info"),
        "scalar_info": stack_obs("scalar_info"),
        "entity_num": stack_obs("entity_num"),
        "hidden_state": tuple(
            (
                np.stack([np.asarray(traj[0]["hidden_state"][l][0]) for traj in trajs]),
                np.stack([np.asarray(traj[0]["hidden_state"][l][1]) for traj in trajs]),
            )
            for l in range(len(trajs[0][0]["hidden_state"]))
        ),
        "action_info": stack_tb(lambda s: s["action_info"]),
        "selected_units_num": stack_tb(lambda s: s["selected_units_num"]),
        "behaviour_logp": stack_tb(lambda s: s["behaviour_logp"]),
        "teacher_logit": stack_tb(lambda s: s["teacher_logit"]),
        "reward": stack_tb(lambda s: s["reward"]),
        "step": stack_tb(lambda s: s["step"]),
        "done": stack_tb(lambda s: s.get("done", 0.0)),
        "model_last_iter": np.asarray(
            [float(traj[0].get("model_last_iter", 0.0)) for traj in trajs], np.float32
        ),
    }
    if "value_feature" in trajs[0][0]:
        batch["value_feature"] = stack_obs("value_feature")
    sun = batch["selected_units_num"].astype(np.int64)
    masks = stack_tb(lambda s: s["mask"])
    masks["selected_units_mask"] = (
        np.arange(F.MAX_SELECTED_UNITS_NUM)[None, None, :] < sun[..., None]
    )
    batch["mask"] = masks
    return batch


class RLDataLoader:
    """Pulls trajectories for a player token over the Adapter and yields
    collated [T, B] batches."""

    def __init__(
        self,
        adapter: Adapter,
        player_id: str,
        batch_size: int,
        cache_size: int = 64,
        token_suffix: str = "traj",
    ):
        self._adapter = adapter
        self._token = f"{player_id}{token_suffix}"
        self._batch_size = batch_size
        self._cache_size = cache_size
        # the pull loop notifies this condition on every append, so __next__
        # sleeps in cond.wait instead of a 5 ms busy-poll (the timeout is a
        # liveness backstop, not the wake mechanism)
        self._cond = threading.Condition()
        # keep_trace: the loop leaves spans open so THIS consumer records the
        # terminal hop (cache entries are (traj, trace_ctx) tuples)
        self._cache = adapter.start_pull_loop(
            self._token, maxlen=cache_size, keep_trace=True, condition=self._cond
        )
        reg = get_registry()
        self._m_batches = reg.counter(
            "distar_dataloader_batches_total", "collated batches yielded", token=self._token
        )
        self._m_occupancy = reg.gauge(
            "distar_dataloader_occupancy", "pull-cache fill fraction", token=self._token
        )
        self._m_wait = reg.histogram(
            "distar_dataloader_wait_s",
            "wall-clock the learner starved waiting for trajectories, per batch",
            token=self._token,
        )

    @property
    def token(self) -> str:
        """The adapter token this loader consumes (telemetry/broker depth)."""
        return self._token

    def buffered(self) -> int:
        """Trajectories currently banked in the pull cache."""
        return len(self._cache)

    def occupancy(self) -> float:
        """Buffered-trajectory share of the pull cache (0..1): ~0 means the
        learner is actor-starved, ~1 means the actors outrun the learner
        (the saturation axis of the reference's staleness regime,
        rl_learner.py:90-101)."""
        return self.buffered() / max(self._cache_size, 1)

    def __iter__(self) -> Iterator[Dict]:
        return self

    def __next__(self) -> Dict:
        trajs: List[list] = []
        traces: List[Optional[dict]] = []
        waited_s = 0.0
        while len(trajs) < self._batch_size:
            if self._cache:
                traj, ctx = self._cache.popleft()
                trajs.append(traj)
                traces.append(ctx)
            else:
                # starvation: block on the pull loop's condition instead of
                # busy-polling; the timeout only bounds a missed notify
                t0 = time.monotonic()
                with self._cond:
                    self._cond.wait_for(lambda: bool(self._cache), timeout=0.5)
                waited_s += time.monotonic() - t0
        self._m_wait.observe(waited_s)
        # close out the actor-minted pipeline spans: the batch reaching the
        # learner is the terminal hop, and its age (actor env-step ->
        # learner consume) is the wall-clock half of staleness. Span ids and
        # ages ride the batch as host-side fields for the learner's log.
        span_ids, ages = [], []
        for traj, ctx in zip(trajs, traces):
            if isinstance(traj[0], dict):
                ctx = traj[0].pop("trace", ctx)  # same object when both exist
            if is_trace(ctx):
                ages.append(finish_trace(ctx, hop="learner_collate"))
                span_ids.append(ctx["span_id"])
        batch = collate_trajectories(trajs)
        if span_ids:
            batch["trace_span_ids"] = span_ids
            batch["trace_age_s"] = np.asarray(ages, np.float32)
        self._m_batches.inc()
        self._m_occupancy.set(self.occupancy())
        return batch


class ReplayDataLoader:
    """Store-backed sampling mode: batches come from a replay-store table
    (``replay.SampleClient``) instead of the point-to-point pull cache, then
    flow through the SAME ``collate_trajectories`` — the learner cannot tell
    which data plane fed it.

    What changes operationally: trajectories may be sampled more than once
    (the table's samples-per-insert ratio governs reuse), the last batch's
    per-item store metadata (seq/priority/sample_count/staleness) is kept on
    ``last_sample_info`` for priority updates and logging, and starvation
    blocks server-side in the store's rate limiter (the client retries
    rate-limit timeouts under its policy). Staleness/reuse histograms are
    recorded store-side (``distar_replay_sampled_*``).

    The client can be a single-store ``SampleClient``, the zero-copy
    ``LocalReplayClient`` (colocated fast path), or a
    ``ShardedSampleClient`` fanning in across a shard fleet — the loader is
    agnostic: all three speak the same ``sample``/``update_priorities``
    surface, and for the sharded one the per-item ``shard`` field on
    ``last_sample_info`` routes priority updates back to exactly the shard
    each item came from."""

    def __init__(self, sample_client, player_id: str, batch_size: int,
                 table: Optional[str] = None, sample_timeout_s: float = 30.0):
        self._client = sample_client
        self._table = table or player_id
        self._batch_size = batch_size
        self._sample_timeout_s = sample_timeout_s
        self.last_sample_info: List[dict] = []
        reg = get_registry()
        self._m_batches = reg.counter(
            "distar_dataloader_batches_total", "collated batches yielded",
            token=self._table,
        )
        self._m_wait = reg.histogram(
            "distar_dataloader_wait_s",
            "wall-clock the learner starved waiting for trajectories, per batch",
            token=self._table,
        )

    @property
    def token(self) -> str:
        """The replay table this loader samples (telemetry parity with the
        adapter loader's token)."""
        return self._table

    def __iter__(self) -> "ReplayDataLoader":
        return self

    def __next__(self) -> Dict:
        t0 = time.monotonic()
        items, info = self._client.sample(
            self._table, batch_size=self._batch_size,
            timeout_s=self._sample_timeout_s,
        )
        self._m_wait.observe(time.monotonic() - t0)
        span_ids, ages = [], []
        for traj in items:
            if traj and isinstance(traj[0], dict):
                ctx = traj[0].pop("trace", None)
                if is_trace(ctx):
                    ages.append(finish_trace(ctx, hop="learner_collate"))
                    span_ids.append(ctx["span_id"])
        batch = collate_trajectories(items)
        if span_ids:
            batch["trace_span_ids"] = span_ids
            batch["trace_age_s"] = np.asarray(ages, np.float32)
        self.last_sample_info = info
        self._m_batches.inc()
        return batch

    def update_priorities(self, updates: Dict[int, float]) -> int:
        """PER hook: push learner-side priorities (e.g. TD error magnitudes)
        back to the table; unknown seqs (already evicted) are ignored. On a
        sharded fleet the last batch's sample info routes each update to
        the shard that served the item (seqs are per-shard counters, so a
        broadcast could re-prioritize a stranger's seq)."""
        if getattr(self._client, "sharded", False):
            return self._client.update_priorities(
                self._table, updates, info=self.last_sample_info)
        return self._client.update_priorities(self._table, updates)
