"""SL dataloader: disk-backed decoded-replay datasets -> learner batches.

Role parity with the reference SLDataloader (reference: distar/agent/default/
sl_training/sl_dataloader.py — replay-decode workers feeding trajectory
windows with carried LSTM state). Datasets are produced by the two-pass SC2
replay decoder (envs/replay_decoder.py) over the client layer (envs/sc2), or
by ``make_fake_dataset`` for tests; the step contract is frozen in
ReplayDataset.save.

Windowing matches the reference: each trajectory is cut into unroll_len
windows; a batch slot advances through one trajectory's windows before
loading the next (new_episodes flags the learner to zero that slot's hidden
state, sl_learner.py:31-35).
"""
from __future__ import annotations

import os
import pickle
import zlib
from typing import Dict, Iterator, List, Optional

import numpy as np

from ..lib import features as F
from .data import fake_sl_batch


class ReplayDataset:
    """A directory of decoded trajectories (zlib-pickled step lists)."""

    SUFFIX = ".traj.zpkl"

    def __init__(self, root: str):
        self.root = root
        self.paths = sorted(
            os.path.join(root, f) for f in os.listdir(root) if f.endswith(self.SUFFIX)
        )
        if not self.paths:
            raise FileNotFoundError(f"no {self.SUFFIX} files under {root}")

    @classmethod
    def save(cls, root: str, name: str, steps: List[dict]) -> str:
        """Persist one decoded trajectory. Each step dict carries:
        spatial_info / scalar_info / entity_info / entity_num (feature
        schema) + action_info + action_mask + selected_units_num."""
        os.makedirs(root, exist_ok=True)
        path = os.path.join(root, f"{name}{cls.SUFFIX}")
        with open(path, "wb") as f:
            f.write(zlib.compress(pickle.dumps(steps, protocol=5), level=1))
        return path

    def load(self, idx: int) -> List[dict]:
        with open(self.paths[idx % len(self.paths)], "rb") as f:
            return pickle.loads(zlib.decompress(f.read()))


class SLDataloader:
    """Batch slots stream trajectory windows from a ReplayDataset."""

    def __init__(self, dataset: ReplayDataset, batch_size: int, unroll_len: int, seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.unroll_len = unroll_len
        self._rng = np.random.default_rng(seed)
        self._slots: List[List[dict]] = [[] for _ in range(batch_size)]
        self._fresh = [True] * batch_size

    def _refill(self, slot: int) -> None:
        idx = int(self._rng.integers(0, len(self.dataset.paths)))
        traj = self.dataset.load(idx)
        if not traj:
            raise RuntimeError(f"empty trajectory: {self.dataset.paths[idx]}")
        self._slots[slot] = list(traj)
        self._fresh[slot] = True

    @staticmethod
    def _pad_window(window: List[dict], length: int) -> List[dict]:
        """Pad a short window (short replay, or a trajectory tail) to the
        fixed unroll by repeating the final step with every action_mask head
        zeroed — padded steps contribute to no SL loss term. The reference
        pads short trajectories rather than dropping them; skipping would
        discard short-game replays wholesale at unroll 32-64."""
        pad_src = dict(window[-1])
        pad_src["action_mask"] = {k: 0.0 for k in pad_src["action_mask"]}
        return window + [pad_src] * (length - len(window))

    def __iter__(self) -> Iterator[Dict]:
        return self

    def __next__(self) -> Dict:
        T = self.unroll_len
        windows, new_episodes = [], []
        for b in range(self.batch_size):
            if not self._slots[b]:
                self._refill(b)
            new_episodes.append(self._fresh[b])
            self._fresh[b] = False
            window = self._slots[b][:T]
            self._slots[b] = self._slots[b][T:]
            if len(window) < T:
                window = self._pad_window(window, T)
            windows.append(window)
        # flatten batch-major: [B*T] with per-slot contiguous windows
        flat = [step for win in windows for step in win]
        batch = {
            "spatial_info": F.batch_tree([s["spatial_info"] for s in flat]),
            "entity_info": F.batch_tree([s["entity_info"] for s in flat]),
            "scalar_info": F.batch_tree([s["scalar_info"] for s in flat]),
            "entity_num": np.stack([np.asarray(s["entity_num"]) for s in flat]),
            "action_info": F.batch_tree([s["action_info"] for s in flat]),
            "action_mask": F.batch_tree([s["action_mask"] for s in flat]),
            "selected_units_num": np.stack(
                [np.asarray(s["selected_units_num"]) for s in flat]
            ),
            "new_episodes": np.asarray(new_episodes, bool),
            "traj_lens": np.full((self.batch_size,), T, np.int64),
        }
        return batch


def make_fake_dataset(root: str, n_trajectories: int = 4, steps_per_traj: int = 16,
                      seed: int = 0) -> ReplayDataset:
    """Synthesise a decoded-replay dataset with the frozen contract (test
    double for the SC2 replay decoder)."""
    rng = np.random.default_rng(seed)
    for i in range(n_trajectories):
        batch = fake_sl_batch(1, steps_per_traj, rng=rng)
        steps = []
        for t in range(steps_per_traj):
            def at(tree):
                import jax

                return jax.tree.map(lambda x: np.asarray(x)[t], tree)

            steps.append(
                {
                    "spatial_info": at(batch["spatial_info"]),
                    "entity_info": at(batch["entity_info"]),
                    "scalar_info": at(batch["scalar_info"]),
                    "entity_num": np.asarray(batch["entity_num"][t]),
                    "action_info": at(batch["action_info"]),
                    "action_mask": at(batch["action_mask"]),
                    "selected_units_num": np.asarray(batch["selected_units_num"][t]),
                }
            )
        ReplayDataset.save(root, f"fake_{i:04d}", steps)
    return ReplayDataset(root)
