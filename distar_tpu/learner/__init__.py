from .base_learner import BaseLearner
from .data import FakeRLDataloader, FakeSLDataloader, fake_rl_batch, fake_sl_batch
from .distill_learner import DistillLearner, make_distill_train_step
from .hooks import Hook, HookRegistry, LambdaHook, default_hooks
from .rl_dataloader import CollationError, RLDataLoader, ReplayDataLoader, collate_trajectories
from .rl_learner import RLLearner, make_rl_train_step
from .sl_learner import SLLearner, make_sl_train_step

__all__ = [
    "CollationError",
    "RLDataLoader",
    "ReplayDataLoader",
    "collate_trajectories",
    "BaseLearner",
    "FakeRLDataloader",
    "FakeSLDataloader",
    "fake_rl_batch",
    "fake_sl_batch",
    "Hook",
    "HookRegistry",
    "LambdaHook",
    "default_hooks",
    "DistillLearner",
    "make_distill_train_step",
    "RLLearner",
    "make_rl_train_step",
    "SLLearner",
    "make_sl_train_step",
]
