"""Hook-driven learner extension points.

Role of the reference hook registry (reference: distar/ctools/worker/learner/
learner_hook.py): hooks attach at before_run / before_iter / after_iter /
after_run with priorities; the stock set covers checkpoint load/save, log
display, and (in distributed runs) cross-process log reduction — which on a
jax mesh is a no-op for gradients (XLA psum handles them) and a
process-level mean for logged scalars.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List

POSITIONS = ("before_run", "before_iter", "after_iter", "after_run")


class Hook:
    def __init__(self, name: str, position: str, priority: int = 50, freq: int = 1):
        assert position in POSITIONS
        self.name = name
        self.position = position
        self.priority = priority
        self.freq = freq

    def __call__(self, learner) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class LambdaHook(Hook):
    def __init__(self, name, position, fn: Callable, priority: int = 50, freq: int = 1):
        super().__init__(name, position, priority, freq)
        self._fn = fn

    def __call__(self, learner) -> None:
        self._fn(learner)


class HookRegistry:
    def __init__(self):
        self._hooks: Dict[str, List[Hook]] = {p: [] for p in POSITIONS}

    def add(self, hook: Hook) -> None:
        self._hooks[hook.position].append(hook)
        self._hooks[hook.position].sort(key=lambda h: h.priority)

    def call(self, position: str, learner) -> None:
        for hook in self._hooks[position]:
            if position in ("before_iter", "after_iter") and hook.freq > 1:
                if learner.last_iter.val % hook.freq != 0:
                    continue
            hook(learner)


class LoadCkptHook(Hook):
    """before_run: resume from cfg.learner.load_path when present."""

    def __init__(self, priority=20):
        super().__init__("load_ckpt", "before_run", priority)

    def __call__(self, learner) -> None:
        path = learner.cfg.learner.get("load_path", "")
        if path and os.path.exists(path):
            learner.restore(path)
            learner.logger.info(f"loaded checkpoint {path} @ iter {learner.last_iter.val}")


class SaveCkptHook(Hook):
    """after_iter (freq) + after_run: rank-0 writes the checkpoint."""

    def __init__(self, position="after_iter", priority=20, freq=1000):
        super().__init__("save_ckpt", position, priority, freq)

    def __call__(self, learner) -> None:
        if learner.rank != 0:
            return
        path = learner.checkpoint_path()
        learner.save(path)
        learner.logger.info(f"saved checkpoint {path}")


class LogShowHook(Hook):
    """after_iter (freq): render the meter table + scalar sink."""

    def __init__(self, priority=80, freq=100):
        super().__init__("log_show", "after_iter", priority, freq)

    def __call__(self, learner) -> None:
        if learner.rank != 0:
            return
        it = learner.last_iter.val
        record = learner.variable_record
        learner.logger.info(
            f"=== iter {it} ===\n{record.get_vars_text()}"
        )
        learner.scalar_sink.add_scalars(
            {k: m.avg for k, m in record.vars().items()}, global_step=it
        )


class LogReduceHook(Hook):
    """after_iter: fold the step's log dict into the meters."""

    def __init__(self, priority=10):
        super().__init__("log_reduce", "after_iter", priority)

    def __call__(self, learner) -> None:
        learner.variable_record.update_var(
            {k: float(v) for k, v in learner.log_buffer.items() if _is_scalar(v)}
        )
        learner.log_buffer.clear()


def _is_scalar(v) -> bool:
    try:
        float(v)
        return True
    except (TypeError, ValueError):
        return False


class MetricsExportHook(Hook):
    """after_iter (freq): dump the process metrics registry to the JSONL
    scalar stream under the learner's log dir (always-on export — the
    Prometheus /metrics route is pull-based and may have no scraper)."""

    def __init__(self, priority=85, freq=100):
        super().__init__("metrics_export", "after_iter", priority, freq)

    def __call__(self, learner) -> None:
        if learner.rank != 0:
            return
        exporter = getattr(learner, "_obs_exporter", None)
        if exporter is None:
            from ..obs import JsonlExporter

            exporter = JsonlExporter(
                os.path.join(learner.save_dir, "logs", "obs"),
                registry=getattr(learner, "metrics", None),
            )
            learner._obs_exporter = exporter
        exporter.export(step=learner.last_iter.val)


class ProfilerHook(Hook):
    """after_iter: freq-gated jax.profiler capture (like SaveCkptHook's
    cadence): every ``freq`` iterations start a device trace, stop it
    ``duration`` iterations later. Runs at every iteration (registry freq=1)
    because the stop edge falls between gate points; the start gate is
    internal. Rank-0 only; profiler failures are logged, never fatal."""

    # consecutive start failures before the hook retires itself: a logdir
    # on a read-only/full volume fails every gate — skip, don't spam/crash
    MAX_CONSECUTIVE_FAILURES = 3

    def __init__(self, logdir: str, freq: int = 1000, duration: int = 2,
                 priority: int = 90, profiler=None):
        super().__init__("profiler", "after_iter", priority, freq=1)
        assert freq > 0 and duration > 0
        self._freq = freq
        self._duration = duration
        self._stop_at = None
        self._consecutive_failures = 0
        self.disabled = False
        from ..obs import ProfilerSession

        self.session = ProfilerSession(logdir, profiler=profiler)

    def __call__(self, learner) -> None:
        if learner.rank != 0 or self.disabled:
            return
        it = learner.last_iter.val
        if self.session.active:
            if it >= self._stop_at and self.session.stop():
                learner.logger.info(
                    f"profiler trace captured -> "
                    f"{self.session.last_profile_path or self.session.logdir}"
                )
        elif it % self._freq == 0:
            if self.session.start():
                self._stop_at = it + self._duration
                self._consecutive_failures = 0
            else:
                self._consecutive_failures += 1
                if self._consecutive_failures >= self.MAX_CONSECUTIVE_FAILURES:
                    self.disabled = True
                    learner.logger.info(
                        f"profiler hook disabled after "
                        f"{self._consecutive_failures} consecutive start "
                        f"failures (logdir {self.session.logdir!r} unwritable?)"
                    )


def default_hooks(
    save_freq: int = 1000,
    log_freq: int = 100,
    profile_freq: int = 0,
    profile_duration: int = 2,
    profile_logdir: str = "",
) -> HookRegistry:
    reg = HookRegistry()
    reg.add(LoadCkptHook())
    reg.add(SaveCkptHook(freq=save_freq))
    reg.add(SaveCkptHook(position="after_run"))
    reg.add(LogReduceHook())
    reg.add(LogShowHook(freq=log_freq))
    reg.add(MetricsExportHook(freq=log_freq))
    if profile_freq > 0:
        reg.add(ProfilerHook(profile_logdir, freq=profile_freq, duration=profile_duration))
    return reg
