"""Abstract hook-driven train engine.

Role of the reference BaseLearner (reference: distar/ctools/worker/learner/
base_learner.py:24-272): owns the model/optimizer state, a dataloader
iterator, the hook registry, timing, logging, and the crash-safe run loop.
Subclasses implement `_setup_state()` (build params/opt) and `_train(data)`
(one jitted step). Distributed-ness is ambient: the train step is pjit'd
over a mesh, rank == jax.process_index().
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Iterator, Optional

import jax

from ..utils import Config, EasyTimer, build_logger, deep_merge_dicts
from ..utils.checkpoint import (
    AsyncCheckpointer,
    CountVar,
    auto_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from .hooks import HookRegistry, default_hooks

DEFAULT_LEARNER_CONFIG = Config(
    {
        "common": {"experiment_name": "default_experiment", "save_path": ""},
        "learner": {
            "job_type": "train",
            "learning_rate": 1e-5,
            "save_freq": 1000,
            "log_freq": 100,
            "load_path": "",
            "max_iterations": 10 ** 9,
            "grad_clip": {"type": "none", "threshold": 1.0},
        },
    }
)


class BaseLearner:
    def __init__(self, cfg: Optional[dict] = None):
        self.cfg = deep_merge_dicts(DEFAULT_LEARNER_CONFIG, cfg or {})
        self.rank = jax.process_index()
        self.world_size = jax.process_count()
        exp = self.cfg.common.experiment_name
        root = self.cfg.common.save_path or os.path.join(os.getcwd(), "experiments", exp)
        self.save_dir = root
        self.logger, self.scalar_sink, self.variable_record = build_logger(
            os.path.join(root, "logs"), f"{self.name}_rank{self.rank}", to_console=self.rank == 0
        )
        self.timer = EasyTimer()
        self.last_iter = CountVar(0)
        self._checkpointer = AsyncCheckpointer()
        self.log_buffer: Dict[str, Any] = {}
        self.hooks: HookRegistry = default_hooks(
            save_freq=self.cfg.learner.save_freq, log_freq=self.cfg.learner.log_freq
        )
        self._state = None  # TrainState pytree (params, opt_state, step)
        self._dataloader: Optional[Iterator] = None
        self._setup_dataloader()
        self._setup_state()

    # pad-to-bucket entity cap: subclasses set _CAP_FN to the layout-aware
    # slicer (data.cap_entities / cap_entities_rl); one choke point for all
    # of setup/prefetch/train host paths
    _CAP_FN = None

    def _cap(self, batch):
        n = self.cfg.learner.get("max_entities")
        fn = type(self)._CAP_FN
        if n and fn is not None:
            batch = fn(batch, int(n))
        return batch

    # -------------------------------------------------------------- plumbing
    @property
    def name(self) -> str:
        return type(self).__name__.lower()

    @property
    def state(self):
        return self._state

    def checkpoint_path(self) -> str:
        return os.path.join(self.save_dir, "checkpoints", f"iteration_{self.last_iter.val}.ckpt")

    def save(self, path: str, sync: bool = False) -> None:
        """Checkpoint the train state. By default (learner.async_save) the
        serialize+write overlaps training on a background thread; ``sync``
        forces a durable write before returning (crash/debug paths)."""
        meta = {"last_iter": self.last_iter.val}
        if sync or not self.cfg.learner.get("async_save", True):
            self._checkpointer.wait()  # never race an in-flight async write
            save_checkpoint(path, self._state, metadata=meta)
        else:
            self._checkpointer.save(path, self._state, metadata=meta)

    def restore(self, path: str) -> None:
        self._checkpointer.wait()  # the path may still be being written
        out = load_checkpoint(path, target=self._state)
        self._state = out["state"]
        self.last_iter.update(out["metadata"].get("last_iter", 0))

    # -------------------------------------------------------------- abstract
    def _setup_state(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _setup_dataloader(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _train(self, data) -> Dict[str, Any]:  # pragma: no cover - abstract
        """One optimisation step; returns the log dict."""
        raise NotImplementedError

    # -------------------------------------------------------------- prefetch
    def _place_batch(self, batch):  # overridden by learners that prefetch
        return batch

    def _maybe_enable_prefetch(self) -> None:
        """Wrap the dataloader in a device prefetcher (the reference's async
        copy process, rl_dataloader.py:113-127): the next batch lands in HBM
        while the current step trains. Disable with learner.prefetch_depth=0."""
        from .prefetch import DevicePrefetcher

        depth = int(self.cfg.learner.get("prefetch_depth", 2))
        if depth <= 0 or isinstance(self._dataloader, DevicePrefetcher):
            return
        if type(self)._place_batch is BaseLearner._place_batch:
            return  # learner doesn't define placement
        self._dataloader = DevicePrefetcher(self._dataloader, self._place_batch, depth)

    # ------------------------------------------------------------------ run
    def run(self, max_iterations: Optional[int] = None) -> None:
        max_iterations = max_iterations or self.cfg.learner.max_iterations
        self._maybe_enable_prefetch()

        # crash path writes synchronously: the process may be about to die
        @auto_checkpoint(lambda: self.save(self.checkpoint_path(), sync=True))
        def _run():
            self.hooks.call("before_run", self)
            while self.last_iter.val < max_iterations:
                with self.timer:
                    data = next(self._dataloader)
                self.log_buffer["data_time"] = self.timer.value
                self.hooks.call("before_iter", self)
                with self.timer:
                    log_vars = self._train(data)
                self.log_buffer["train_time"] = self.timer.value
                self.log_buffer.update(log_vars)
                self.last_iter.add(1)
                self.hooks.call("after_iter", self)
            self.hooks.call("after_run", self)

        _run()
        self._checkpointer.wait()  # drain the async writer before returning
