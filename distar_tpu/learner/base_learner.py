"""Abstract hook-driven train engine.

Role of the reference BaseLearner (reference: distar/ctools/worker/learner/
base_learner.py:24-272): owns the model/optimizer state, a dataloader
iterator, the hook registry, timing, logging, and the crash-safe run loop.
Subclasses implement `_setup_state()` (build params/opt) and `_train(data)`
(one jitted step). Distributed-ness is ambient: the train step is pjit'd
over a mesh, rank == jax.process_index().
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

import jax

from ..obs import (
    DYNAMICS_DEFAULTS,
    DynamicsMonitor,
    PerfMonitor,
    get_registry,
    record_step_phases,
    tree_spec,
)
from ..utils import Config, EasyTimer, build_logger, deep_merge_dicts
from ..utils.timing import sw as global_stopwatch
from ..utils.checkpoint import (
    AsyncCheckpointer,
    CheckpointCorruptError,
    CheckpointManager,
    CountVar,
    auto_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from .hooks import HookRegistry, default_hooks


def experiments_root() -> str:
    """Default experiment-dir root. ``DISTAR_EXPERIMENTS_ROOT`` overrides the
    cwd-relative ``experiments/`` — the test harness points it at a tmp dir
    so a stale ``experiments/`` from a previous run can never poison a later
    run's auto-resume (the PR 5 tier-1 failure mode)."""
    return os.environ.get("DISTAR_EXPERIMENTS_ROOT") or os.path.join(
        os.getcwd(), "experiments"
    )


DEFAULT_LEARNER_CONFIG = Config(
    {
        "common": {"experiment_name": "default_experiment", "save_path": ""},
        "learner": {
            "job_type": "train",
            "learning_rate": 1e-5,
            "save_freq": 1000,
            "log_freq": 100,
            "load_path": "",
            "max_iterations": 10 ** 9,
            "grad_clip": {"type": "none", "threshold": 1.0},
            # sharded checkpoints (parallel/ckpt.py): one CRC'd blob per
            # parameter shard + layout manifest, restorable onto ANY mesh.
            # Default off: monolithic .ckpt files stay the single-chip norm;
            # the --mesh CLI path and the executor turn it on.
            "sharded_ckpt": False,
            # device profiler hook: every profile.freq iters capture
            # profile.duration iters of jax.profiler trace (0 = disabled)
            "profile": {"freq": 0, "duration": 2, "logdir": ""},
            # live perf gauges (obs/perf.py): frames/s + step time always;
            # perf.aot extracts the step's flop count (MFU numerator) on a
            # background thread ("auto" = on unless DISTAR_PERF_AOT=0 — the
            # test harness opts out so dozens of small learners don't each
            # trace in the background); aot_compile additionally compiles
            # for the static memory_analysis footprint (cache-served when
            # the live step already compiled)
            "perf": {"aot": "auto", "aot_compile": False,
                     "mem_sample_every": 16},
            # training-dynamics observatory (obs/dynamics.py): the in-jit
            # diagnostics tree is computed every step; every_n gates gauge
            # EXPORT; anomalies (non-finite loss/grads, grad explosion,
            # entropy collapse) write debounced black-box bundles that
            # tools/stepreplay.py re-executes deterministically
            "dynamics": dict(DYNAMICS_DEFAULTS),
        },
    }
)


class BaseLearner:
    def __init__(self, cfg: Optional[dict] = None):
        self.cfg = deep_merge_dicts(DEFAULT_LEARNER_CONFIG, cfg or {})
        self.rank = jax.process_index()
        self.world_size = jax.process_count()
        exp = self.cfg.common.experiment_name
        root = self.cfg.common.save_path or os.path.join(experiments_root(), exp)
        self.save_dir = root
        self.logger, self.scalar_sink, self.variable_record = build_logger(
            os.path.join(root, "logs"), f"{self.name}_rank{self.rank}", to_console=self.rank == 0
        )
        self.timer = EasyTimer()
        self.last_iter = CountVar(0)
        self._checkpointer = AsyncCheckpointer()
        self._ckpt_manager = CheckpointManager(
            os.path.join(root, "checkpoints"),
            role=self.cfg.learner.get("ckpt_role", "") or self.CKPT_ROLE,
        )
        self.log_buffer: Dict[str, Any] = {}
        self.metrics = get_registry()
        prof = self.cfg.learner.get("profile", {})
        self.hooks: HookRegistry = default_hooks(
            save_freq=self.cfg.learner.save_freq,
            log_freq=self.cfg.learner.log_freq,
            profile_freq=int(prof.get("freq", 0)),
            profile_duration=int(prof.get("duration", 2)),
            profile_logdir=prof.get("logdir", "")
            or os.path.join(root, "profiles"),
        )
        pcfg = self.cfg.learner.get("perf", {})
        aot = pcfg.get("aot", "auto")
        if aot == "auto":
            aot = os.environ.get("DISTAR_PERF_AOT", "1").lower() not in ("0", "false")
        self._perf_aot = bool(aot)
        self._perf = PerfMonitor(
            token=self.name,
            registry=self.metrics,
            aot_compile=bool(pcfg.get("aot_compile", False)),
            mem_sample_every=int(pcfg.get("mem_sample_every", 16)),
        )
        self._dynamics = DynamicsMonitor(
            dict(self.cfg.learner.get("dynamics", {}) or {}),
            name=self.name,
            registry=self.metrics,
            blackbox_dir=os.path.join(root, "blackbox"),
        )
        self._profile_lock = threading.Lock()
        self._profile_req: Optional[Dict[str, Any]] = None
        self._state = None  # TrainState pytree (params, opt_state, step)
        self._dataloader: Optional[Iterator] = None
        self._setup_dataloader()
        self._setup_state()

    # pad-to-bucket entity cap: subclasses set _CAP_FN to the layout-aware
    # slicer (data.cap_entities / cap_entities_rl); one choke point for all
    # of setup/prefetch/train host paths
    _CAP_FN = None

    # params-init PRNG seed; recorded in black-box bundles so stepreplay can
    # rebuild bit-identical init state when a bundle omits the train state
    init_prng_seed = 0

    # checkpoint role key (utils.checkpoint.CheckpointManager): "" is the
    # teacher/default tier; the distillation student sets "student" so the
    # two tiers' generations can never cross on resume even when they share
    # an experiment directory
    CKPT_ROLE = ""

    def _cap(self, batch):
        n = self.cfg.learner.get("max_entities")
        fn = type(self)._CAP_FN
        if n and fn is not None:
            batch = fn(batch, int(n))
        return batch

    # -------------------------------------------------------------- plumbing
    @property
    def name(self) -> str:
        return type(self).__name__.lower()

    @property
    def state(self):
        return self._state

    def checkpoint_path(self) -> str:
        return os.path.join(self.save_dir, "checkpoints", f"iteration_{self.last_iter.val}.ckpt")

    @property
    def checkpoint_manager(self) -> CheckpointManager:
        return self._ckpt_manager

    def save(self, path: str, sync: bool = False) -> None:
        """Checkpoint the train state. By default (learner.async_save) the
        serialize+write overlaps training on a background thread; ``sync``
        forces a durable write before returning (crash/debug paths). Every
        save publishes the ``latest`` pointer only AFTER the bytes are
        durable, so crash-resume never points at a half-written file."""
        meta = {"last_iter": self.last_iter.val}
        step = self.last_iter.val
        snapshot_fn = write_fn = None
        if self.cfg.learner.get("sharded_ckpt", False):
            # distributed mode: per-shard D2H snapshot (sync — donated
            # buffers), per-shard CRC'd blob writes + layout manifest
            # (background); generation pointer discipline is identical
            from ..parallel import ckpt as dist_ckpt

            snapshot_fn = dist_ckpt.snapshot_sharded
            write_fn = dist_ckpt.write_sharded
        if sync or not self.cfg.learner.get("async_save", True):
            self._checkpointer.wait()  # never race an in-flight async write
            if write_fn is not None:
                write_fn(path, snapshot_fn(self._state), meta)
            else:
                save_checkpoint(path, self._state, metadata=meta)
            self._ckpt_manager.record(path, step=step)
        else:
            self._checkpointer.save(
                path, self._state, metadata=meta,
                on_complete=lambda p, s=step: self._ckpt_manager.record(p, step=s),
                snapshot_fn=snapshot_fn, write_fn=write_fn,
            )

    def restore(self, path: str) -> None:
        self._checkpointer.wait()  # the path may still be being written
        out = load_checkpoint(path, target=self._state)
        self._validate_restored(path, out["state"])
        layout = out.get("sharding_layout") or {}
        saved_mesh = layout.get("mesh_shape")
        cur_mesh = dict(self.mesh.shape) if getattr(self, "mesh", None) is not None else None
        if saved_mesh and cur_mesh and dict(saved_mesh) != cur_mesh:
            # resharding restore: the checkpoint's host-global arrays are
            # about to be re-pinned onto a DIFFERENT mesh layout
            self.metrics.counter(
                "distar_ckpt_reshards_total",
                "sharded checkpoints restored onto a different mesh shape",
            ).inc()
            self.logger.info(
                f"resharding restore: checkpoint mesh {saved_mesh} -> "
                f"live mesh {cur_mesh}"
            )
        self._state = self._place_state(out["state"])
        self.last_iter.update(out["metadata"].get("last_iter", 0))

    def _validate_restored(self, path: str, state) -> None:
        """Auto-resume guard: a checkpoint whose leaves don't match this
        learner's state shapes (different model config — typically a stale
        experiment dir from an unrelated run) must fail TYPED here, so
        ``resume_latest`` falls back/cold-starts instead of poisoning the
        run (and a direct ``restore`` fails before the train step does,
        with the offending leaves named)."""
        if self._state is None:
            return
        from ..utils.checkpoint import CheckpointMismatchError

        cur = jax.tree_util.tree_flatten_with_path(self._state)[0]
        new = jax.tree_util.tree_flatten_with_path(state)[0]
        cur_shapes = {
            jax.tree_util.keystr(p): tuple(getattr(x, "shape", ()) or ())
            for p, x in cur
        }
        mismatched = []
        for p, x in new:
            key = jax.tree_util.keystr(p)
            shape = tuple(getattr(x, "shape", ()) or ())
            if key in cur_shapes and cur_shapes[key] != shape:
                mismatched.append(f"{key}: ckpt {shape} != state {cur_shapes[key]}")
        if mismatched:
            raise CheckpointMismatchError(
                f"{path} does not fit this learner "
                f"({len(mismatched)} mismatched leaves, e.g. "
                f"{'; '.join(mismatched[:3])}); refusing to resume from it"
            )

    def resume_latest(self) -> Optional[str]:
        """Crash-resume: restore from the newest VALID generation behind the
        durable ``latest`` pointer. A corrupt/truncated newest checkpoint is
        detected (manifest CRC/size) and skipped in favour of the previous
        generation. Returns the restored path, or None when nothing usable
        exists (cold start)."""
        self._checkpointer.wait()
        for gen in self._ckpt_manager.generations():
            try:
                self.restore(gen["path"])
            except (CheckpointCorruptError, FileNotFoundError, OSError, ValueError):
                CheckpointManager._note_fallback(gen["path"])
                continue
            self.metrics.counter(
                "distar_resilience_resumes_total",
                "learner restarts resumed from the latest pointer",
            ).inc()
            from ..obs import get_flight_recorder

            get_flight_recorder().record(
                "learner_resume", path=gen["path"], step=gen.get("step", 0)
            )
            self.logger.info(f"resumed from {gen['path']} (iter {self.last_iter.val})")
            return gen["path"]
        return None

    def _place_state(self, state):
        """Re-place restored host leaves onto this instance's compiled
        shardings. The donated train step's executable pairs each donated
        input buffer with a same-shaped output; uncommitted host arrays let
        the compiler choose input shardings on the next call, and its choice
        can disagree with the donation aliasing (observed: a replicated
        f32[8] output aliased to an input placed as f32[1] dp-shards ->
        XlaRuntimeError INTERNAL). Committing the state per-instance, to the
        exact shardings its train step was compiled for, removes the
        compiler's freedom to disagree."""
        shardings = getattr(self, "_shardings", None)
        if not shardings:
            return state

        def put(tree, sh):
            # materialize through a jitted add-0 rather than device_put: the
            # outputs are freshly XLA-allocated buffers pinned to ``sh``.
            # device_put of host numpy can be ZERO-COPY on the CPU backend,
            # and the train step DONATES these buffers — XLA reusing/freeing
            # memory that numpy's allocator owns is heap corruption
            # (observed: "corrupted double-linked list" aborts on the second
            # post-restore iteration), the runtime sibling of the hazard
            # checkpoint._host_snapshot documents
            place = jax.jit(
                lambda t: jax.tree.map(
                    lambda a: a + 0 if hasattr(a, "shape") else a, t
                ),
                out_shardings=sh,
            )
            return place(tree)

        state = dict(state)
        for key, sh_key in (("params", "param"), ("opt_state", "opt")):
            if key in state and sh_key in shardings:
                state[key] = put(state[key], shardings[sh_key])
        return state

    # ------------------------------------------------------------- optimizer
    def _build_optimizer(self):
        """One optimizer-construction choke point for every learner (and the
        RL admin-rebuild path): learning_rate/betas/eps/weight_decay plus the
        ``grad_clip`` block routed through parallel/grad_clip.py — the norm
        path is exercised end-to-end by tests/test_learner.py."""
        from ..parallel import GradClipConfig, build_optimizer

        lc = self.cfg.learner
        return build_optimizer(
            learning_rate=lc.learning_rate,
            betas=tuple(lc.get("betas", (0.0, 0.99))),
            eps=lc.get("eps", 1e-5),
            weight_decay=float(lc.get("weight_decay", 0.0) or 0.0),
            clip=GradClipConfig(**lc.grad_clip),
        )

    def _dynamics_spec(self):
        """Static spec threaded into make_*_train_step; None compiles the
        step WITHOUT the diagnostics tree (the overhead A/B's off arm)."""
        lc = self.cfg.learner
        return tree_spec(lc.get("dynamics"), lc.get("grad_clip"))

    # -------------------------------------------------------------- abstract
    def _setup_state(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _setup_dataloader(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _train(self, data) -> Dict[str, Any]:  # pragma: no cover - abstract
        """One optimisation step; returns the log dict."""
        raise NotImplementedError

    # -------------------------------------------------------------- prefetch
    def _place_batch(self, batch):  # overridden by learners that prefetch
        return batch

    def _maybe_enable_prefetch(self) -> None:
        """Wrap the dataloader in the sharded batch feeder (the reference's
        async copy process, rl_dataloader.py:113-127, generalised to a mesh):
        the next batch is collated on the host and placed — sharded over the
        live mesh — while the current step trains. Disable with
        learner.prefetch_depth=0."""
        from ..parallel.feeder import ShardFeeder
        from .prefetch import DevicePrefetcher

        depth = int(self.cfg.learner.get("prefetch_depth", 2))
        if depth <= 0 or isinstance(self._dataloader, (ShardFeeder, DevicePrefetcher)):
            return
        if type(self)._place_batch is BaseLearner._place_batch:
            return  # learner doesn't define placement
        self._dataloader = ShardFeeder(
            self._dataloader, self._place_batch, depth=depth, token=self.name
        )

    # ----------------------------------------------------------------- perf
    def _perf_note_step_args(self, jitted, *args) -> None:
        """Subclass ``_train`` calls this with the jitted step + its live
        call args on every iteration; the monitor snapshots shape specs once
        and extracts the flop count (MFU numerator) in the background."""
        if self._perf_aot:
            self._perf.note_step_args(jitted, *args)

    # ---------------------------------------------------------------- admin
    def start_admin(self, port: int = 0):
        """Serve the live admin API (status / save_ckpt / profile, plus
        update_config / reset_value on learners that implement them);
        requests apply at iteration boundaries."""
        from .admin import LearnerAdminServer

        self._admin = LearnerAdminServer(self, port=port)
        self._admin.start()
        self.logger.info(f"admin API on {self._admin.host}:{self._admin.port}")
        return self._admin

    def request_save(self) -> None:
        self._pending_save = True

    def request_stop(self) -> None:
        """Cooperative run-loop exit at the next iteration boundary (admin /
        test harness surface): after_run hooks (final checkpoint) still
        run. The next ``run()`` call starts fresh."""
        self._stop_requested = True

    # -------------------------------------------------------------- profile
    def request_profile(self, steps: int = 2, timeout_s: float = 600.0) -> dict:
        """On-demand bounded capture (admin ``POST /profile?steps=N``):
        arm a profiler session that the RUN LOOP starts/stops at iteration
        boundaries (mid-step capture would split device steps), then block
        this (admin-thread) caller until the trace is analyzed. Returns the
        ranked bucket report; raises on timeout / profiler failure."""
        req = {
            "steps": max(1, int(steps)),
            "event": threading.Event(),
            "session": None,
            "stop_at": None,
            "report": None,
            "error": None,
        }
        with self._profile_lock:
            pending = self._profile_req
            if pending is not None and not pending["event"].is_set():
                raise RuntimeError("a profile capture is already in flight")
            self._profile_req = req
        if not req["event"].wait(timeout_s):
            with self._profile_lock:
                if self._profile_req is req:
                    self._profile_req = None  # abandoned: unblock later arms
            raise TimeoutError(
                f"profile did not complete within {timeout_s}s "
                f"(is the learner's run loop advancing?)"
            )
        if req["error"]:
            raise RuntimeError(req["error"])
        return req["report"]

    def _profile_tick(self) -> None:
        """Run-loop leg of on-demand profiling: start the armed session at
        this boundary, stop+analyze once the requested steps elapsed."""
        req = self._profile_req
        if req is None or req["event"].is_set():
            return
        if req["session"] is None:
            from ..obs import ProfilerSession

            logdir = os.path.join(
                self.save_dir, "profiles", f"ondemand_{self.last_iter.val}"
            )
            session = ProfilerSession(logdir, registry=self.metrics)
            if not session.start():
                req["error"] = f"profiler start failed (logdir {logdir!r})"
                self._finish_profile(req)
                return
            req["session"] = session
            req["stop_at"] = self.last_iter.val + req["steps"]
            return
        if self.last_iter.val < req["stop_at"]:
            return
        session = req["session"]
        if not session.stop():
            req["error"] = "profiler stop failed"
            self._finish_profile(req)
            return
        try:
            from ..obs import analyze_trace, render_markdown

            report = analyze_trace(
                session.last_profile_path or session.logdir, steps=req["steps"]
            )
            report["markdown"] = render_markdown(report)
            report["captured_steps"] = req["steps"]
            report["last_iter"] = self.last_iter.val
            report["perf"] = self._perf.snapshot()
            req["report"] = report
        except Exception as e:
            req["error"] = f"trace analysis failed: {e!r}"
        self._finish_profile(req)

    def _finish_profile(self, req) -> None:
        with self._profile_lock:
            if self._profile_req is req:
                self._profile_req = None
        req["event"].set()

    # ------------------------------------------------------------------ run
    def run(self, max_iterations: Optional[int] = None) -> None:
        max_iterations = max_iterations or self.cfg.learner.max_iterations
        self._maybe_enable_prefetch()

        # crash path writes synchronously: the process may be about to die
        iters_total = self.metrics.counter(
            "distar_learner_iterations_total", "optimisation steps completed"
        )
        step_time = self.metrics.histogram(
            "distar_learner_step_seconds", "device train-step wall time"
        )
        data_wait = self.metrics.histogram(
            "distar_learner_data_wait_seconds", "dataloader wait per iteration"
        )
        # a gauge (not histogram) on purpose: the NaN/Inf health rule needs
        # the raw last value — a reservoir quantile would mask non-finites
        loss_gauge = self.metrics.gauge(
            "distar_learner_loss", "last total_loss (NaN/Inf watchdog input)"
        )

        frames_per_iter = float(
            (self.cfg.learner.get("batch_size") or 0)
            * (self.cfg.learner.get("unroll_len") or 0)
        )

        self._stop_requested = False

        @auto_checkpoint(lambda: self.save(self.checkpoint_path(), sync=True))
        def _run():
            self.hooks.call("before_run", self)
            while self.last_iter.val < max_iterations and not self._stop_requested:
                with self.timer:
                    data = next(self._dataloader)
                t_data = self.timer.value
                self.log_buffer["data_time"] = t_data
                self.hooks.call("before_iter", self)
                # stash aux refs (e.g. the SL pre-step hidden carry) so an
                # anomaly bundle can reconstruct the step's exact inputs
                self._dynamics.before_step(self)
                with self.timer:
                    log_vars = self._train(data)
                t_train = self.timer.value
                self.log_buffer["train_time"] = t_train
                self.log_buffer.update(log_vars)
                loss = log_vars.get("total_loss")
                if loss is not None:
                    try:
                        loss_gauge.set(float(loss))
                    except (TypeError, ValueError):
                        pass
                # detection + gauge export from the already-fetched host log
                # (no extra device sync); the batch is only touched if an
                # anomaly writes a black-box bundle
                self._dynamics.on_step(self, log_vars, data)
                self.last_iter.add(1)
                # host-callback phase = everything after the device step:
                # hook pass (log reduction, checkpoint scheduling, weight
                # publication) — the third leg of the step breakdown
                with self.timer:
                    self.hooks.call("after_iter", self)
                iters_total.inc()
                step_time.observe(t_train)
                data_wait.observe(t_data)
                record_step_phases(
                    {
                        "data_wait": t_data,
                        "device_step": t_train,
                        "host_callback": self.timer.value,
                    },
                    registry=self.metrics,
                )
                self._perf.on_step(t_train, frames_per_iter)
                self._profile_tick()
            self.hooks.call("after_run", self)

        try:
            _run()
        finally:
            # a profile armed while we were the run loop must not strand its
            # admin-thread waiter once no more iterations will happen
            req = self._profile_req
            if req is not None and not req["event"].is_set():
                if req.get("session") is not None:
                    req["session"].stop()
                req["error"] = "learner run ended before the capture completed"
                self._finish_profile(req)
        # drain per-region stopwatch samples into the registry (decorated
        # regions anywhere in the process accumulate between reports)
        global_stopwatch.report(registry=self.metrics)
        self._checkpointer.wait()  # drain the async writer before returning
