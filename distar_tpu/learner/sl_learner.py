"""Supervised learner: behaviour cloning from decoded replays.

Role of the reference SLLearner (reference: distar/agent/default/
sl_learner.py:23-86): teacher-forced CE training with LSTM hidden state
carried across iterations and reset on new episodes. The carry lives in the
learner (host-managed [B, H] arrays fed back into the jitted step), matching
the reference's stateful-BPTT-across-windows design.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..losses import SupervisedLossConfig, compute_sl_loss
from ..model import Model, default_model_config
from ..parallel import MeshSpec, make_mesh
from ..parallel.grad_clip import leaf_norms
from ..utils import deep_merge_dicts
from .base_learner import DEFAULT_LEARNER_CONFIG, BaseLearner
from .data import FakeSLDataloader, cap_entities

SL_LEARNER_DEFAULTS = deep_merge_dicts(
    DEFAULT_LEARNER_CONFIG,
    {
        "learner": {
            "batch_size": 2,
            "unroll_len": 32,
            "learning_rate": 1e-3,
            "betas": [0.9, 0.999],
            "eps": 1e-8,
            "weight_decay": 1e-5,
            "grad_clip": {"type": "norm", "threshold": 1.0},
            "label_smooth": 0.0,
            # per-parameter grad/param-norm logging (reference save_grad)
            "save_grad": False,
            # pad-to-bucket entity cap (throughput; see data.cap_entities)
            "max_entities": None,
            # loss-spike debug snapshots (reference sl_learner debug mode)
            "debug_loss_spike": False,
            "debug_spike_factor": 10.0,
            "debug_spike_warmup": 200,
        },
        "model": {},
    },
)


def make_sl_train_step(model: Model, loss_cfg: SupervisedLossConfig, optimizer,
                       batch_size: int, save_grad: bool = False, dynamics=None):
    def loss_fn(params, batch, hidden_state):
        logits, out_state = model.apply(
            params,
            batch["spatial_info"], batch["entity_info"], batch["scalar_info"],
            batch["entity_num"], batch["action_info"], batch["selected_units_num"],
            hidden_state, batch_size,
            method=model.sl_forward,
        )
        total, info = compute_sl_loss(
            logits,
            batch["action_info"],
            batch["action_mask"],
            batch["selected_units_num"],
            batch["entity_num"],
            loss_cfg,
        )
        return total, (info, out_state)

    def train_step(params, opt_state, batch, hidden_state):
        (_, (info, out_state)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, hidden_state
        )
        info["grad_norm"] = optax.global_norm(grads)
        if save_grad:
            # per-parameter norms (reference save_grad TB dumps)
            info.update(leaf_norms(grads, "grad_norm"))
            info.update(leaf_norms(params, "param_norm"))
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if dynamics is not None:
            # pre-step params + post-clip updates: ratios/censuses describe
            # exactly this step (obs/dynamics.py)
            from ..obs import dynamics_tree

            info.update(dynamics_tree(
                params, grads, updates=updates, batch=batch, spec=dynamics
            ))
        params = optax.apply_updates(params, updates)
        return params, opt_state, out_state, info

    return train_step


class SLLearner(BaseLearner):
    _CAP_FN = staticmethod(cap_entities)

    def __init__(self, cfg: Optional[dict] = None, mesh=None):
        cfg = deep_merge_dicts(SL_LEARNER_DEFAULTS, cfg or {})
        self.mesh = mesh if mesh is not None else make_mesh(MeshSpec())
        self.model_cfg = deep_merge_dicts(default_model_config(), cfg.get("model", {}))
        self.model = Model(self.model_cfg)
        self.loss_cfg = SupervisedLossConfig(label_smooth=cfg.learner.label_smooth)
        super().__init__(cfg)

    def _setup_dataloader(self) -> None:
        lc = self.cfg.learner
        self._dataloader = iter(FakeSLDataloader(lc.batch_size, lc.unroll_len))

    def set_dataloader(self, it) -> None:
        self._dataloader = iter(it)

    def _setup_state(self) -> None:
        lc = self.cfg.learner
        B = lc.batch_size
        from ..parallel.mesh import shrink_dp

        new_mesh = shrink_dp(self.mesh, B)
        if new_mesh is not self.mesh:
            self.logger.info(
                f"batch {B} not divisible by mesh dp={self.mesh.shape['dp']}; "
                f"shrunk to dp={new_mesh.shape['dp']} (other axes preserved)"
            )
            self.mesh = new_mesh
        from ..parallel.mesh import set_context_mesh

        set_context_mesh(self.mesh)  # ring attention resolves sp at trace time
        core = self.model_cfg.encoder.core_lstm
        self._hidden = tuple(
            (jnp.zeros((B, core.hidden_size)), jnp.zeros((B, core.hidden_size)))
            for _ in range(core.num_layers)
        )
        self.optimizer = self._build_optimizer()
        batch = next(self._dataloader)
        batch.pop("new_episodes", None)
        batch.pop("traj_lens", None)
        batch = self._cap(batch)  # init at the capped shape: one compile, not two
        batch = jax.tree.map(jnp.asarray, batch)

        def init_fn(rng, spatial, entity, scalar, entity_num, action, sun, hidden):
            return self.model.init(
                rng, spatial, entity, scalar, entity_num, action, sun, hidden, B,
                method=self.model.sl_forward,
            )

        params = jax.jit(init_fn)(
            jax.random.PRNGKey(self.init_prng_seed),
            batch["spatial_info"], batch["entity_info"], batch["scalar_info"],
            batch["entity_num"], batch["action_info"], batch["selected_units_num"],
            self._hidden,
        )
        from ..parallel.mesh import batch_sharding, fsdp_param_sharding

        repl = NamedSharding(self.mesh, P())
        param_sh = fsdp_param_sharding(self.mesh, params)
        params = jax.device_put(params, param_sh)
        opt_sh = fsdp_param_sharding(self.mesh, jax.eval_shape(self.optimizer.init, params))
        self._state = {
            "params": params,
            "opt_state": jax.jit(self.optimizer.init, out_shardings=opt_sh)(params),
        }
        # batch_size validates here: typed MeshConfigError at compile time,
        # not an opaque XLA sharding error on the first step
        flat_sh = batch_sharding(self.mesh, batch_size=B)
        self._shardings = dict(repl=repl, param=param_sh, opt=opt_sh, flat=flat_sh)
        self._train_step = jax.jit(
            make_sl_train_step(
                self.model, self.loss_cfg, self.optimizer, B,
                save_grad=self.cfg.learner.get("save_grad", False),
                dynamics=self._dynamics_spec(),
            ),
            donate_argnums=(0, 1),
            # params/opt keep their fsdp shardings; the carried hidden state
            # shards over batch; the info scalars replicate (prefix leaves
            # broadcast over their subtrees)
            out_shardings=(param_sh, opt_sh, flat_sh, repl),
        )
        # analytic per-step collective estimate (obs/perf.py)
        self._perf.set_collectives(self.mesh, self._state["params"])

    def evaluate(self, dataloader, max_batches: int = 0) -> Dict[str, float]:
        """Held-out metric pass: run the SL forward + loss/metric grid over
        a dataloader WITHOUT gradients or state mutation, averaging the
        scalar metrics across batches (the eval axis of SURVEY §7 milestone
        4 — train acc alone can't show generalization). Hidden state starts
        cold per batch; windows within a batch still carry it forward
        through the unroll. Stops at ``max_batches`` (0 = drain)."""
        if not hasattr(self, "_eval_step"):
            B = self.cfg.learner.batch_size

            def eval_step(params, batch, hidden_state):
                logits, out_state = self.model.apply(
                    params,
                    batch["spatial_info"], batch["entity_info"],
                    batch["scalar_info"], batch["entity_num"],
                    batch["action_info"], batch["selected_units_num"],
                    hidden_state, B,
                    method=self.model.sl_forward,
                )
                total, info = compute_sl_loss(
                    logits, batch["action_info"], batch["action_mask"],
                    batch["selected_units_num"], batch["entity_num"],
                    self.loss_cfg,
                )
                info["total_loss"] = total
                return info

            self._eval_step = jax.jit(eval_step)
        sums: Dict[str, float] = {}
        n = 0
        core = self.model_cfg.encoder.core_lstm
        B = self.cfg.learner.batch_size
        hidden = tuple(
            (jnp.zeros((B, core.hidden_size)), jnp.zeros((B, core.hidden_size)))
            for _ in range(core.num_layers)
        )
        for batch in dataloader:
            batch = dict(batch)
            batch.pop("new_episodes", None)
            batch.pop("traj_lens", None)
            batch = self._cap(batch)
            batch = jax.tree.map(jnp.asarray, batch)
            info = self._eval_step(self.state["params"], batch, hidden)
            for k, v in info.items():
                v = np.asarray(v)
                if v.ndim == 0 and np.issubdtype(v.dtype, np.floating):
                    sums[k] = sums.get(k, 0.0) + float(v)
            n += 1
            if max_batches and n >= max_batches:
                break
        return {k: v / max(n, 1) for k, v in sums.items()}

    def _place_batch(self, data):
        """Prefetch placement: placed (mesh-sharded) ahead of time, host
        fields kept. Routes through ``assemble_global`` so per-host shards
        assemble into global arrays on a pod."""
        from ..parallel.feeder import assemble_global

        data = self._cap(dict(data))
        host = {k: np.asarray(data.pop(k)) for k in ("new_episodes", "traj_lens") if k in data}
        out = jax.tree.map(
            lambda x: assemble_global(jnp.asarray(x), self._shardings["flat"]), data
        )
        out.update(host)
        out["_on_device"] = True
        return out

    def _dynamics_aux(self) -> Dict[str, Any]:
        """Pre-step extras for a black-box bundle: the carried LSTM hidden
        BEFORE this step's episode-reset (replay restores it and lets
        _train re-apply the reset from the batch's own new_episodes).
        Device-array REFS only — hidden is not donated, so they stay valid;
        the D2H fetch happens only if a bundle is written."""
        return {"hidden_state": self._hidden}

    def _train(self, data) -> Dict[str, Any]:
        data = dict(data)  # callers may reuse the batch dict
        on_device = data.pop("_on_device", False)
        if not on_device:
            data = self._cap(data)
        new_episodes = np.asarray(data.pop("new_episodes"))
        traj_lens = data.pop("traj_lens", None)
        if new_episodes.any():
            # reset hidden state for restarted trajectories (reference
            # sl_learner.py:31-35)
            keep = jnp.asarray(~new_episodes, jnp.float32)[:, None]
            self._hidden = tuple((h * keep, c * keep) for h, c in self._hidden)
        if not on_device:
            data = jax.tree.map(
                lambda x: jax.device_put(jnp.asarray(x), self._shardings["flat"]), data
            )
        debug_on = self.cfg.learner.get("debug_loss_spike", False)
        if debug_on:
            # the step's exact inputs: batch + post-reset hidden (params are
            # donated, so a spike's checkpoint is one Adam step past — noted
            # in the snapshot)
            pre_step = {
                "batch": data,
                "hidden_state": self._hidden,
                "new_episodes": new_episodes,
                "traj_lens": traj_lens,
            }
        self._perf_note_step_args(
            self._train_step,
            self._state["params"], self._state["opt_state"], data, self._hidden,
        )
        params, opt_state, out_state, info = self._train_step(
            self._state["params"], self._state["opt_state"], data, self._hidden
        )
        self._state = {"params": params, "opt_state": opt_state}
        self._hidden = jax.tree.map(jax.lax.stop_gradient, out_state)
        # one batched D2H transfer instead of a round-trip per metric
        log = {k: float(v) for k, v in jax.device_get(info).items()}
        if debug_on:
            self._loss_spike_guard(log, pre_step)
        return log

    # snapshots per run: a misbehaving trigger must not flood the disk
    _DEBUG_DUMP_CAP = 20
    # EMAs this small are "no signal yet" (masked heads are exactly 0.0 for
    # batches without those actions) — never treat growth from them as a spike
    _DEBUG_EMA_FLOOR = 0.01

    def _loss_spike_guard(self, log: Dict[str, float], pre_step: dict) -> None:
        """Debug mode: EMA-track every loss term; when one spikes past
        ``debug_spike_factor``× its EMA after ``debug_spike_warmup`` iters —
        or goes non-finite — save a checkpoint and dump the step's exact
        inputs (batch, post-reset hidden state, episode boundaries) + log
        for offline repro (role of the reference SL debug mode,
        sl_learner.py:55-60: 0.95/0.05 EMA, 10x trigger, iter>200)."""
        if not hasattr(self, "_debug_ema"):
            self._debug_ema = {}
            self._debug_dumps = 0
            self._debug_nonfinite = set()  # keys already reported as blown up
        factor = float(self.cfg.learner.get("debug_spike_factor", 10.0))
        warmup = int(self.cfg.learner.get("debug_spike_warmup", 200))
        dumped = False
        for k, v in log.items():
            if "loss" not in k:
                continue
            prev = self._debug_ema.get(k)
            blown_up = not np.isfinite(v)  # divergence is the headline event
            if not blown_up:
                self._debug_nonfinite.discard(k)  # recovered: re-arm
            elif k in self._debug_nonfinite:
                continue  # one snapshot per divergence event, not per iter
            # blown_up alone qualifies — a run that is non-finite from the
            # FIRST iteration (prev never seeded) is exactly the scenario
            # this mode exists to capture; ratio spikes need a finite EMA
            spiked = blown_up or (
                prev is not None
                and np.isfinite(prev)
                and prev > self._DEBUG_EMA_FLOOR
                and v > prev * factor
            )
            if (
                spiked
                # warmup only mutes ratio spikes (noisy early losses); a
                # non-finite loss must dump even at iteration 1
                and (blown_up or self.last_iter.val > warmup)
                and not dumped  # one snapshot per iteration is plenty
                and self._debug_dumps < self._DEBUG_DUMP_CAP
            ):
                dumped = True
                self._debug_dumps += 1
                if blown_up:
                    self._debug_nonfinite.add(k)
                self._dump_spike(k, v, prev, log, pre_step)
            if not blown_up:  # never poison the EMA with inf/nan
                self._debug_ema[k] = v if prev is None else prev * 0.95 + v * 0.05
        return

    def _dump_spike(self, key, value, ema, log, pre_step) -> None:
        from ..comm.serializer import dumps

        os.makedirs(os.path.join(self.save_dir, "debug"), exist_ok=True)
        path = os.path.join(
            self.save_dir, "debug",
            f"{key.replace('/', '_')}_iter_{self.last_iter.val}"
            f"_rank{self.rank}_{self._debug_dumps}.spike",
        )
        with open(path, "wb") as f:
            f.write(dumps({
                "key": key, "value": value, "ema": ema, "log": log,
                **{k: jax.device_get(v) for k, v in pre_step.items()},
                "note": "params in the companion checkpoint are one "
                        "optimizer step PAST the spike (donated buffers); "
                        "batch/hidden_state are the step's exact inputs",
            }, compress=True))
        self.save(self.checkpoint_path(), sync=True)  # debug artifacts are durable
        ema_txt = f"{ema:.4f}" if ema is not None else "unseeded"
        self.logger.info(
            f"loss spike: {key}={value:.4f} (ema {ema_txt}); snapshot {path}"
        )
