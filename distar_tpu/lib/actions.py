"""Action-space contract: the 327-action table and every derived lookup.

The raw table and id vocabularies live in ``distar_tpu/data/game_contract.json``
(extracted from the reference by tools/extract_contract.py — see its
provenance block). This module materialises the derived tables the training
stack needs, with semantics matching the reference derivations
(reference: distar/agent/default/lib/actions.py:333-426 and
distar/pysc2/lib/static_data.py), as numpy arrays ready for jnp conversion.

Every action is a dict with keys:
  func_id, general_ability_id, goal, name, queued, selected_units,
  target_location, target_unit, and optionally game_id.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

_DATA_PATH = os.path.join(os.path.dirname(__file__), "..", "data", "game_contract.json")

with open(_DATA_PATH) as _f:
    _CONTRACT = json.load(_f)

ACTIONS: List[dict] = _CONTRACT["actions"]
UNIT_TYPES: List[int] = _CONTRACT["unit_types"]
BUFFS: List[int] = _CONTRACT["buffs"]
UPGRADES: List[int] = _CONTRACT["upgrades"]
ADDON: List[int] = _CONTRACT["addon"]
ABILITIES: List[int] = _CONTRACT["abilities"]
UNIT_SPECIFIC_ABILITIES: List[int] = _CONTRACT["unit_specific_abilities"]
UNIT_GENERAL_ABILITIES: List[int] = _CONTRACT["unit_general_abilities"]
UNIT_MIX_ABILITIES: List[int] = _CONTRACT["unit_mix_abilities"]
ORDER_ACTIONS: List[int] = _CONTRACT["order_actions"]

NUM_ACTIONS = len(ACTIONS)  # 327
NUM_UNIT_TYPES = len(UNIT_TYPES)  # 260
NUM_BUFFS = len(BUFFS)  # 50
NUM_UPGRADES = len(UPGRADES)  # 90
NUM_ADDON = len(ADDON)  # 9
NUM_UNIT_MIX_ABILITIES = len(UNIT_MIX_ABILITIES)  # 269
NUM_ORDER_ACTIONS = len(ORDER_ACTIONS) + 1


def reorder_lookup_array(ids: List[int]) -> np.ndarray:
    """Game-id -> dense-index LUT; -1 marks ids outside the vocabulary."""
    arr = np.full(max(ids) + 1, -1, dtype=np.int64)
    for index, item in enumerate(ids):
        arr[item] = index
    return arr


UNIT_TYPES_REORDER_ARRAY = reorder_lookup_array(UNIT_TYPES)
BUFFS_REORDER_ARRAY = reorder_lookup_array(BUFFS)
UPGRADES_REORDER_ARRAY = reorder_lookup_array(UPGRADES)
ADDON_REORDER_ARRAY = reorder_lookup_array(ADDON)
ABILITIES_REORDER_ARRAY = reorder_lookup_array(ABILITIES)

ORDER_ACTIONS_REORDER_ARRAY = np.zeros(573 + 1, dtype=np.int64)
for _idx, _v in enumerate(ORDER_ACTIONS):
    ORDER_ACTIONS_REORDER_ARRAY[_v] = _idx + 1

# --- ability remapping: specific ability id -> mixed-vocabulary index -------
# An ability maps to its general ability when one exists, else to itself;
# index is its position in UNIT_MIX_ABILITIES. Index 0 is the no-op.
_MIX_INDEX: Dict[int, int] = {a: i for i, a in enumerate(UNIT_MIX_ABILITIES)}

UNIT_ABILITY_REORDER = np.full(max(UNIT_MIX_ABILITIES) + 1, -1, dtype=np.int64)
ABILITY_TO_GABILITY: Dict[int, int] = {}
for _i, _spec in enumerate(UNIT_SPECIFIC_ABILITIES):
    _gen = UNIT_GENERAL_ABILITIES[_i]
    _target = _spec if _gen == 0 else _gen
    ABILITY_TO_GABILITY[_spec] = _target
    UNIT_ABILITY_REORDER[_spec] = _MIX_INDEX[_target]
UNIT_ABILITY_REORDER[0] = 0

FUNC_ID_TO_ACTION_TYPE: Dict[int, int] = {a["func_id"]: i for i, a in enumerate(ACTIONS)}

# --- queue actions: Train_*/Research* general abilities get a dense id ------
GABILITY_TO_QUEUE_ACTION: Dict[int, int] = {}
QUEUE_ACTIONS: List[int] = []
_count = 1  # 0 is the no-op slot
for _idx, _a in enumerate(ACTIONS):
    if "Train_" in _a["name"] or "Research" in _a["name"]:
        GABILITY_TO_QUEUE_ACTION[_a["general_ability_id"]] = _count
        QUEUE_ACTIONS.append(_idx)
        _count += 1
    else:
        GABILITY_TO_QUEUE_ACTION[_a["general_ability_id"]] = 0

ABILITY_TO_QUEUE_ACTION = np.full(max(ABILITY_TO_GABILITY) + 1, -1, dtype=np.int64)
ABILITY_TO_QUEUE_ACTION[0] = 0
for _aid, _gid in ABILITY_TO_GABILITY.items():
    ABILITY_TO_QUEUE_ACTION[_aid] = GABILITY_TO_QUEUE_ACTION.get(_gid, 0)

NUM_QUEUE_ACTIONS = len(QUEUE_ACTIONS)  # 109 as derived; see note below
# The reference's model yaml pins the order_id_{1,2,3} embedding width to 49
# (actor_critic_default_config.yaml:6) even though its derivation yields 109
# queue actions; inputs are clamped into the table at runtime
# (entity_encoder.py:72). We reproduce that contract: embeddings are 49 wide,
# lookups clamp.
QUEUE_ACTION_EMBEDDING_DIM = 49

# --- strategy-statistic action subsets --------------------------------------
# Supply/worker/creep actions are excluded from build-order targets; static
# defense and a few others additionally from cumulative targets
# (reference: actions.py:374-387).
EXCLUDE_ACTIONS = [
    "Build_Pylon_pt", "Train_Overlord_quick", "Build_SupplyDepot_pt",
    "Train_Drone_quick", "Train_SCV_quick", "Train_Probe_quick",
    "Build_CreepTumor_pt", "",
]
CUM_EXCLUDE_ACTIONS = [
    "Build_SpineCrawler_pt", "Build_SporeCrawler_pt", "Build_PhotonCannon_pt",
    "Build_ShieldBattery_pt", "Build_Bunker_pt", "Morph_Overseer_quick",
    "Build_MissileTurret_pt",
]

BEGINNING_ORDER_ACTIONS: List[int] = [0]
CUMULATIVE_STAT_ACTIONS: List[int] = [0]
for _idx, _a in enumerate(ACTIONS):
    if _a["goal"] in ("unit", "build", "research") and _a["name"] not in EXCLUDE_ACTIONS:
        BEGINNING_ORDER_ACTIONS.append(_idx)
        if _a["name"] not in CUM_EXCLUDE_ACTIONS:
            CUMULATIVE_STAT_ACTIONS.append(_idx)

NUM_BEGINNING_ORDER_ACTIONS = len(BEGINNING_ORDER_ACTIONS)  # 174
NUM_CUMULATIVE_STAT_ACTIONS = len(CUMULATIVE_STAT_ACTIONS)  # 167

BEGINNING_ORDER_REORDER_ARRAY = reorder_lookup_array(BEGINNING_ORDER_ACTIONS)
CUMULATIVE_STAT_REORDER_ARRAY = reorder_lookup_array(CUMULATIVE_STAT_ACTIONS)

# --- per-head availability masks over action types --------------------------
SELECTED_UNITS_MASK = np.array([a["selected_units"] for a in ACTIONS], dtype=bool)
TARGET_UNIT_MASK = np.array([a["target_unit"] for a in ACTIONS], dtype=bool)
TARGET_LOCATION_MASK = np.array([a["target_location"] for a in ACTIONS], dtype=bool)
QUEUED_MASK = np.array([a["queued"] for a in ACTIONS], dtype=bool)

UNIT_BUILD_ACTIONS = [a["func_id"] for a in ACTIONS if a["goal"] == "build"]
UNIT_TRAIN_ACTIONS = [a["func_id"] for a in ACTIONS if a["goal"] == "unit"]

GENERAL_ABILITY_IDS = [a["general_ability_id"] for a in ACTIONS]
UNIT_ABILITY_TO_ACTION: Dict[int, int] = {}
for _idx, _ab in enumerate(UNIT_MIX_ABILITIES):
    if _ab in GENERAL_ABILITY_IDS:
        UNIT_ABILITY_TO_ACTION[_idx] = GENERAL_ABILITY_IDS.index(_ab)

# --- replay-decode ability canonicalisation (reference features.py:862-871) -
# cancel-slot and unload-unit ability families collapse onto their general
# actions; Dance/Cheer are dropped.
CANCEL_SLOT_ABILITIES = {313, 1039, 305, 307, 309, 1832, 1834, 3672}
UNLOAD_UNIT_ABILITIES = {410, 415, 397, 1440, 2373, 1409, 914, 3670}
FRIVOLOUS_ABILITIES = {6, 7}  # Dance, Cheer
CANCEL_SLOT_TARGET = 3671  # Cancel_Last/cancel_quick general
UNLOAD_ALL_TARGET = 3664


def action_kind(a: dict) -> str:
    """Which raw-command form an action takes: 'unit' (targets a unit), 'pt'
    (targets a location), 'autocast', or 'quick' (no target) — the cmd_type
    disambiguation of reference reverse_raw_action (:875-878)."""
    if a["target_unit"]:
        return "unit"
    if a["target_location"]:
        return "pt"
    if a["name"].endswith("_autocast"):
        return "autocast"
    return "quick"


# (general_ability_id, kind) -> action index; verified collision-free
GAB_KIND_TO_ACTION: Dict[tuple, int] = {}
for _idx, _a in enumerate(ACTIONS):
    if _a["general_ability_id"] is not None:
        GAB_KIND_TO_ACTION.setdefault((_a["general_ability_id"], action_kind(_a)), _idx)

# game unit-type / upgrade id -> cumulative-stat slot (-1 when untracked)
UNIT_TO_CUM: Dict[int, int] = {}
UPGRADE_TO_CUM: Dict[int, int] = {}
for _idx, _a in enumerate(ACTIONS):
    if "game_id" in _a and _idx in CUMULATIVE_STAT_ACTIONS:
        _slot = CUMULATIVE_STAT_ACTIONS.index(_idx)
        if _a["goal"] in ("unit", "build"):
            UNIT_TO_CUM[_a["game_id"]] = _slot
        elif _a["goal"] == "research":
            UPGRADE_TO_CUM[_a["game_id"]] = _slot
