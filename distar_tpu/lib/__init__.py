from . import actions, features

__all__ = ["actions", "features"]
