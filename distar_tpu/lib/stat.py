"""In-game statistics: unit counts, action success rates, per-race legality.

Role parity with the reference Stat module (reference: distar/agent/default/
lib/stat.py): ``Stat`` tracks built-unit counts and per-action success rates
during an episode; ``ACTION_RACE_MASK`` gates action-type logits by race in
play mode (action_type_head.py:53-55); ``cum_dict`` names the cumulative-stat
slots for TB logging. All data tables come from the extracted contract
(tools/extract_contract.py).
"""
from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Dict, Optional

import numpy as np

from .actions import ACTIONS, FUNC_ID_TO_ACTION_TYPE

_DATA_PATH = os.path.join(os.path.dirname(__file__), "..", "data", "game_contract.json")
with open(_DATA_PATH) as _f:
    _C = json.load(_f)

UNIT_DICT: Dict[str, Dict[int, str]] = {
    race: {int(k): v for k, v in table.items()} for race, table in _C["unit_dict"].items()
}
CUM_DICT = _C["cum_dict"]  # slot index -> human-readable name
ACTION_RESULT_NAMES = _C["action_result_dict"]
NUM_ACTION_RESULT = len(ACTION_RESULT_NAMES)

# race -> bool[327] action legality (reference stat.py:533+)
ACTION_RACE_MASK: Dict[str, np.ndarray] = {
    race: np.asarray(mask, dtype=bool) for race, mask in _C["action_race_mask"].items()
}


class Stat:
    """Per-episode unit-count and action-success tracking."""

    def __init__(self, race: str = "zerg"):
        self._race = race
        self._unit_num: Dict[str, float] = defaultdict(int)
        self._unit_num["max_unit_num"] = 0
        for name in UNIT_DICT.get(race, {}).values():
            self._unit_num[name] = 0
        self._success: Dict[str, int] = defaultdict(int)

    def set_race(self, race: str) -> None:
        self._race = race

    def update(self, last_action_type: int, action_result: int, observation: Optional[dict],
               game_step: float) -> None:
        if action_result < 1:
            return
        if action_result == 1:
            self._count_unit(last_action_type)
        if observation is not None:
            ent = observation.get("entity_info")
            n = int(np.asarray(observation.get("entity_num", 0)))
            if ent is not None and (np.asarray(ent["alliance"])[:n] == 1).sum() > 10:
                self._success_rate(last_action_type, action_result)

    def _count_unit(self, action_type: int) -> None:
        func_id = ACTIONS[action_type]["func_id"]
        name = UNIT_DICT.get(self._race, {}).get(func_id)
        if not name:
            return
        self._unit_num[name] += 1
        self._unit_num["max_unit_num"] = max(self._unit_num[name], self._unit_num["max_unit_num"])

    def _success_rate(self, action_type: int, action_result: int) -> None:
        action_name = ACTIONS[action_type]["name"]
        msg = (
            ACTION_RESULT_NAMES[action_result]
            if 0 <= action_result < NUM_ACTION_RESULT
            else f"code{action_result}"
        )
        self._success[f"rate/{action_name}/{msg}"] += 1
        self._success[f"rate/{action_name}/count"] += 1

    def get_stat_data(self) -> Dict[str, float]:
        data: Dict[str, float] = {}
        denom = max(self._unit_num["max_unit_num"], 1)
        for k, v in self._unit_num.items():
            if k != "max_unit_num":
                data[f"units/{k}"] = v / denom
        for k, v in self._success.items():
            if k.endswith("/count"):
                data[k] = v
            else:
                action = k.split("rate/")[1].split("/")[0]
                data[k] = v / (self._success[f"rate/{action}/count"] + 1e-6)
        return data

    @property
    def unit_num(self) -> Dict[str, float]:
        return dict(self._unit_num)
