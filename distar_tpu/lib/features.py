"""Feature-space contract: observation/action schemas and fixed shapes.

This is the compatibility keel of the framework — the schema every layer
(env, agent, dataloader, model, losses) agrees on. Dimensions and field lists
match the reference contract (reference: distar/agent/default/lib/features.py:31-145)
but the fixtures are plain numpy (host side) with fixed shapes chosen for XLA:
entity arrays are always padded to MAX_ENTITY_NUM and selected-units to
MAX_SELECTED_UNITS_NUM so every jit sees one static shape.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .actions import (
    NUM_ACTIONS,
    NUM_BEGINNING_ORDER_ACTIONS,
    NUM_CUMULATIVE_STAT_ACTIONS,
    NUM_UNIT_MIX_ABILITIES,
    NUM_UNIT_TYPES,
    NUM_UPGRADES,
)

# Fixed sizes (reference: features.py:31-38)
SPATIAL_SIZE = (152, 160)  # (y, x)
BUFF_LENGTH = 3
UPGRADE_LENGTH = 20
MAX_DELAY = 127
BEGINNING_ORDER_LENGTH = 20
MAX_SELECTED_UNITS_NUM = 64
MAX_ENTITY_NUM = 512
EFFECT_LENGTH = 100

DEFAULT_SPATIAL_SIZE = SPATIAL_SIZE

# Spatial planes: name -> dtype. 'effect_*' planes arrive as flat-index
# coordinate lists of length EFFECT_LENGTH and are scattered on device.
SPATIAL_INFO = {
    "height_map": np.uint8,
    "visibility_map": np.uint8,
    "creep": np.uint8,
    "player_relative": np.uint8,
    "alerts": np.uint8,
    "pathable": np.uint8,
    "buildable": np.uint8,
    "effect_PsiStorm": np.int16,
    "effect_NukeDot": np.int16,
    "effect_LiberatorDefenderZone": np.int16,
    "effect_BlindingCloud": np.int16,
    "effect_CorrosiveBile": np.int16,
    "effect_LurkerSpines": np.int16,
}

# Scalar features: name -> (dtype, shape)
SCALAR_INFO = {
    "home_race": (np.uint8, ()),
    "away_race": (np.uint8, ()),
    "upgrades": (np.int16, (NUM_UPGRADES,)),
    "time": (np.float32, ()),
    "unit_counts_bow": (np.uint8, (NUM_UNIT_TYPES,)),
    "agent_statistics": (np.float32, (10,)),
    "cumulative_stat": (np.uint8, (NUM_CUMULATIVE_STAT_ACTIONS,)),
    "beginning_order": (np.int16, (BEGINNING_ORDER_LENGTH,)),
    "last_queued": (np.int16, ()),
    "last_delay": (np.int16, ()),
    "last_action_type": (np.int16, ()),
    "bo_location": (np.int16, (BEGINNING_ORDER_LENGTH,)),
    "unit_order_type": (np.uint8, (NUM_UNIT_MIX_ABILITIES,)),
    "unit_type_bool": (np.uint8, (NUM_UNIT_TYPES,)),
    "enemy_unit_type_bool": (np.uint8, (NUM_UNIT_TYPES,)),
}

# Per-entity features (each a [MAX_ENTITY_NUM] vector): name -> dtype
ENTITY_INFO = {
    "unit_type": np.int16,
    "alliance": np.uint8,
    "cargo_space_taken": np.uint8,
    "build_progress": np.float16,
    "health_ratio": np.float16,
    "shield_ratio": np.float16,
    "energy_ratio": np.float16,
    "display_type": np.uint8,
    "x": np.uint8,
    "y": np.uint8,
    "cloak": np.uint8,
    "is_blip": np.uint8,
    "is_powered": np.uint8,
    "mineral_contents": np.float16,
    "vespene_contents": np.float16,
    "cargo_space_max": np.uint8,
    "assigned_harvesters": np.uint8,
    "weapon_cooldown": np.uint8,
    "order_length": np.uint8,
    "order_id_0": np.int16,
    "order_id_1": np.int16,
    "is_hallucination": np.uint8,
    "buff_id_0": np.uint8,
    "buff_id_1": np.uint8,
    "addon_unit_type": np.uint8,
    "is_active": np.uint8,
    "order_progress_0": np.float16,
    "order_progress_1": np.float16,
    "order_id_2": np.int16,
    "order_id_3": np.int16,
    "is_in_cargo": np.uint8,
    "attack_upgrade_level": np.uint8,
    "armor_upgrade_level": np.uint8,
    "shield_upgrade_level": np.uint8,
    "last_selected_units": np.int8,
    "last_targeted_unit": np.int8,
}

ACTION_HEADS = ("action_type", "delay", "queued", "selected_units", "target_unit", "target_location")

# Per-head logit widths; selected_units has MAX_ENTITY_NUM+1 classes (the +1
# is the end-flag token).
LOGIT_SHAPES = {
    "action_type": (NUM_ACTIONS,),
    "delay": (MAX_DELAY + 1,),
    "queued": (2,),
    "selected_units": (MAX_SELECTED_UNITS_NUM, MAX_ENTITY_NUM + 1),
    "target_unit": (MAX_ENTITY_NUM,),
    "target_location": (SPATIAL_SIZE[0] * SPATIAL_SIZE[1],),
}

ACTION_SHAPES = {
    "action_type": (),
    "delay": (),
    "queued": (),
    "selected_units": (MAX_SELECTED_UNITS_NUM,),
    "target_unit": (),
    "target_location": (),
}


def _zeros(shape, dtype):
    return np.zeros(shape, dtype=dtype)


def fake_spatial_info(size=SPATIAL_SIZE) -> Dict[str, np.ndarray]:
    out = {}
    for k, dtype in SPATIAL_INFO.items():
        if k.startswith("effect_"):
            out[k] = _zeros((EFFECT_LENGTH,), dtype)
        else:
            out[k] = _zeros(size, dtype)
    return out


def fake_scalar_info() -> Dict[str, np.ndarray]:
    return {k: _zeros(shape, dtype) for k, (dtype, shape) in SCALAR_INFO.items()}


def fake_entity_info() -> Dict[str, np.ndarray]:
    return {k: _zeros((MAX_ENTITY_NUM,), dtype) for k, dtype in ENTITY_INFO.items()}


def fake_action_info() -> Dict[str, np.ndarray]:
    return {k: _zeros(shape, np.int64) for k, shape in ACTION_SHAPES.items()}


def fake_action_logp() -> Dict[str, np.ndarray]:
    return {k: _zeros(ACTION_SHAPES[k], np.float32) for k in ACTION_HEADS}


def fake_action_logits() -> Dict[str, np.ndarray]:
    return {k: _zeros(shape, np.float32) for k, shape in LOGIT_SHAPES.items()}


def fake_action_mask() -> Dict[str, np.ndarray]:
    return {k: np.ones((), dtype=bool) for k in ACTION_HEADS}


def fake_step_data(
    train: bool = True,
    rng: Optional[np.random.Generator] = None,
    size=SPATIAL_SIZE,
) -> Dict:
    """A schema-complete single observation (no batch dim).

    Role of the reference's fake_step_data (features.py:95-127): model warmup,
    shape contract for batched inference, and test fixture.
    """
    rng = rng or np.random.default_rng(0)
    ret = {
        "spatial_info": fake_spatial_info(size),
        "scalar_info": fake_scalar_info(),
        "entity_info": fake_entity_info(),
        "entity_num": np.asarray(rng.integers(1, MAX_ENTITY_NUM), dtype=np.int64),
    }
    if train:
        ret.update(
            {
                "action_info": fake_action_info(),
                "action_mask": fake_action_mask(),
                "selected_units_num": np.asarray(
                    rng.integers(0, MAX_SELECTED_UNITS_NUM), dtype=np.int64
                ),
            }
        )
    return ret


def fake_model_output(hidden_layers: int = 3, hidden_size: int = 384, teacher: bool = False) -> Dict:
    """Schema-complete model output (no batch dim); the device-buffer layout
    for batched actor inference (role of reference features.py:130-145)."""
    ret = {
        "logit": fake_action_logits(),
        "entity_num": np.asarray(0, dtype=np.int64),
        "selected_units_num": np.asarray(0, dtype=np.int64),
        "hidden_state": [
            (_zeros((hidden_size,), np.float32), _zeros((hidden_size,), np.float32))
            for _ in range(hidden_layers)
        ],
    }
    if not teacher:
        ret.update(
            {
                "action_info": fake_action_info(),
                "action_logp": fake_action_logp(),
                "extra_units": _zeros((MAX_ENTITY_NUM + 1,), np.float32),
            }
        )
    return ret


# Centralized-critic feature schema (league RL with use_value_feature; role
# of the reference's value_feature dict built in transform_obs, features.py
# :691-765 — opponent stats + both sides' unit scatter inputs + behaviour Z).
VALUE_FEATURE_INFO = {
    "enemy_unit_counts_bow": (np.uint8, ("NUM_UNIT_TYPES",)),
    "enemy_unit_type_bool": (np.uint8, ("NUM_UNIT_TYPES",)),
    "enemy_agent_statistics": (np.float32, (10,)),
    "enemy_upgrades": (np.int16, ("NUM_UPGRADES",)),
    "enemy_cumulative_stat": (np.uint8, ("NUM_CUMULATIVE_STAT_ACTIONS",)),
    "unit_alliance": (np.uint8, ("MAX_ENTITY_NUM",)),
    "unit_type": (np.int16, ("MAX_ENTITY_NUM",)),
    "unit_x": (np.uint8, ("MAX_ENTITY_NUM",)),
    "unit_y": (np.uint8, ("MAX_ENTITY_NUM",)),
    "total_unit_count": (np.int64, ()),
    "own_units_spatial": (np.uint8, "SPATIAL"),
    "enemy_units_spatial": (np.uint8, "SPATIAL"),
    "beginning_order": (np.int16, (BEGINNING_ORDER_LENGTH,)),
    "bo_location": (np.int16, (BEGINNING_ORDER_LENGTH,)),
}


def fake_value_feature(rng: Optional[np.random.Generator] = None) -> Dict[str, np.ndarray]:
    rng = rng or np.random.default_rng(0)
    dims = {
        "NUM_UNIT_TYPES": NUM_UNIT_TYPES,
        "NUM_UPGRADES": NUM_UPGRADES,
        "NUM_CUMULATIVE_STAT_ACTIONS": NUM_CUMULATIVE_STAT_ACTIONS,
        "MAX_ENTITY_NUM": MAX_ENTITY_NUM,
    }
    out = {}
    for k, (dtype, shape) in VALUE_FEATURE_INFO.items():
        if shape == "SPATIAL":
            out[k] = _zeros(SPATIAL_SIZE, dtype)
        else:
            resolved = tuple(dims.get(s, s) for s in shape)
            out[k] = _zeros(resolved, dtype)
    out["total_unit_count"] = np.asarray(int(rng.integers(1, MAX_ENTITY_NUM)), np.int64)
    return out


def batch_tree(trees, stack=np.stack):
    """Stack a list of nested dict/tuple/array structures along axis 0."""
    first = trees[0]
    if isinstance(first, dict):
        return {k: batch_tree([t[k] for t in trees], stack) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(batch_tree([t[i] for t in trees], stack) for i in range(len(first)))
    return stack([np.asarray(t) for t in trees])
