"""Strategy-statistics ("Z") libraries.

Role parity with the reference Z machinery (reference: distar/bin/gen_z.py
and agent.py:176-243): a Z library is a json keyed
``map_name -> mix_race -> born_location_str -> [entries]`` where each entry is
``[building_order, cumulative_stat_indices, bo_location, z_loop(, z_type)]``.
Agents sample an entry at episode start and are rewarded for following it
(pseudo-rewards) and conditioned on it (scalar encoder Z inputs).

z_type semantics (agent.py:213-217): 1 disables bo reward, 2 disables cum
reward, 3 disables both.
"""
from __future__ import annotations

import json
import os
import random
from typing import Dict, List, Optional

import numpy as np

from . import actions as ACT
from .features import BEGINNING_ORDER_LENGTH


def z_entry_to_target(entry: List, fake_reward_prob: float = 1.0) -> dict:
    """Normalise one raw library entry into the agent's target dict."""
    if len(entry) == 5:
        bo, cum_idx, bo_location, z_loop, z_type = entry
    else:
        bo, cum_idx, bo_location, z_loop = entry
        z_type = None
    use_cum = not (z_type in (2, 3))
    use_bo = not (z_type in (1, 3))
    if random.random() > fake_reward_prob:
        use_cum = False
    if random.random() > fake_reward_prob:
        use_bo = False
    return {
        "beginning_order": list(bo),
        "bo_location": list(bo_location),
        "cumulative_stat": list(cum_idx),
        "z_loop": z_loop,
        "use_bo_reward": use_bo,
        "use_cum_reward": use_cum,
        "bo_norm": max(len(bo), 1),
        "cum_norm": max(len(cum_idx), 1),
    }


class ZLibrary:
    def __init__(self, path: str):
        with open(path) as f:
            raw = json.load(f)
        # dunder keys hold metadata (e.g. the extraction provenance block)
        self.data = {k: v for k, v in raw.items() if not k.startswith("__")}

    def sample(
        self,
        map_name: str,
        mix_race: str,
        born_location: int,
        fake_reward_prob: float = 1.0,
    ) -> dict:
        entries = self.data[map_name][mix_race][str(born_location)]
        return z_entry_to_target(random.choice(entries), fake_reward_prob)

    def keys(self):
        return {
            m: {r: list(locs.keys()) for r, locs in races.items()}
            for m, races in self.data.items()
        }

    def sample_any(
        self,
        map_name: str,
        mix_race: Optional[str] = None,
        fake_reward_prob: float = 1.0,
    ) -> Optional[dict]:
        """Sample with graceful key fallback: unknown map/race/location keys
        fall back to a random available one (the reference tolerates partial
        libraries via its own fallbacks, agent.py:189-206); None when the
        library is empty."""
        if not self.data:
            return None
        races = self.data.get(map_name) or self.data[random.choice(list(self.data))]
        locs = races.get(mix_race) if mix_race else None
        if not locs:
            locs = races[random.choice(list(races))]
        entries = locs[random.choice(list(locs))]
        if not entries:
            return None
        return z_entry_to_target(random.choice(entries), fake_reward_prob)


def build_z_library(
    episodes: List[dict],
    min_winloss: int = 1,
) -> Dict:
    """Aggregate recorded episode summaries into a Z library.

    Role of the reference gen_z result_loop (gen_z.py:49+ — decode *winning*
    replays into Z entries). ``episodes`` entries carry: map_name, mix_race,
    born_location, winloss, beginning_order, bo_location, cumulative_stat
    (dense 0/1 vector or index list), game_loop.
    """
    lib: Dict = {}
    for ep in episodes:
        if ep.get("winloss", 0) < min_winloss:
            continue
        cum = ep["cumulative_stat"]
        cum = np.asarray(cum)
        cum_idx = (
            cum.nonzero()[0].tolist() if cum.ndim and len(cum) == ACT.NUM_CUMULATIVE_STAT_ACTIONS
            else [int(x) for x in cum]
        )
        bo = [int(x) for x in ep["beginning_order"] if x != 0][:BEGINNING_ORDER_LENGTH]
        loc = [int(x) for x in ep["bo_location"]][: len(bo)]
        entry = [bo, cum_idx, loc, int(ep.get("game_loop", 0))]
        lib.setdefault(ep["map_name"], {}).setdefault(ep["mix_race"], {}).setdefault(
            str(int(ep["born_location"])), []
        ).append(entry)
    return lib


def save_z_library(lib: Dict, path: str) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(lib, f)
    return path
