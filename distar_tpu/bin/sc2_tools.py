"""SC2 developer tools: replay inspection, map listing, throughput benches.

Role parity with the reference pysc2 tool scripts (reference: distar/pysc2/
bin/replay_info.py, map_list.py, benchmark_observe.py:1-149,
benchmark_replay.py:1-106): one CLI with subcommands instead of a script
per tool. Every subcommand accepts ``--endpoint host:port`` to drive an
already-running SC2 (or the in-process fake server in tests) instead of
launching a binary.

  replay-info        print per-replay metadata (map, duration, players,
                     version) for a path or directory
  map-list           print the map registry (sizes + localized names)
  benchmark-observe  steps a game and measures observe + transform_obs
                     throughput (the actor's per-step CPU cost)
  benchmark-replay   measures two-pass decode throughput in steps/s
"""
from __future__ import annotations

import argparse
import time


def _controller(args):
    from ..envs.sc2.remote_controller import RemoteController

    if args.endpoint:
        host, _, port = args.endpoint.rpartition(":")
        return RemoteController(host or "127.0.0.1", int(port), timeout_seconds=30)
    from ..envs.sc2 import run_configs

    proc = run_configs.get(version=args.version).start(want_rgb=False)
    return proc.controller


def replay_info(args) -> None:
    from ..envs.sc2 import run_configs

    rc = run_configs.get() if not args.endpoint else None
    paths = (
        list(rc.replay_paths(args.replays)) if rc is not None else [args.replays]
    )
    print(f"found {len(paths)} replays")
    c = _controller(args)
    try:
        for path in paths:
            data = None
            if rc is not None:
                data = rc.replay_data(path)
            info = c.replay_info(replay_path=None if data else path, replay_data=data)
            print(f"\n{path}")
            print(f"  map: {info.map_name}")
            print(
                f"  version: {info.game_version} (build {info.base_build}), "
                f"loops: {info.game_duration_loops}"
            )
            for p in info.player_info:
                pi = p.player_info
                print(
                    f"  player {pi.player_id}: race {pi.race_actual} "
                    f"mmr {p.player_mmr} apm {p.player_apm} "
                    f"result {p.player_result.result}"
                )
    finally:
        c.quit()


def map_list(args) -> None:
    from ..envs.sc2 import maps

    for name in sorted(maps.MAPS):
        size = maps.get_map_size(name)
        localized = maps.get_localized_map_name(name)
        print(f"{name:32s} {size[0]}x{size[1]}  {', '.join(localized[:3])}")


def benchmark_observe(args) -> None:
    """Observe+transform throughput over a running game (reference
    benchmark_observe.py measures raw/feature interfaces the same way)."""
    from ..envs.features import ProtoFeatures
    from ..envs.sc2.launcher import Bot, Player, SC2GameLauncher

    kw = {}
    if args.endpoint:
        c = _controller(args)
        kw["controller_factory"] = lambda i: c
    launcher = SC2GameLauncher(
        map_name=args.map,
        # one agent vs a built-in bot: a single controller drives the bench
        players=[Player("zerg"), Bot("zerg", 3)],
        realtime=False,
        version=args.version,
        **kw,
    )
    launcher.ensure_game()
    controller = launcher.controllers[0]
    features = launcher.features[0] if launcher.features else None
    if features is None:
        features = ProtoFeatures(controller.game_info())

    observe_s = transform_s = 0.0
    for i in range(args.steps):
        controller.step(args.step_mul)
        t0 = time.perf_counter()
        obs = controller.observe()
        t1 = time.perf_counter()
        features.transform_obs(obs)
        t2 = time.perf_counter()
        observe_s += t1 - t0
        transform_s += t2 - t1
    n = max(args.steps, 1)
    print(
        f"steps={n} observe={1e3 * observe_s / n:.2f}ms/step "
        f"transform={1e3 * transform_s / n:.2f}ms/step "
        f"throughput={n / (observe_s + transform_s):.1f} obs/s"
    )
    launcher.close()


def benchmark_replay(args) -> None:
    """Two-pass decode throughput (reference benchmark_replay.py:1-106)."""
    from ..envs.replay_decoder import ReplayDecoder

    provider = None
    if args.endpoint:
        host, _, port = args.endpoint.rpartition(":")

        def provider(version):
            from ..envs.sc2.remote_controller import RemoteController

            return RemoteController(host or "127.0.0.1", int(port), timeout_seconds=30)

    dec = ReplayDecoder(
        cfg={"minimum_action_length": args.min_actions,
             "external_endpoint": bool(args.endpoint)},
        controller_provider=provider,
    )
    t0 = time.perf_counter()
    total_steps = 0
    try:
        for path in args.replays:
            traj = dec.run(path, player_index=args.player)
            n = len(traj) if traj else 0
            total_steps += n
            print(f"{path}: {n} steps")
    finally:
        dec.close()
    dt = time.perf_counter() - t0
    print(f"decoded {total_steps} steps in {dt:.1f}s = {total_steps / max(dt, 1e-9):.1f} steps/s")


def main() -> None:
    p = argparse.ArgumentParser()
    sub = p.add_subparsers(dest="cmd", required=True)

    ri = sub.add_parser("replay-info")
    ri.add_argument("replays", help="replay file or directory")
    ri.add_argument("--endpoint", default="")
    ri.add_argument("--version", default=None)
    ri.set_defaults(fn=replay_info)

    ml = sub.add_parser("map-list")
    ml.set_defaults(fn=map_list)

    bo = sub.add_parser("benchmark-observe")
    bo.add_argument("--map", default="KairosJunction")
    bo.add_argument("--steps", type=int, default=100)
    bo.add_argument("--step-mul", type=int, default=8)
    bo.add_argument("--endpoint", default="")
    bo.add_argument("--version", default=None)
    bo.set_defaults(fn=benchmark_observe)

    br = sub.add_parser("benchmark-replay")
    br.add_argument("replays", nargs="+")
    br.add_argument("--player", type=int, default=0)
    br.add_argument("--min-actions", type=int, default=2)
    br.add_argument("--endpoint", default="")
    br.set_defaults(fn=benchmark_replay)

    args = p.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()
