"""Resumable pretrained-model downloader.

Role parity with the reference (reference: distar/bin/download_model.py:
10-62): fetch a released model by name from the DI-star HuggingFace repo,
resuming partial downloads via HTTP Range requests, with a console progress
bar. Stdlib urllib only (no requests dependency); downloaded ``.pth``
checkpoints load directly through model/ref_convert.convert_model (see
bin/play.py load_params).
"""
from __future__ import annotations

import argparse
import os
import ssl
import sys
import time
import urllib.error
import urllib.request

DEFAULT_URL = (
    "https://huggingface.co/OpenDILabCommunity/DI-star/resolve/main/"
    "{name}?download=true"
)


class Downloader:
    def __init__(self, url: str, file_path: str, timeout: float = 60.0,
                 max_retries: int = 5):
        self.url = url
        self.file_path = file_path
        self.timeout = timeout
        self.max_retries = max_retries
        self._ctx = ssl.create_default_context()
        self.total_size = self._head_total_size()

    def _open(self, headers=None, method="GET"):
        req = urllib.request.Request(self.url, headers=headers or {}, method=method)
        return urllib.request.urlopen(req, timeout=self.timeout, context=self._ctx)

    def _head_total_size(self) -> int:
        try:
            with self._open(method="HEAD") as r:
                if r.status == 200:
                    return int(r.headers.get("Content-Length", 0))
        except urllib.error.HTTPError:
            pass  # server without HEAD support: fall through to GET
        with self._open() as r:
            if r.status != 200:
                raise ConnectionError(f"cannot connect {self.url} ({r.status})")
            return int(r.headers.get("Content-Length", 0))

    def _progress(self, done_bytes: int) -> None:
        if self.total_size <= 0:
            sys.stdout.write(f"\r{done_bytes // 1000} kB")
        else:
            done = int(50 * done_bytes / self.total_size)
            sys.stdout.write(
                "\r[%s%s] %d kB / %d kB "
                % ("#" * done, " " * (50 - done), done_bytes // 1000,
                   self.total_size // 1000)
            )
        sys.stdout.flush()

    def download(self) -> str:
        """Fetch with Range-resume; retries continue from what's on disk."""
        for attempt in range(self.max_retries):
            temp_size = (
                os.path.getsize(self.file_path) if os.path.exists(self.file_path) else 0
            )
            if self.total_size and temp_size >= self.total_size:
                break
            try:
                with self._open(
                    {"Range": f"bytes={temp_size}-"} if temp_size else {}
                ) as r:
                    if temp_size and r.status != 206:
                        # server ignored the Range header: appending the full
                        # body would corrupt the partial file — start over
                        temp_size = 0
                    with open(self.file_path, "ab" if temp_size else "wb") as f:
                        while True:
                            chunk = r.read(1 << 16)
                            if not chunk:
                                break
                            temp_size += len(chunk)
                            f.write(chunk)
                            self._progress(temp_size)
            except (urllib.error.URLError, OSError) as e:
                if attempt == self.max_retries - 1:
                    raise
                wait = 2.0 * (attempt + 1)
                print(f"\ndownload interrupted ({e!r}); retrying in {wait:.0f}s")
                time.sleep(wait)
            else:
                if not self.total_size or temp_size >= self.total_size:
                    break
        print()
        return self.file_path


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--name", required=True,
                   help="released model name, e.g. rl_model or sl_model")
    p.add_argument("--out", default="",
                   help="output path (default: ./<name>.pth)")
    p.add_argument("--url", default="",
                   help="override the download URL entirely")
    args = p.parse_args()

    model_name = args.name if args.name.endswith(".pth") else args.name + ".pth"
    url = args.url or DEFAULT_URL.format(name=model_name)
    path = args.out or os.path.join(os.getcwd(), model_name)
    print(f"downloading {url} -> {path}")
    Downloader(url, path, timeout=60.0).download()


if __name__ == "__main__":
    main()
