"""Supervised-learning launcher.

Role parity with the reference (reference: distar/bin/sl_train.py:28-50):
three roles —
  learner        train on decoded-replay data: a local ReplayDataset dir
                 (--data), trajectories pulled off the Adapter data plane
                 from remote replay actors (--remote), or — with neither —
                 schema-complete fake batches (the reference FakeDataloader
                 path);
  replay_actor   shard a replay list over SLURM tasks × workers, decode via
                 the two-pass SC2 decoder, push to the learner
                 (reference replay_actor.py);
  coordinator    the metadata broker both sides register with.
"""
from __future__ import annotations

import argparse

from .. import plugins
from ..utils import read_config
from .rl_train import (
    _addr, _dynamics_cfg, _init_health, _mesh_kwargs, _restart_policy,
    _run_learner_supervised,
)


def _learner(args) -> None:
    from .rl_train import SMOKE_MODEL

    _init_health(
        args, roles=("learner", "trace"), source="sl_learner",
        shipper_addr=_addr(args.coordinator_addr) if args.remote else None,
    )
    user_cfg = read_config(args.config) if args.config else {}
    model_cfg = user_cfg.get("model", SMOKE_MODEL if args.smoke_model else {})
    learner = plugins.load_component(args.pipeline, "SLLearner")(
        {
            "common": {"experiment_name": args.experiment_name,
                       **({"save_path": args.save_path}
                          if args.save_path else {})},
            "learner": {
                "batch_size": args.batch_size,
                "unroll_len": args.traj_len,
                "log_freq": max(args.iters // 4, 1),
                "save_freq": 10 ** 9,
                "sharded_ckpt": (
                    bool(args.mesh) if args.sharded_ckpt is None
                    else bool(args.sharded_ckpt)
                ),
                **_dynamics_cfg(args),
            },
            "model": model_cfg,
        },
        **_mesh_kwargs(args),
    )
    if args.data:
        from ..learner.sl_dataloader import ReplayDataset, SLDataloader

        learner.set_dataloader(
            SLDataloader(ReplayDataset(args.data), args.batch_size, args.traj_len)
        )
    elif args.remote:
        from ..comm import Adapter
        from ..learner.replay_actor import RemoteSLDataloader

        adapter = Adapter(coordinator_addr=_addr(args.coordinator_addr))
        learner.set_dataloader(
            RemoteSLDataloader(adapter, args.batch_size, args.traj_len)
        )
    # else: the built-in fake dataloader (schema-complete random batches)
    if args.eval_data:
        # held-out metric pass every eval_freq iters (beyond the reference,
        # which only tracks train-set metrics): catches memorization that
        # train acc alone can't (tools/sl_curve.py demonstrates the split)
        import json

        from ..learner.hooks import LambdaHook
        from ..learner.sl_dataloader import ReplayDataset, SLDataloader

        eval_freq = args.eval_freq or max(args.iters // 8, 1)
        eval_batches = max(args.eval_batches, 1)  # 0 would drain an
        # infinite sampler; a bad path must fail BEFORE training starts
        eval_dataset = ReplayDataset(args.eval_data)

        def _eval(lrn):
            # SPMD: EVERY rank must run the jitted eval over the sharded
            # params (a rank-gated computation would hang the pod in the
            # first collective) — only the host-side print is rank-0
            metrics = lrn.evaluate(
                # fresh seed-2 loader per eval: the same fixed sample of
                # held-out windows every time, so the curve is comparable
                SLDataloader(eval_dataset, args.batch_size, args.traj_len,
                             seed=2),
                max_batches=eval_batches,
            )
            if getattr(lrn, "rank", 0) == 0:
                print("EVAL " + json.dumps(
                    {"iter": lrn.last_iter.val,
                     **{k: round(v, 4) for k, v in sorted(metrics.items())}}
                ), flush=True)

        learner.hooks.add(LambdaHook("holdout_eval", "after_iter", _eval,
                                     freq=eval_freq))
    if not getattr(args, "no_supervise", False):
        # restarted SL learner processes resume from their durable pointer
        learner.resume_latest()
    _run_learner_supervised(args, learner, args.iters)
    print(
        f"sl_train done: {learner.last_iter.val} iters, "
        f"loss={learner.variable_record.get('total_loss').avg:.4f}, "
        f"action_type_acc={learner.variable_record.get('action_type_acc').avg:.4f}"
    )


def _replay_actor(args) -> None:
    import os

    from ..comm import Adapter
    from ..learner.replay_actor import ReplayActor

    # no replay-specific rules yet, but shipping makes decode throughput
    # visible in the broker's fleet view (/timeseries per source)
    _init_health(args, roles=("trace",), source=f"replay_actor:{os.getpid()}",
                 shipper_addr=_addr(args.coordinator_addr))
    decoder_cls = plugins.load_component(args.pipeline, "ReplayDecoder")
    coordinator = _addr(args.coordinator_addr)

    def run_actor():
        ReplayActor(
            replays=args.replays,
            adapter_factory=lambda: Adapter(coordinator_addr=coordinator),
            decoder_factory=lambda: decoder_cls(cfg={}),
            num_workers=args.num_workers,
            epochs=args.epochs,
        ).run()

    if getattr(args, "no_supervise", False):
        run_actor()
    else:
        from ..resilience import supervise_call

        supervise_call(run_actor, op="replay_actor", policy=_restart_policy(args))


def _coordinator(args) -> None:
    import time

    from ..comm import CoordinatorServer

    # broker-side rulebook over shipped telemetry (the fleet view)
    _init_health(args, roles=("learner", "actor", "coordinator", "trace"),
                 source="coordinator")
    server = CoordinatorServer(port=_addr(args.coordinator_addr)[1])
    server.start()
    print(f"coordinator serving on {server.host}:{server.port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--type", default="learner",
                   choices=("learner", "replay_actor", "coordinator"))
    p.add_argument("--config", default="")
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--traj-len", type=int, default=None)
    p.add_argument("--experiment-name", default="sl_train")
    p.add_argument("--save-path", default="",
                   help="experiment root override (default "
                        "$DISTAR_EXPERIMENTS_ROOT or ./experiments/<name>)")
    p.add_argument("--mesh", default="",
                   help="device-mesh spec, e.g. 'dp=4,fsdp=2' — live-mesh "
                        "GSPMD train step + sharded checkpoints "
                        "(docs/parallel.md)")
    p.add_argument("--host-devices", type=int, default=0,
                   help="force a virtual n-device CPU platform before jax "
                        "init (multichip smoke without silicon)")
    p.add_argument("--sharded-ckpt", action="store_true", default=None,
                   help="one CRC'd blob per parameter shard + layout "
                        "manifest (default: on when --mesh is given)")
    p.add_argument("--no-sharded-ckpt", dest="sharded_ckpt",
                   action="store_false")
    p.add_argument("--data", default="",
                   help="local ReplayDataset directory (decoded trajectories)")
    p.add_argument("--eval-data", default="",
                   help="held-out ReplayDataset directory: run a no-grad "
                        "metric pass every --eval-freq iters")
    p.add_argument("--eval-freq", type=int, default=0,
                   help="held-out eval cadence (0 = iters/8)")
    p.add_argument("--eval-batches", type=int, default=8)
    p.add_argument("--remote", action="store_true",
                   help="pull trajectories from replay actors via the coordinator")
    p.add_argument("--smoke-model", action="store_true", default=True)
    p.add_argument("--full-model", dest="smoke_model", action="store_false")
    p.add_argument("--coordinator-addr", default="127.0.0.1:8422")
    p.add_argument("--pipeline", default="default",
                   help="learner implementation: 'default' or an importable "
                        "custom-pipeline module (plugins.py)")
    p.add_argument("--replays", default="", help="replay list file or directory")
    p.add_argument("--no-health", action="store_true",
                   help="disable the fleet-health subsystem (watchdog rules, "
                        "telemetry shipping, crash recorder)")
    p.add_argument("--dynamics-every", type=int, default=None,
                   help="training-dynamics gauge-export stride (learner "
                        "dynamics.every_n); 0 disables the in-jit "
                        "diagnostics tree entirely; default: config/10")
    p.add_argument("--no-supervise", action="store_true",
                   help="disable crash-restart supervision and learner "
                        "auto-resume from the latest checkpoint pointer")
    p.add_argument("--admin-port", type=int, default=None,
                   help="serve the learner admin API (status / save_ckpt "
                        "and on-demand POST /profile?steps=N trace capture; "
                        "see `opsctl profile`) on this port")
    p.add_argument("--restart-max", type=int, default=5,
                   help="restart budget per role within --restart-window-s")
    p.add_argument("--restart-window-s", type=float, default=300.0,
                   help="sliding window for the restart budget")
    p.add_argument("--num-workers", type=int, default=1)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--platform", default="auto", choices=("auto", "cpu", "tpu"),
                   help="jax backend; cpu must be pinned via jax.config "
                        "(this image selects the TPU at interpreter start, "
                        "so JAX_PLATFORMS=cpu alone is too late)")
    args = p.parse_args()
    if args.host_devices:
        # must precede ANY jax backend init (device query) in this process
        from ..parallel.executor import force_host_devices

        force_host_devices(args.host_devices,
                           cache_base="/tmp/jax_cache_distar_tpu")
    elif args.platform != "auto":
        import jax

        jax.config.update("jax_platforms", args.platform)
        from ..utils.compile_cache import configure as _cc
        _cc(jax, "/tmp/jax_cache_distar_tpu")
    user_cfg = read_config(args.config) if args.config else {}
    learner_cfg = user_cfg.get("learner", {})
    if args.batch_size is None:
        args.batch_size = int(learner_cfg.get("batch_size", 2))
    if args.traj_len is None:
        args.traj_len = int(learner_cfg.get("unroll_len", 8))

    if args.type == "learner":
        _learner(args)
    elif args.type == "replay_actor":
        _replay_actor(args)
    else:
        _coordinator(args)


if __name__ == "__main__":
    main()
