"""Supervised-learning launcher.

Role parity with the reference (reference: distar/bin/sl_train.py:28-50):
learner / replay-actor roles. Until the SC2 replay decoder lands, --fake-data
drives the learner with schema-complete batches (the reference's
FakeDataloader path) — same model, loss, and meters as real training.
"""
from __future__ import annotations

import argparse

from ..learner import SLLearner
from ..utils import read_config


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="")
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--traj-len", type=int, default=8)
    p.add_argument("--experiment-name", default="sl_train")
    p.add_argument("--fake-data", action="store_true", default=True)
    p.add_argument("--smoke-model", action="store_true", default=True)
    p.add_argument("--full-model", dest="smoke_model", action="store_false")
    args = p.parse_args()

    from .rl_train import SMOKE_MODEL

    user_cfg = read_config(args.config) if args.config else {}
    model_cfg = user_cfg.get("model", SMOKE_MODEL if args.smoke_model else {})
    learner = SLLearner(
        {
            "common": {"experiment_name": args.experiment_name},
            "learner": {
                "batch_size": args.batch_size,
                "unroll_len": args.traj_len,
                "log_freq": max(args.iters // 4, 1),
                "save_freq": 10 ** 9,
            },
            "model": model_cfg,
        }
    )
    learner.run(max_iterations=args.iters)
    print(
        f"sl_train done: {learner.last_iter.val} iters, "
        f"loss={learner.variable_record.get('total_loss').avg:.4f}, "
        f"action_type_acc={learner.variable_record.get('action_type_acc').avg:.4f}"
    )


if __name__ == "__main__":
    main()
