"""Serve entrypoint: stand up the inference gateway + both frontends.

``python -m distar_tpu.bin.serve --mock`` runs the full serving stack on
the CPU mock engine (smoke/deploy-shape checks, loadgen targets);
``--checkpoint <storage url>`` serves a real model — the checkpoint loads
through the versioned registry, warms up (one compiled ``sample_action``
batch), and activates before the frontends accept traffic. At runtime new
versions hot-swap through POST /serve/load + /serve/swap (or the TCP
``load``/``swap`` ops) with zero downtime.

Fleet membership: ``--coordinator-addr host:port`` registers this
gateway's TCP data-plane endpoint under the ``serve_gateway`` token with
lease/heartbeat keep-alive, so serve-fleet routers (``serve.fleet``),
``opsctl status`` and the rollout controller discover it; dying (or
draining) gateways fall out of fresh maps when the lease lapses.

Player multiplexing: ``--players MP0,MP1`` (mock) or repeated
``--player-checkpoint PLAYER=URL`` (real models) serve several player
models behind this ONE address (``GatewayMux``) — requests route by the
wire ``player`` field; clients that send none get the first player.

Shutdown (SIGTERM/SIGINT) is drain-then-stop: frontends stop accepting,
admitted requests flush, then the process exits.
"""
from __future__ import annotations

import argparse
import os
import signal
import threading

from ..utils.log import TextLogger


def build_engine(args, checkpoint=None):
    """Engine + (optional) registry load_fn for the chosen model."""
    from ..serve import BatchedInferenceEngine, MockModelEngine

    if args.mock:
        return MockModelEngine(args.slots, delay_s=args.mock_delay_s), None

    from ..actor.inference import BatchedInference
    from ..model import Model, default_model_config
    from ..serve.registry import default_load_fn
    from ..utils import deep_merge_dicts, read_config

    model_cfg = default_model_config()
    if args.config:
        model_cfg = deep_merge_dicts(model_cfg, read_config(args.config).get("model", {}))
    model = Model(model_cfg)
    params = default_load_fn(checkpoint or args.checkpoint)
    infer = BatchedInference(model, params, args.slots, seed=args.seed)
    return BatchedInferenceEngine(infer), default_load_fn


def build_gateway(args, checkpoint=None):
    """One ``InferenceGateway`` serving one model (the per-player unit)."""
    from ..serve import InferenceGateway, ModelRegistry

    engine, load_fn = build_engine(args, checkpoint=checkpoint)
    gateway = InferenceGateway(
        engine,
        max_batch=args.slots,
        max_delay_s=args.max_delay_ms / 1000.0,
        queue_capacity=args.queue_capacity,
        idle_ttl_s=args.idle_ttl_s,
    )
    if load_fn is not None:
        # re-register the checkpoint through the registry so later hot-swaps
        # and the already-loaded boot version share one version table
        gateway.registry = ModelRegistry(load_fn=load_fn, warmup_fn=gateway._warmup)
        gateway.load_version(args.version, source=checkpoint or args.checkpoint,
                             activate=True)
    else:
        # mock: register a boot version too (gateway_proc parity) so the
        # fleet rollout always has a rollback target and status shows a
        # real generation instead of the engine's v0 default
        gateway.load_version(args.version,
                             params={"version": args.version, "bias": 0.0},
                             activate=True)
    return gateway


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--http-port", type=int, default=8000)
    p.add_argument("--tcp-port", type=int, default=8001)
    p.add_argument("--slots", type=int, default=32, help="batch lanes = max live sessions")
    p.add_argument("--max-delay-ms", type=float, default=5.0, help="flush deadline")
    p.add_argument("--queue-capacity", type=int, default=256)
    p.add_argument("--idle-ttl-s", type=float, default=300.0, help="session idle eviction")
    p.add_argument("--checkpoint", help="storage URL of the checkpoint to serve")
    p.add_argument("--version", default="v1", help="registry name for --checkpoint")
    p.add_argument("--config", help="yaml with a model: section (must match the checkpoint)")
    p.add_argument("--mock", action="store_true", help="CPU mock engine (no jax/model)")
    p.add_argument("--mock-delay-s", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--drain-timeout-s", type=float, default=30.0)
    p.add_argument("--players", default="",
                   help="mock multiplexing: comma list of player ids served "
                        "behind this one address (each gets its own mock "
                        "engine + registry)")
    p.add_argument("--player-checkpoint", action="append", default=[],
                   metavar="PLAYER=URL",
                   help="real-model multiplexing: serve PLAYER from URL "
                        "behind this one address (repeatable; first named "
                        "player is the default for legacy clients)")
    p.add_argument("--coordinator-addr", default="",
                   help="register this gateway under the serve_gateway "
                        "token at host:port (lease/heartbeat; routers and "
                        "opsctl discover the fleet there)")
    p.add_argument("--lease-s", type=float, default=10.0,
                   help="registration lease TTL (stop heartbeating = "
                        "evicted from the fleet map)")
    p.add_argument("--no-health", action="store_true",
                   help="disable the fleet-health subsystem (watchdog rules, "
                        "TSDB, crash recorder)")
    p.add_argument("--no-trace", action="store_true",
                   help="disable distributed-tracing span minting on the "
                        "request path (the overhead A/B posture; tail "
                        "sampling bounds retention when left on)")
    p.add_argument("--telemetry-interval-s", type=float, default=5.0,
                   help="cadence of registry-snapshot + tail-sampled-trace "
                        "shipping to the coordinator (requires "
                        "--coordinator-addr; 0 disables)")
    p.add_argument("--transport", default="auto",
                   choices=("auto", "shm", "tcp"),
                   help="TCP-frontend transport policy: auto/shm negotiate "
                        "shared-memory rings with colocated clients (the "
                        "socket stays as control channel + fallback), tcp "
                        "refuses rings (cross-host posture)")
    args = p.parse_args()
    if args.no_trace:
        from ..obs import set_tracing

        set_tracing(False)
    player_ckpts = dict(s.split("=", 1) for s in args.player_checkpoint)
    if not args.mock and not args.checkpoint and not player_ckpts:
        p.error("--checkpoint (or --player-checkpoint) is required unless --mock")
    if args.players and not args.mock:
        p.error("--players is the mock multiplexer; use --player-checkpoint "
                "PLAYER=URL for real models")

    from ..learner.base_learner import experiments_root

    serve_dir = os.path.join(experiments_root(), "serve")
    logger = TextLogger(serve_dir, "serve")

    # fleet health: serve rulebook (shed-rate + request-trace SLO), TSDB
    # behind GET /healthz /alerts /timeseries on the HTTP frontend, crash
    # flight recorder bundling to the experiment dir
    if not args.no_health:
        from ..obs import default_rulebook, init_fleet_health

        fleet = init_fleet_health(rules=default_rulebook(("serve", "trace")),
                                  source="serve")
        fleet.recorder.install_crash_hook(
            os.path.join(serve_dir, "flight"), config=vars(args)
        )

    from ..serve import GatewayMux, ServeHTTPServer, ServeTCPServer

    players = [s.strip() for s in args.players.split(",") if s.strip()]
    if player_ckpts:
        target = GatewayMux({pl: build_gateway(args, checkpoint=url)
                             for pl, url in player_ckpts.items()})
        players = sorted(player_ckpts)
    elif players:
        target = GatewayMux({pl: build_gateway(args) for pl in players})
    else:
        target = build_gateway(args)
    target.start()

    http = ServeHTTPServer(target, host=args.host, port=args.http_port).start()
    tcp = ServeTCPServer(target, host=args.host, port=args.tcp_port,
                         transport=args.transport).start()

    beat = None
    if args.coordinator_addr:
        from ..comm.discovery import unregister_endpoint
        from ..serve.fleet import register_gateway

        chost, _, cport = args.coordinator_addr.rpartition(":")
        coord = (chost or "127.0.0.1", int(cport))
        beat = register_gateway(
            coord, tcp.host, tcp.port,
            meta={"players": players, "slots": args.slots,
                  "http_port": http.port, "version": args.version,
                  "mock": bool(args.mock)},
            lease_s=args.lease_s or None,
        )

        def _deregister(beat=beat, coord=coord, host=tcp.host, port=tcp.port):
            beat.stop_event.set()
            unregister_endpoint(coord, host, port)

        # graceful drain's step 1 (begin_drain calls it): leave discovery
        # NOW so routers stop pinning new sessions here, instead of
        # heartbeating on until the lease dies
        target.deregister = _deregister

    shipper = None
    if args.coordinator_addr and args.telemetry_interval_s > 0:
        # ship registry snapshots + tail-sampled request traces + latency
        # exemplars to the broker: the coordinator's rulebook sees this
        # gateway's latency series, and its trace store can answer
        # "show me THIS slow request" across the fleet (opsctl trace)
        from ..obs import TelemetryShipper

        shipper = TelemetryShipper(
            source=f"serve:{tcp.port}", coordinator_addr=coord,
            interval_s=args.telemetry_interval_s,
            endpoint=f"{tcp.host}:{tcp.port}",
        ).start()
    logger.info(
        f"serving: http={http.host}:{http.port} tcp={tcp.host}:{tcp.port} "
        f"slots={args.slots} max_delay={args.max_delay_ms}ms "
        f"players={players or ['<single>']} "
        f"{'mock' if args.mock else (args.checkpoint or player_ckpts)}"
        + (f" registered@{args.coordinator_addr}" if beat else "")
    )

    done = threading.Event()

    def _shutdown(sig, frame):
        logger.info(f"signal {sig}: draining")
        done.set()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    done.wait()
    # begin_drain (inside drain_and_stop) deregisters the lease first —
    # the fleet stops routing here immediately, not a lease TTL later
    if shipper is not None:
        shipper.stop()
    if beat is not None:
        beat.stop_event.set()
    http.stop()
    tcp.stop()
    target.drain_and_stop(args.drain_timeout_s)
    logger.info("drained; bye")


if __name__ == "__main__":
    main()
