"""Generate strategy-statistics (Z) libraries.

Role parity with the reference gen_z (reference: distar/bin/gen_z.py —
decodes *winning* replays into building-order + cumulative-stat targets
keyed by map/matchup/born-location). Three sources:

  --replays DIR     decode .SC2Replay files with the two-pass decoder's
                    Z-only pass (envs/replay_decoder.decode_z) — requires
                    the SC2 client (or a fake server via DISTAR_SC2_PORT)
  --input FILE      aggregate episode-summary JSONL records (one episode per
                    line, as emitted by the actor's episode logger)
  --demo            synthetic entries for smoke tests

Usage:
  python -m distar_tpu.bin.gen_z --replays path/to/replays --output my_z.json
  python -m distar_tpu.bin.gen_z --input episodes.jsonl --output my_z.json
"""
from __future__ import annotations

import argparse
import json
import os

import numpy as np

from ..lib import actions as ACT
from ..lib.z_library import build_z_library, save_z_library


def decode_replay_episodes(replay_dir: str, min_mmr: int = 0, workers: int = 1):
    """Decode every replay-player in ``replay_dir`` into episode summaries
    (the reference's path_queue/worker_loop pipeline, gen_z.py:49-107;
    worker parallelism comes from running several gen_z processes over
    disjoint shards, the replay_actor pattern)."""
    from ..envs.replay_decoder import ReplayDecoder

    del workers
    provider = None
    cfg = {"parse_race": "ZTP"}
    port = os.environ.get("DISTAR_SC2_PORT")
    if port:
        # an already-running SC2 endpoint (or fake_sc2 server) instead of
        # launching binaries; external_endpoint keeps close() from quitting it
        from ..envs.sc2.remote_controller import RemoteController

        provider = lambda version: RemoteController("127.0.0.1", int(port))  # noqa: E731
        cfg["external_endpoint"] = True
    decoder = ReplayDecoder(cfg=cfg, controller_provider=provider)
    episodes = []
    paths = sorted(
        os.path.join(replay_dir, f)
        for f in os.listdir(replay_dir)
        if f.lower().endswith(".sc2replay")
    )
    try:
        for path in paths:
            for player_index in (0, 1):
                ep = decoder.decode_z(path, player_index)
                if ep is None:
                    continue
                if min_mmr and ep.get("mmr", 0) < min_mmr:
                    continue
                episodes.append(ep)
                print(f"gen_z: {path} p{player_index} -> {ep['mix_race']}@{ep['born_location']}")
    finally:
        decoder.close()
    return episodes


def demo_episodes(n: int = 8, seed: int = 0):
    rng = np.random.default_rng(seed)
    eps = []
    for i in range(n):
        n_bo = int(rng.integers(5, 20))
        eps.append(
            {
                "map_name": "KairosJunction",
                "mix_race": "zerg",
                "born_location": int(rng.choice([22, 38 * 160 + 140])),
                "winloss": int(rng.choice([-1, 1])),
                "beginning_order": rng.integers(
                    1, ACT.NUM_BEGINNING_ORDER_ACTIONS, n_bo
                ).tolist(),
                "bo_location": rng.integers(0, 152 * 160, n_bo).tolist(),
                "cumulative_stat": rng.integers(
                    1, ACT.NUM_CUMULATIVE_STAT_ACTIONS, 15
                ).tolist(),
                "game_loop": int(rng.integers(5000, 30000)),
            }
        )
    return eps


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--input", default="", help="episodes JSONL")
    p.add_argument("--replays", default="", help="directory of .SC2Replay files")
    p.add_argument("--output", required=True)
    p.add_argument("--min-winloss", type=int, default=1)
    p.add_argument("--min-mmr", type=int, default=0)
    p.add_argument("--demo", action="store_true")
    args = p.parse_args(argv)

    if args.demo:
        episodes = demo_episodes()
    elif args.replays:
        episodes = decode_replay_episodes(args.replays, min_mmr=args.min_mmr)
    else:
        with open(args.input) as f:
            episodes = [json.loads(line) for line in f if line.strip()]
    lib = build_z_library(episodes, min_winloss=args.min_winloss)
    save_z_library(lib, args.output)
    n = sum(
        len(entries)
        for races in lib.values()
        for locs in races.values()
        for entries in locs.values()
    )
    print(f"gen_z: wrote {n} entries to {args.output}")


if __name__ == "__main__":
    main()
