"""Generate strategy-statistics (Z) libraries from recorded episodes.

Role parity with the reference gen_z (reference: distar/bin/gen_z.py —
decodes *winning* replays into building-order + cumulative-stat targets
keyed by map/matchup/born-location). Replay decoding requires the SC2
client; until that binding lands this tool aggregates episode summary
records (JSONL, one episode per line, as emitted by the actor's episode
logger or any external decoder) into the same library format.

Usage:
  python -m distar_tpu.bin.gen_z --input episodes.jsonl --output my_z.json
  python -m distar_tpu.bin.gen_z --demo --output demo_z.json   # synthetic
"""
from __future__ import annotations

import argparse
import json

import numpy as np

from ..lib import actions as ACT
from ..lib.z_library import build_z_library, save_z_library


def demo_episodes(n: int = 8, seed: int = 0):
    rng = np.random.default_rng(seed)
    eps = []
    for i in range(n):
        n_bo = int(rng.integers(5, 20))
        eps.append(
            {
                "map_name": "KairosJunction",
                "mix_race": "zerg",
                "born_location": int(rng.choice([22, 38 * 160 + 140])),
                "winloss": int(rng.choice([-1, 1])),
                "beginning_order": rng.integers(
                    1, ACT.NUM_BEGINNING_ORDER_ACTIONS, n_bo
                ).tolist(),
                "bo_location": rng.integers(0, 152 * 160, n_bo).tolist(),
                "cumulative_stat": rng.integers(
                    1, ACT.NUM_CUMULATIVE_STAT_ACTIONS, 15
                ).tolist(),
                "game_loop": int(rng.integers(5000, 30000)),
            }
        )
    return eps


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--input", default="", help="episodes JSONL")
    p.add_argument("--output", required=True)
    p.add_argument("--min-winloss", type=int, default=1)
    p.add_argument("--demo", action="store_true")
    args = p.parse_args()

    if args.demo:
        episodes = demo_episodes()
    else:
        with open(args.input) as f:
            episodes = [json.loads(line) for line in f if line.strip()]
    lib = build_z_library(episodes, min_winloss=args.min_winloss)
    save_z_library(lib, args.output)
    n = sum(
        len(entries)
        for races in lib.values()
        for locs in races.values()
        for entries in locs.values()
    )
    print(f"gen_z: wrote {n} entries to {args.output}")


if __name__ == "__main__":
    main()
