"""Replay-fleet ops tool: sharded decode with throughput + memory telemetry.

Role parity with the reference's fleet-scale replay tooling (reference:
distar/pysc2/bin/replay_actions.py — process-parallel decode over a replay
shard with per-replay stats; benchmark_replay.py — decode steps/s;
mem_leak_check.py — RSS growth over repeated games). One CLI on top of the
production ReplayActor sharding (learner/replay_actor.py: SLURM task x
worker sharding) that decodes N replays and reports:

  * decode frames/s (observation steps produced per second, the number that
    sizes a 1,792-core replay fleet for SL training)
  * per-replay success/failure counts with the first error lines
  * RSS over time for this process tree (self + SC2 children), with a
    linear-fit MB/min slope — the mem-leak verdict

Usage:
  python -m distar_tpu.bin.replay_fleet --replays DIR_OR_LIST [--workers N]
      [--epochs K] [--parse-race ZTP] [--filter-actions] [--fake-decoder]

``--fake-decoder`` swaps the SC2-client decoder for a synthetic one (labelled
in the report) so the harness itself can be exercised on hosts without the
game binary.
"""
from __future__ import annotations

import argparse
import json
import os
import threading
import time
from typing import Dict, List, Optional


def process_tree_rss_mb(root_pid: Optional[int] = None) -> float:
    """Total RSS (MB) of ``root_pid`` and every descendant, via /proc (SC2
    clients are child processes; their memory is the leak that matters)."""
    root_pid = root_pid if root_pid is not None else os.getpid()
    children: Dict[int, List[int]] = {}
    rss_pages: Dict[int, int] = {}
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        pid = int(entry)
        try:
            with open(f"/proc/{pid}/stat") as f:
                stat = f.read()
        except OSError:
            continue
        # field 4 = ppid, field 24 = rss (pages); comm may contain spaces,
        # so split after the closing paren
        after = stat.rpartition(")")[2].split()
        try:
            ppid, rss = int(after[1]), int(after[21])
        except (IndexError, ValueError):
            continue
        children.setdefault(ppid, []).append(pid)
        rss_pages[pid] = rss
    total, stack, seen = 0, [root_pid], set()
    while stack:
        pid = stack.pop()
        if pid in seen:
            continue
        seen.add(pid)
        total += rss_pages.get(pid, 0)
        stack.extend(children.get(pid, []))
    return total * os.sysconf("SC_PAGE_SIZE") / 1e6


class _StatsSink:
    """Adapter-shaped sink: counts trajectories/frames instead of shipping
    them (ReplayActor pushes here)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.trajectories = 0
        self.frames = 0

    def push(self, token, steps, **kwargs) -> None:
        with self.lock:
            self.trajectories += 1
            self.frames += len(steps)


class _RssSampler(threading.Thread):
    def __init__(self, interval_s: float = 5.0):
        super().__init__(daemon=True)
        self.interval_s = interval_s
        self.samples: List[tuple] = []  # (t, rss_mb)
        self._halt = threading.Event()

    def run(self) -> None:
        t0 = time.time()
        while not self._halt.is_set():
            self.samples.append((time.time() - t0, process_tree_rss_mb()))
            self._halt.wait(self.interval_s)
        self.samples.append((time.time() - t0, process_tree_rss_mb()))

    def stop(self) -> None:
        self._halt.set()

    def report(self) -> dict:
        if not self.samples:
            return {}
        ts = [s[0] for s in self.samples]
        rss = [s[1] for s in self.samples]
        out = {
            "start_mb": round(rss[0], 1),
            "peak_mb": round(max(rss), 1),
            "end_mb": round(rss[-1], 1),
            "samples": len(rss),
        }
        # least-squares slope in MB/min — the mem-leak verdict (role of
        # reference mem_leak_check.py's before/after RSS comparison)
        if len(rss) >= 2 and ts[-1] > ts[0]:
            n = len(rss)
            mt, mr = sum(ts) / n, sum(rss) / n
            denom = sum((t - mt) ** 2 for t in ts)
            if denom > 0:
                slope = sum((t - mt) * (r - mr) for t, r in zip(ts, rss)) / denom
                out["slope_mb_per_min"] = round(slope * 60, 2)
        return out


class _FakeDecoder:
    """Synthetic decoder for harness smoke tests (no SC2 binary): emits
    step-dicts at a deterministic rate."""

    def __init__(self, steps_per_replay: int = 64, delay_s: float = 0.0):
        self.steps_per_replay = steps_per_replay
        self.delay_s = delay_s

    def run(self, path, player_idx):
        if self.delay_s:
            time.sleep(self.delay_s)
        if "corrupt" in os.path.basename(path):
            raise ValueError(f"synthetic corrupt replay: {path}")
        return [{"replay": path, "player": player_idx, "i": i} for i in range(self.steps_per_replay)]

    def close(self):
        pass


def run_fleet(
    replays,
    workers: int = 2,
    epochs: int = 1,
    decoder_factory=None,
    rss_interval_s: float = 5.0,
    ntasks: Optional[int] = None,
    proc_id: Optional[int] = None,
    decoder_cfg: Optional[dict] = None,
    pipeline: str = "default",
) -> dict:
    """Decode a replay shard and return the telemetry report (the CLI body,
    callable in-process for tests)."""
    from ..learner.replay_actor import ReplayActor

    fake = decoder_factory is not None
    if decoder_factory is None:
        from .. import plugins

        decoder_cls = plugins.load_component(pipeline, "ReplayDecoder")

        def decoder_factory():
            return decoder_cls(cfg=decoder_cfg or {})

    sink = _StatsSink()
    sampler = _RssSampler(rss_interval_s)
    actor = ReplayActor(
        replays,
        adapter_factory=lambda: sink,
        decoder_factory=decoder_factory,
        num_workers=workers,
        epochs=epochs,
        ntasks=ntasks,
        proc_id=proc_id,
    )
    n_replays = len(actor._paths)
    sampler.start()
    t0 = time.perf_counter()
    actor.run()
    wall = time.perf_counter() - t0
    sampler.stop()
    sampler.join(timeout=5)
    return {
        "metric": "replay-decode frames/s (fleet shard)",
        "value": round(sink.frames / wall, 2) if wall > 0 else 0.0,
        "unit": "frames/s",
        "replays": n_replays,
        "workers": workers,
        "trajectories": sink.trajectories,
        # counted at the source: raising decodes vs legitimately-empty ones
        # (race-filtered players are empty, not failed)
        "failed_decodes": actor.failed,
        "empty_decodes": actor.empty,
        "frames": sink.frames,
        "wall_s": round(wall, 2),
        "rss": sampler.report(),
        "decoder": "fake (harness smoke)" if fake else "sc2",
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--replays", required=True, help="replay dir or list file")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--parse-race", default="ZTP", help="races to decode (e.g. Z)")
    p.add_argument("--filter-actions", action="store_true",
                   help="de-dupe keyboard-spam actions (reference FilterActions)")
    p.add_argument("--rss-interval", type=float, default=5.0)
    p.add_argument("--ntasks", type=int, default=None, help="override SLURM_NTASKS")
    p.add_argument("--pipeline", default="default",
                   help="decoder implementation: 'default' or an importable "
                        "custom-pipeline module (plugins.py)")
    p.add_argument("--proc-id", type=int, default=None, help="override SLURM_PROCID")
    p.add_argument("--fake-decoder", action="store_true",
                   help="synthetic decoder (no SC2): harness smoke only")
    args = p.parse_args(argv)
    report = run_fleet(
        args.replays,
        workers=args.workers,
        epochs=args.epochs,
        decoder_factory=(lambda: _FakeDecoder()) if args.fake_decoder else None,
        rss_interval_s=args.rss_interval,
        ntasks=args.ntasks,
        proc_id=args.proc_id,
        decoder_cfg={"parse_race": args.parse_race,
                     "filter_action": args.filter_actions},
        pipeline=args.pipeline,
    )
    print(json.dumps(report))


if __name__ == "__main__":
    main()
