"""RL training launcher.

Role parity with the reference launcher (reference: distar/bin/
rl_train.py:19-162): spawns the four roles — coordinator, league, learner,
actor — either all-in-one (small-scale/smoke, mock env) or a single role for
multi-host runs (league/coordinator serve HTTP; learners/actors connect by
address).

Usage:
  python -m distar_tpu.bin.rl_train --type all --iters 4        # smoke loop
  python -m distar_tpu.bin.rl_train --type league --port 8421
  python -m distar_tpu.bin.rl_train --type learner --player-id MP0 ...
"""
from __future__ import annotations

import argparse
import json
import os
import time
from typing import Optional

from ..actor import Actor
from ..comm import Adapter, Coordinator, CoordinatorServer
from ..envs import MockEnv
from ..league import League, LeagueAPIServer
from .. import plugins
from ..learner.rl_dataloader import RLDataLoader
from ..resilience import AlertRemediator, RestartPolicy, Supervisor, supervise_call
from ..utils import read_config

SMOKE_MODEL = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}


def _model_cfg(args) -> dict:
    user_cfg = read_config(args.config) if args.config else {}
    return user_cfg.get("model", SMOKE_MODEL if args.smoke_model else {})


def _resolve_args(args) -> None:
    """Fill CLI sentinels from the user config's learner/actor blocks —
    explicit CLI flags win, then config, then defaults (the reference's
    deep-merge cascade applied to the launcher surface)."""
    user_cfg = read_config(args.config) if args.config else {}
    learner_cfg = user_cfg.get("learner", {})
    actor_cfg = user_cfg.get("actor", {})
    if args.batch_size is None:
        args.batch_size = int(learner_cfg.get("batch_size", 4))
    if args.traj_len is None:
        args.traj_len = int(learner_cfg.get("unroll_len", actor_cfg.get("traj_len", 4)))
    if args.env_num is None:
        args.env_num = int(actor_cfg.get("env_num", 2))


def _jaxenv_cfgs(args):
    """(EnvConfig, ScenarioConfig) from the --jaxenv-* CLI knobs."""
    from ..envs.jaxenv import EnvConfig, ScenarioConfig

    u = args.jaxenv_units
    return (EnvConfig(units_per_squad=u),
            ScenarioConfig(units_per_squad=u, max_units=u,
                           episode_len=args.jaxenv_episode_len))


def _env_fn(args):
    """Env factory: ``--env`` wins, then the user config's env block
    (``env.type: sc2`` launches real games through the client layer;
    ``jaxenv`` is the pure-JAX micro-battle world through the host
    adapter); the default mock env keeps game-free smoke loops working."""
    user_cfg = read_config(args.config) if args.config else {}
    env_cfg = dict(user_cfg.get("env", {}))
    env_type = getattr(args, "env", "") or env_cfg.pop("type", "mock")
    env_cfg.pop("type", None)
    if env_type == "sc2":
        from ..envs.sc2.launcher import make_sc2_env

        return lambda: make_sc2_env({"env": env_cfg})
    if env_type == "jaxenv":
        from ..envs.jaxenv import JaxMicroBattleEnv

        jcfg, scfg = _jaxenv_cfgs(args)
        return lambda: JaxMicroBattleEnv(jcfg, scfg)
    return lambda: MockEnv(episode_game_loops=args.episode_game_loops)


def _dynamics_cfg(args) -> dict:
    """--dynamics-every N -> the learner's training-dynamics block: 0
    disables the in-jit diagnostics tree entirely (the overhead A/B's off
    arm), N > 0 sets the gauge-export stride, absent keeps defaults."""
    every = getattr(args, "dynamics_every", None)
    if every is None:
        return {}
    if every <= 0:
        return {"dynamics": {"enabled": False}}
    return {"dynamics": {"every_n": every}}


def _learner_cfg(args, model_cfg: dict, load_path: str = "") -> dict:
    return {
        "common": {"experiment_name": args.experiment_name,
                   **({"save_path": args.save_path}
                      if getattr(args, "save_path", "") else {})},
        "learner": {
            "batch_size": args.batch_size,
            "unroll_len": args.traj_len,
            "log_freq": max(args.iters // 4, 1),
            "save_freq": 10 ** 9,
            # --mesh implies the distributed checkpoint layout (restorable
            # onto any other mesh shape); --sharded-ckpt/--no-sharded-ckpt
            # override either way
            "sharded_ckpt": (
                bool(getattr(args, "mesh", ""))
                if getattr(args, "sharded_ckpt", None) is None
                else bool(args.sharded_ckpt)
            ),
            **({"load_path": load_path} if load_path else {}),
            **_dynamics_cfg(args),
        },
        "model": model_cfg,
    }


def _mesh_from_args(args):
    """--mesh dp=K,fsdp=M,tp=N,sp=S -> a live jax mesh (None without the
    flag: learners build their own all-dp default). Typed MeshConfigError
    when the axes don't factor the devices."""
    if not getattr(args, "mesh", ""):
        return None
    import jax

    from ..parallel import MeshSpec, make_mesh

    spec = MeshSpec.parse(args.mesh)
    devices = None
    if spec.dp != -1:
        # fully explicit spec: claim exactly that many devices (--mesh dp=4
        # on an 8-device host means a 4-chip mesh, not a config error)
        devices = jax.devices()[: spec.dp * spec.fsdp * spec.tp * spec.sp]
    return make_mesh(spec, devices)


def _mesh_kwargs(args) -> dict:
    mesh = _mesh_from_args(args)
    return {"mesh": mesh} if mesh is not None else {}


def _init_health(args, roles, source="local", shipper_addr=None):
    """Stand up the fleet-health subsystem for this process: TSDB sampler +
    the default rulebook for the roles it hosts + the crash flight recorder
    (bundles land under <experiment>/flight). With ``shipper_addr`` the
    process additionally ships registry snapshots to the coordinator so the
    broker-side rulebook sees the whole fleet. Disable with --no-health."""
    if getattr(args, "no_health", False):
        return None
    from ..obs import TelemetryShipper, default_rulebook, init_fleet_health

    fleet = init_fleet_health(
        rules=default_rulebook(roles),
        sample_interval_s=getattr(args, "health_sample_s", 1.0),
        eval_interval_s=getattr(args, "health_eval_s", 2.0),
        source=source,
    )
    from ..learner.base_learner import experiments_root

    artifact_dir = os.path.join(
        getattr(args, "save_path", "") or os.path.join(
            experiments_root(), getattr(args, "experiment_name", "run")),
        "flight",
    )
    fleet.recorder.install_crash_hook(artifact_dir, config=vars(args))
    if shipper_addr is not None:
        TelemetryShipper(
            source, coordinator_addr=shipper_addr,
            interval_s=getattr(args, "telemetry_interval_s", 5.0),
        ).start()
    return fleet


def _restart_policy(args) -> RestartPolicy:
    return RestartPolicy(
        max_restarts=getattr(args, "restart_max", 5),
        window_s=getattr(args, "restart_window_s", 300.0),
    )


def _run_learner_supervised(args, learner, iters) -> None:
    """Foreground crash-resume for the learner role: a crash restores from
    the durable ``latest`` pointer (corrupt newest generation falls back a
    checkpoint) and re-enters the run loop, bounded by the restart budget.
    The final failure still dies loudly (flight bundle + raise)."""
    if getattr(args, "admin_port", None) is not None:
        # live admin surface: update_config / save_ckpt / status and the
        # on-demand POST /profile?steps=N capture (opsctl profile)
        admin = learner.start_admin(port=args.admin_port)
        print(f"learner admin on http://{admin.host}:{admin.port}/learner/status",
              flush=True)
    if getattr(args, "no_supervise", False):
        learner.run(max_iterations=iters)
        return

    def resume(error):
        path = learner.resume_latest()
        print(f"learner restart after {error!r}: "
              f"resume={path or 'cold'} iter={learner.last_iter.val}", flush=True)

    supervise_call(
        lambda: learner.run(max_iterations=iters),
        op="learner", policy=_restart_policy(args), on_restart=resume,
    )


def _table_config(args):
    """Per-player replay-table settings from the CLI surface (the replay
    role's table factory; every player token gets one of these)."""
    from ..replay import TableConfig

    spi = args.replay_spi
    batch = max(args.batch_size or 1, 1)
    error_buffer = args.replay_error_buffer
    if error_buffer is None and spi > 0:
        # batch-aware slack (Reverb sizes its min/max_diff to the batch the
        # same way): the limiter must be able to admit a whole learner batch
        # or sampler and inserter deadlock trading timeouts — see
        # RateLimiter.max_sample_batch
        error_buffer = max(spi, 1.0) * batch
    return TableConfig(
        max_size=args.replay_max_size,
        sampler=args.replay_sampler,
        samples_per_insert=None if spi <= 0 else spi,
        # 0 = "the learner batch size": sampling can't start below one batch
        min_size_to_sample=max(args.replay_min_size or batch, 1),
        error_buffer=error_buffer,
        max_staleness_s=args.replay_max_staleness_s or None,
    )


def _build_replay_store(args, shard_id: str = "", spill_dir: Optional[str] = None):
    """Store + spill for a serving replay role; recovery runs before serving
    so acked-but-unsampled trajectories from a crashed generation are
    resident before the first sample lands (as pre-encoded payloads, so
    re-serving them skips the recompression pass). ``shard_id`` labels this
    member's metrics/stats when it is one of a fleet."""
    from ..replay import ReplayStore, SpillRing

    _table_config(args)  # fail fast on invalid combos (e.g. fifo + spi > 1)
    spill = None
    spill_dir = args.replay_spill_dir if spill_dir is None else spill_dir
    if spill_dir:
        spill = SpillRing(spill_dir, max_items=args.replay_spill_max)
    store = ReplayStore(table_factory=lambda name: _table_config(args),
                        spill=spill, shard_id=shard_id, recover_encoded=True)
    recovered = store.recover()
    if recovered:
        print(f"replay{f' shard {shard_id}' if shard_id else ''}: recovered "
              f"{recovered} acked trajectories from spill", flush=True)
    return store


def _learner_replay_client(args, addrs: str):
    """Sample-side client for a learner: ``inproc`` -> the colocated
    zero-copy handle, one address -> a plain ``SampleClient``, several ->
    the consistent-hash fleet's fan-in sampler (per-shard breakers,
    stalled shards skip), ``discover`` -> the coordinator's shard map."""
    from ..replay import (
        LocalReplayClient, SampleClient, ShardMap, ShardedSampleClient,
        is_inproc_addr,
    )

    compress = getattr(args, "replay_compress", True)
    transport = getattr(args, "transport", "auto")
    if is_inproc_addr(addrs):
        return LocalReplayClient()
    if addrs.strip().lower() == "discover":
        shard_map = ShardMap.discover(_addr(args.coordinator_addr))
    else:
        shard_map = ShardMap.parse(addrs)
    if len(shard_map) == 1:
        return SampleClient(*_addr(shard_map.addrs[0]), compress=compress,
                            transport=transport)
    return ShardedSampleClient(shard_map, mode=args.replay_fanin,
                               compress=compress, transport=transport)


def run_replay(args) -> None:
    """Standalone replay-store role: framed-TCP data plane on --port, HTTP
    admin/stats (+ /metrics + health routes) on --metrics-port, crash-restart
    under the supervisor with spill recovery on every (re)start. With
    --coordinator-addr the shard registers under the ``replay_shard`` token
    (lease + heartbeat), so actors/learners started with ``--replay-addr
    discover`` find the whole fleet without static address lists."""
    from ..replay import ReplayAdminServer, ReplayServer, register_shard

    shard_id = args.replay_shard_id or (f":{args.port}" if args.port else "")
    _init_health(
        args, roles=("replay",), source=f"replay{shard_id}" if shard_id else "replay",
        shipper_addr=_addr(args.coordinator_addr) if args.coordinator_addr else None,
    )

    def serve_loop(ctx):
        store = _build_replay_store(args, shard_id=shard_id)
        server = ReplayServer(store, port=args.port,
                              compress=args.replay_compress,
                              transport=args.transport)
        server.start()
        admin = None
        if args.metrics_port is not None:
            admin = ReplayAdminServer(store, port=args.metrics_port,
                                      server=server)
            admin.start()
            print(f"replay admin on http://{admin.host}:{admin.port}/replay/stats",
                  flush=True)
        heartbeat = None
        if args.coordinator_addr:
            heartbeat = register_shard(
                _addr(args.coordinator_addr), server.host, server.port,
                meta={"admin_port": args.metrics_port},
                lease_s=args.lease_s or None,
            )
        print(f"replay store serving on {server.host}:{server.port}", flush=True)
        try:
            while not ctx.should_exit:
                ctx.sleep(1.0)
        finally:
            if heartbeat is not None:
                heartbeat.stop_event.set()
            server.stop()
            if admin is not None:
                admin.stop()

    if getattr(args, "no_supervise", False):
        from ..resilience import TaskContext

        serve_loop(TaskContext())
        return
    supervisor = Supervisor(policy=_restart_policy(args))
    supervisor.add("replay", serve_loop)
    supervisor.start()
    supervisor.join()


def run_arena(args) -> None:
    """Standalone arena-evaluator role: pulls checkpoint generations via
    CheckpointManager role keys, plays deterministic head-to-head batches on
    jaxenv against the coordinator-scheduled opponent, and reports results
    under idempotent match keys — crash-restart under the supervisor is
    exactly-once by construction (the store re-issues the same assignment
    until its results are applied)."""
    from ..arena import ArenaEvaluator

    _init_health(
        args, roles=("arena",), source="arena",
        shipper_addr=_addr(args.coordinator_addr) if args.coordinator_addr else None,
    )
    roles = tuple(r.strip() for r in args.arena_roles.split(",")) \
        if args.arena_roles else ("",)
    env_cfg, scenario_cfg = _jaxenv_cfgs(args)

    def serve_loop(ctx):
        evaluator = ArenaEvaluator(
            ckpt_dir=args.arena_ckpt_dir,
            model_cfg=_model_cfg(args),
            coordinator_addr=_addr(args.coordinator_addr),
            roles=roles,
            episodes=args.arena_episodes,
            env_cfg=env_cfg,
            scenario_cfg=scenario_cfg,
        )
        print(f"arena evaluator on {args.arena_ckpt_dir} "
              f"(roles={','.join(r or 'main' for r in roles)})", flush=True)
        try:
            while not ctx.should_exit:
                out = evaluator.evaluate_once()
                if out is None:
                    ctx.sleep(args.arena_interval_s)
                    continue
                a = out["assignment"]
                print(f"arena: {a['home']} vs {a['away']} r{a['round']} "
                      f"win_rate={out['result']['win_rate']:.3f} "
                      f"applied={out['ack'].get('applied')}", flush=True)
                if args.arena_batches and \
                        evaluator.batches_done >= args.arena_batches:
                    break
        finally:
            if args.arena_artifact:
                ratings = _fetch_arena_ratings(args)
                evaluator.write_artifact(args.arena_artifact, ratings=ratings)
                print(f"arena artifact written to {args.arena_artifact}",
                      flush=True)

    if getattr(args, "no_supervise", False):
        from ..resilience import TaskContext

        serve_loop(TaskContext())
        return
    supervisor = Supervisor(policy=_restart_policy(args))
    supervisor.add("arena", serve_loop)
    supervisor.start()
    supervisor.join()


def _fetch_arena_ratings(args) -> Optional[dict]:
    """GET /arena/ratings from the coordinator for the artifact ledger;
    None when the store isn't hosted there (artifact stays throughput-only)."""
    import urllib.request

    host, port = _addr(args.coordinator_addr)
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/arena/ratings", timeout=10.0) as resp:
            return json.loads(resp.read())
    except Exception:
        return None


def _maybe_serve_metrics(args, coordinator=None):
    """Start an HTTP server exposing GET /metrics for this process's registry
    when --metrics-port is given (CoordinatorServer doubles as the exporter;
    for non-broker roles its POST routes simply go unused). Returns the
    server or None."""
    if args.metrics_port is None:
        return None
    server = CoordinatorServer(coordinator=coordinator, port=args.metrics_port)
    server.start()
    print(f"metrics on http://{server.host}:{server.port}/metrics", flush=True)
    return server


def _make_learner(args, model_cfg: dict, load_path: str = ""):
    """The learner this process hosts: the RL teacher by default, or — with
    ``--distill`` — the student-tier distillation learner, which consumes
    the SAME batch stream (teacher logits already ride every flush) and
    publishes checkpoints under the ``student`` role key so teacher resume
    can never cross tiers (docs/training_guide.md distillation quickstart)."""
    if getattr(args, "distill", False):
        from ..learner import DistillLearner

        return DistillLearner(_learner_cfg(args, model_cfg, load_path=load_path))
    return plugins.load_component(args.pipeline, "RLLearner")(
        _learner_cfg(args, model_cfg, load_path=load_path), **_mesh_kwargs(args))


def run_all(args) -> None:
    """Single-process league-RL loop on the mock env (the small-scale config
    path; swaps to the real SC2 env behind the same interfaces)."""
    user_cfg = read_config(args.config) if args.config else {}
    model_cfg = _model_cfg(args)
    league = League(user_cfg)
    co = Coordinator()
    # one process hosts every role, so the full rulebook applies locally
    roles = ("learner", "actor", "coordinator", "trace") + (
        ("replay",) if args.replay else ()) + (
        ("distill",) if args.distill else ())
    fleet = _init_health(args, roles=roles)
    _maybe_serve_metrics(args, coordinator=co)
    actor_adapter = Adapter(coordinator=co)
    learner_adapter = Adapter(coordinator=co)

    # --replay: an in-process store between actor and learner — the smoke
    # configuration of the store path. Three shapes:
    #   * default: ONE real server + clients on loopback TCP;
    #   * --replay-shards N: N servers, actors route by consistent hash,
    #     the learner fans in (the fleet smoke — real sharded data plane);
    #   * --replay-fast-path: no server at all — the Sebulba colocated
    #     layout hands actor and learner a direct store handle (zero
    #     serialization on push AND sample).
    replay_servers = []
    actor_replay_cfg = {}
    if args.replay and args.replay_fast_path:
        if args.replay_shards > 1:
            raise SystemExit("--replay-fast-path is the single colocated "
                             "store; it cannot combine with --replay-shards")
        from ..replay import set_local_store

        set_local_store(_build_replay_store(args))
        actor_replay_cfg = {"replay": {"enabled": True, "addr": "inproc"}}
        print("replay store (colocated zero-copy fast path)", flush=True)
    elif args.replay:
        from ..replay import ReplayServer

        spill_root = args.replay_spill_dir
        for i in range(max(args.replay_shards, 1)):
            shard_id = f"s{i}" if args.replay_shards > 1 else ""
            spill_dir = os.path.join(spill_root, shard_id) \
                if (spill_root and shard_id) else spill_root
            store = _build_replay_store(args, shard_id=shard_id,
                                        spill_dir=spill_dir)
            replay_servers.append(
                ReplayServer(store, port=0,
                             compress=args.replay_compress,
                             transport=args.transport).start())
        addrs = ",".join(f"{s.host}:{s.port}" for s in replay_servers)
        actor_replay_cfg = {"replay": {"enabled": True, "addr": addrs,
                                       "compress": args.replay_compress,
                                       "transport": args.transport}}
        print(f"replay store{'s' if len(replay_servers) > 1 else ''} "
              f"(in-process) on {addrs}", flush=True)

    player_id = list(league.active_players.keys())[0]
    traj_len = args.traj_len
    actor = Actor(
        cfg={"actor": {"env_num": args.env_num, "traj_len": traj_len,
                       "plane": _plane_cfg(args),
                       **actor_replay_cfg}},
        league=league,
        adapter=actor_adapter,
        model_cfg=model_cfg,
        env_fn=_env_fn(args),
    )

    supervisor = Supervisor(policy=_restart_policy(args))

    def actor_loop(ctx):
        while not ctx.should_exit:
            actor.run_job(episodes=1)

    supervisor.add("actor", actor_loop)
    supervisor.start()
    if fleet is not None and not getattr(args, "no_supervise", False):
        # detect -> remediate: a firing env-starvation alert bounces the
        # actor loop instead of waiting for a human
        AlertRemediator(
            supervisor, {"actor_env_starvation": "actor"}
        ).attach(fleet.evaluator)

    learner = _make_learner(args, model_cfg)
    if args.replay:
        from ..learner.rl_dataloader import ReplayDataLoader

        loader_addr = actor_replay_cfg["replay"]["addr"]
        learner.set_dataloader(ReplayDataLoader(
            _learner_replay_client(args, loader_addr),
            player_id, args.batch_size,
        ))
    else:
        learner.set_dataloader(RLDataLoader(learner_adapter, player_id, args.batch_size))
    if not args.distill:
        # the student tier publishes via checkpoints + fleet rollout, not
        # the league's weight-push plane (its league player is the teacher)
        learner.attach_comm(learner_adapter, player_id, league=league,
                            send_model_freq=4, send_train_info_freq=4)
    _run_learner_supervised(args, learner, args.iters)
    # let the actor finish its in-flight job: a daemon thread killed inside a
    # jitted computation aborts the interpreter teardown
    supervisor.stop(timeout=120)
    for server in replay_servers:
        server.stop()
    if args.replay and args.replay_fast_path:
        from ..replay import set_local_store

        set_local_store(None)
    print(
        f"rl_train done: {learner.last_iter.val} iters, "
        f"loss={learner.variable_record.get('total_loss').avg:.4f}, "
        f"games={league.all_players[player_id].total_game_count}"
    )


def _addr(s: str):
    """``"host:port"`` -> ``(host, port)``; an HA comma list
    (``"h1:p1,h2:p2"``) passes through as ``(spec, None)``, which
    ``coordinator_request`` resolves with leadership failover."""
    if "," in s:
        return s, None
    host, _, port = s.rpartition(":")
    return host or "127.0.0.1", int(port)


def _plane_cfg(args) -> dict:
    """Rollout-plane block for the actor config (docs/serving.md): which
    backend serves policy forwards — per-actor inline (default), one shared
    in-process gateway per player (local), or a remote bin/serve gateway
    (remote, needs --plane-addr)."""
    if args.plane == "remote" and not args.plane_addr:
        raise SystemExit("--plane remote requires --plane-addr host:port "
                         "(or 'h1:p1,h2:p2', or 'discover')")
    if args.plane == "remote" and args.plane_addr == "discover" \
            and not args.coordinator_addr:
        raise SystemExit("--plane-addr discover requires --coordinator-addr "
                         "(gateways register under the serve_gateway token)")
    return {
        "backend": args.plane,
        "addr": args.plane_addr,
        "slots": args.plane_slots,
        "coordinator_addr": args.coordinator_addr or "",
        "transport": getattr(args, "transport", "auto"),
    }


def run_learner(args) -> None:
    """Standalone learner role connecting to remote league + coordinator
    (reference rl_train.py:19-53 learner_run)."""
    import os

    from ..league.remote import RemoteLeague
    from ..parallel.dist import dist_init

    info = dist_init(
        method=args.dist_method,
        coordinator_address=args.dist_coordinator_address or None,
        num_processes=args.dist_num_processes,
        process_id=args.dist_process_id,
    )
    league = RemoteLeague(*_addr(args.league_addr)) if args.league_addr else None
    adapter = Adapter(coordinator_addr=_addr(args.coordinator_addr))
    _init_health(
        args,
        roles=("learner", "trace") + (("distill",) if args.distill else ()),
        source=(f"distill:{args.player_id}:{info['rank']}" if args.distill
                else f"learner:{args.player_id}:{info['rank']}"),
        shipper_addr=_addr(args.coordinator_addr),
    )
    _maybe_serve_metrics(args)
    model_cfg = _model_cfg(args)
    load_path = ""
    if league is not None:
        reply = league.register_learner(args.player_id, rank=info["rank"],
                                        world_size=info["world_size"])
        # resume from the league-assigned player checkpoint when it exists
        # (reference learner_run loads the assigned ckpt)
        ckpt = reply.get("checkpoint_path", "")
        if ckpt and os.path.exists(ckpt):
            load_path = ckpt
    learner = _make_learner(args, model_cfg, load_path=load_path)
    if not load_path and not getattr(args, "no_supervise", False):
        # a restarted learner process (k8s/systemd) picks up its own durable
        # latest pointer before cold-starting — zero manual intervention
        learner.resume_latest()
    if args.replay_addr:
        # store-backed sampling mode: batches come from the replay table(s)
        # instead of the point-to-point pull cache — a comma-separated list
        # (or 'discover') fans in across the shard fleet (docs/data_plane.md)
        from ..learner.rl_dataloader import ReplayDataLoader

        learner.set_dataloader(ReplayDataLoader(
            _learner_replay_client(args, args.replay_addr),
            args.player_id, args.batch_size,
        ))
    else:
        learner.set_dataloader(RLDataLoader(adapter, args.player_id, args.batch_size))
    if not args.distill:
        learner.attach_comm(adapter, args.player_id, league=league)
    _run_learner_supervised(args, learner, args.iters)
    print(f"learner done: {learner.last_iter.val} iters")


def run_actor(args) -> None:
    """Standalone actor role (reference rl_train.py:54-67 actor_run)."""
    from ..league.remote import RemoteLeague

    league = RemoteLeague(*_addr(args.league_addr))
    adapter = Adapter(coordinator_addr=_addr(args.coordinator_addr))
    _init_health(
        args, roles=("actor", "trace"), source=f"actor:{os.getpid()}",
        shipper_addr=_addr(args.coordinator_addr),
    )
    _maybe_serve_metrics(args)
    model_cfg = _model_cfg(args)
    actor_cfg = {"env_num": args.env_num, "traj_len": args.traj_len,
                 "plane": _plane_cfg(args)}
    if args.replay_addr:
        replay_addr = args.replay_addr
        if replay_addr.strip().lower() == "discover":
            # resolve the fleet once at launch from the coordinator's shard
            # registrations (the actor config carries plain addresses)
            from ..replay import ShardMap

            replay_addr = ",".join(
                ShardMap.discover(_addr(args.coordinator_addr)).addrs)
            print(f"replay: discovered shard fleet {replay_addr}", flush=True)
        actor_cfg["replay"] = {"enabled": True, "addr": replay_addr,
                               "compress": args.replay_compress,
                               "transport": args.transport}
    actor = Actor(
        cfg={"actor": actor_cfg},
        league=league,
        adapter=adapter,
        model_cfg=model_cfg,
        env_fn=_env_fn(args),
    )

    def job_loop():
        while True:
            actor.run_job(episodes=1)

    if getattr(args, "no_supervise", False):
        job_loop()
    else:
        # a crashed job loop (league blip, env death) restarts with backoff
        # instead of retiring the whole actor host
        supervise_call(job_loop, op="actor", policy=_restart_policy(args))


def run_anakin(args) -> None:
    """Fused on-device training: the Anakin loop (envs/jaxenv/anakin.py)
    replaces the whole actor plane — env step + sample_action + LSTM carry
    compiled into one scanned XLA program feeding the learner directly.
    ``--batch-size`` is the number of vmapped env lanes, ``--traj-len`` the
    window length. Startup asserts the fused loop is device-pure (no
    host-callback primitives in its jaxpr) and refuses to run otherwise."""
    from ..envs.jaxenv import AnakinDataLoader, AnakinRunner

    model_cfg = _model_cfg(args)
    _init_health(args, roles=("learner", "trace"))
    _maybe_serve_metrics(args)
    learner = _make_learner(args, model_cfg)
    # no host-side prefetch on the fused path: batches are produced ON
    # DEVICE, so the feeder's look-ahead buys nothing — and its producer
    # thread would be sitting inside the NEXT window's jitted rollout when
    # run() returns (minutes at large B); a daemon thread dying inside XLA
    # at interpreter teardown aborts the process (the run_all in-flight-job
    # hazard, reached through the dataloader instead of the actor)
    learner.cfg.learner["prefetch_depth"] = 0
    jcfg, scfg = _jaxenv_cfgs(args)
    runner = AnakinRunner(
        learner.model, batch_size=args.batch_size, unroll_len=args.traj_len,
        env_cfg=jcfg, scenario_cfg=scfg)

    def live_params():
        state = getattr(learner, "_state", None)
        return state["params"] if state else None

    loader = AnakinDataLoader(runner, params_provider=live_params)
    report = runner.purity_report(loader._params(), runner.init_carry())
    print(f"anakin device purity: {report}", flush=True)
    if not report["pure"]:
        raise SystemExit(
            f"anakin loop is not device-pure: {report['offending']}")
    print(f"anakin: B={runner.B} lanes x T={runner.T} steps "
          f"({runner.B * runner.T} env steps/window), "
          f"units_per_squad={jcfg.units_per_squad}", flush=True)
    learner.set_dataloader(loader)
    _run_learner_supervised(args, learner, args.iters)
    print(f"learner done: {learner.last_iter.val} iters")


def run_league_learner(args) -> None:
    """One league learner process (league/runtime/runner.py): register with
    the coordinator-hosted matchmaker, then loop matchmade rounds — fused
    Anakin rollout with the opponent on the away seat, report matches under
    idempotent keys, record checkpoint generations into this player's
    CheckpointManager role-key lineage, and stream train-info (snapshot
    minting happens server-side)."""
    import zlib

    from ..envs.jaxenv import AnakinDataLoader, AnakinRunner
    from ..league.remote import RemoteLeagueService
    from ..league.runtime.runner import LeagueLearnerLoop
    from ..learner.base_learner import experiments_root

    player_id = args.player_id
    _init_health(
        args, roles=("learner", "trace"), source=f"league:{player_id}",
        shipper_addr=_addr(args.coordinator_addr),
    )
    _maybe_serve_metrics(args)
    remote = RemoteLeagueService(args.coordinator_addr)
    cfg = _learner_cfg(args, _model_cfg(args))
    # isolated checkpoint lineage per league player: a per-player save
    # subtree keeps file names (and logs) collision-free across concurrent
    # learners, and the role-keyed pointer file means generations can never
    # cross on resume even if lineages are later merged into one directory
    cfg["common"]["save_path"] = os.path.join(
        cfg["common"].get("save_path")
        or os.path.join(experiments_root(), args.experiment_name),
        player_id)
    cfg["learner"]["ckpt_role"] = player_id
    learner = plugins.load_component(args.pipeline, "RLLearner")(
        cfg, **_mesh_kwargs(args))
    learner.cfg.learner["prefetch_depth"] = 0  # run_anakin teardown hazard
    jcfg, scfg = _jaxenv_cfgs(args)
    runner = AnakinRunner(
        learner.model, batch_size=args.batch_size, unroll_len=args.traj_len,
        env_cfg=jcfg, scenario_cfg=scfg,
        seed=zlib.crc32(player_id.encode()) & 0x7FFFFFFF,
        opponent_seat=True)
    loop = LeagueLearnerLoop(
        player_id, remote, learner, loader=None,
        rounds=args.league_rounds,
        iters_per_round=args.league_iters_per_round)
    loader = AnakinDataLoader(
        runner,
        params_provider=lambda: (learner._state or {}).get("params"),
        opponent_provider=loop.opponent_params)
    loop.loader = loader
    learner.set_dataloader(loader)
    report = runner.purity_report(loader._params(), runner.init_carry(),
                                  loader._opponent_params())
    if not report["pure"]:
        raise SystemExit(
            f"league-learner fused loop is not device-pure: "
            f"{report['offending']}")
    if not getattr(args, "no_supervise", False):
        learner.resume_latest()  # supervised restart resumes the lineage

    def run_loop():
        out = loop.run()
        print(f"league-learner {player_id} done: {json.dumps(out)}",
              flush=True)

    if getattr(args, "no_supervise", False):
        run_loop()
        return
    supervise_call(
        run_loop, op=f"league-learner:{player_id}",
        policy=_restart_policy(args),
        on_restart=lambda e: learner.resume_latest(),
    )


def run_league_run(args) -> None:
    """The self-play economy launcher: coordinator (LeagueService +
    ArenaStore + HA journal) in this process, one league-learner subprocess
    per player (docs/league.md quickstart). Exits 0 only when every learner
    exits 0, at least one historical snapshot was minted from a checkpoint
    generation, and the payoff matrix has real off-diagonal entries."""
    from ..league.runtime.runner import LeagueRunner
    from ..learner.base_learner import experiments_root

    player_ids = [s.strip() for s in args.league_players.split(",") if s.strip()]
    save_path = args.save_path or os.path.join(
        experiments_root(), args.experiment_name)
    journal = args.journal_dir
    if not journal:
        journal = os.path.join(save_path, "league_journal")
    elif journal.lower() == "none":
        journal = ""  # chaos counter-demo: run the economy un-journaled
    extra = [
        "--batch-size", str(args.batch_size),
        "--traj-len", str(args.traj_len),
        "--jaxenv-units", str(args.jaxenv_units),
        "--jaxenv-episode-len", str(args.jaxenv_episode_len),
        "--experiment-name", args.experiment_name,
    ]
    if args.host_devices:
        extra += ["--host-devices", str(args.host_devices)]
    elif args.platform != "auto":
        extra += ["--platform", args.platform]
    if args.mesh:
        extra += ["--mesh", args.mesh]
    if args.no_health:
        extra += ["--no-health"]
    if args.no_supervise:
        extra += ["--no-supervise"]
    runner = LeagueRunner(
        player_ids=player_ids,
        save_path=save_path,
        journal_dir=journal,
        arena_store_path=os.path.join(save_path, "arena_store.pkl"),
        lease_s=args.lease_s or 30.0,
        # first-round asks sit behind each learner's XLA compile on small
        # hosts; a short TTL would count those as orphans
        job_ttl_s=600.0,
        learner_argv_extra=extra,
        rounds=args.league_rounds,
        iters_per_round=args.league_iters_per_round,
        actors_per_player=args.league_actors_per_player,
        reassign=args.league_actors_per_player > 0,
    )
    digest = runner.run(port=args.port)
    raise SystemExit(0 if digest.get("ok") else 1)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--type", default="all",
                   choices=["all", "league", "coordinator", "learner", "actor",
                            "replay", "arena", "league-run", "league-learner"])
    p.add_argument("--config", default="")
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--traj-len", type=int, default=None)
    p.add_argument("--env-num", type=int, default=None)
    p.add_argument("--episode-game-loops", type=int, default=300)
    p.add_argument("--env", default="",
                   choices=("", "mock", "sc2", "jaxenv"),
                   help="environment backend; overrides the config's "
                        "env.type (default mock)")
    p.add_argument("--anakin", action="store_true",
                   help="fused on-device rollout: train the learner from "
                        "the jaxenv Anakin loop (implies --env jaxenv; "
                        "replaces the actor plane entirely)")
    p.add_argument("--jaxenv-units", type=int, default=4,
                   help="jaxenv units per squad (padded squad width)")
    p.add_argument("--jaxenv-episode-len", type=int, default=32,
                   help="jaxenv env steps until episode timeout")
    p.add_argument("--experiment-name", default="rl_train")
    p.add_argument("--save-path", default="",
                   help="experiment root override (default "
                        "$DISTAR_EXPERIMENTS_ROOT or ./experiments/<name>); "
                        "scope smoke runs to tmp dirs so stale checkpoints "
                        "never poison auto-resume")
    p.add_argument("--mesh", default="",
                   help="device-mesh spec for the learner, e.g. "
                        "'dp=4,fsdp=2,tp=1' — compiles the jitted train "
                        "step with NamedSharding in/out shardings on the "
                        "live mesh and turns on sharded checkpoints "
                        "(docs/parallel.md)")
    p.add_argument("--host-devices", type=int, default=0,
                   help="force a virtual n-device CPU platform before jax "
                        "init (multichip smoke without silicon: "
                        "--host-devices 8 --mesh dp=4,fsdp=2)")
    p.add_argument("--sharded-ckpt", action="store_true", default=None,
                   help="checkpoint as one CRC'd blob per parameter shard "
                        "+ layout manifest (default: on when --mesh is "
                        "given, off otherwise)")
    p.add_argument("--no-sharded-ckpt", dest="sharded_ckpt",
                   action="store_false")
    p.add_argument("--smoke-model", action="store_true", default=True)
    p.add_argument("--full-model", dest="smoke_model", action="store_false")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--admin-port", type=int, default=None,
                   help="serve the learner admin API (status / save_ckpt / "
                        "update_config and on-demand POST /profile?steps=N "
                        "trace capture -> ranked bucket report; see "
                        "`opsctl profile`) on this port (learner-hosting "
                        "roles)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve GET /metrics (Prometheus text) on this port; "
                        "the coordinator role serves it on --port already "
                        "(plus /healthz, /alerts, /timeseries)")
    p.add_argument("--no-health", action="store_true",
                   help="disable the fleet-health subsystem (TSDB sampler, "
                        "watchdog rules, telemetry shipping, crash recorder)")
    p.add_argument("--dynamics-every", type=int, default=None,
                   help="training-dynamics gauge-export stride (learner "
                        "dynamics.every_n); 0 disables the in-jit "
                        "diagnostics tree entirely; default: config/10")
    p.add_argument("--health-sample-s", type=float, default=1.0,
                   help="registry->TSDB sampling cadence")
    p.add_argument("--health-eval-s", type=float, default=2.0,
                   help="health rulebook evaluation cadence")
    p.add_argument("--telemetry-interval-s", type=float, default=5.0,
                   help="snapshot shipping cadence to the coordinator "
                        "(learner/actor roles)")
    p.add_argument("--no-supervise", action="store_true",
                   help="disable the resilience layer's role supervision "
                        "(crash-restart of actor loops, learner auto-resume "
                        "from the latest checkpoint pointer)")
    p.add_argument("--restart-max", type=int, default=5,
                   help="restart budget per role within --restart-window-s")
    p.add_argument("--restart-window-s", type=float, default=300.0,
                   help="sliding window for the restart budget")
    p.add_argument("--league-resume", default="",
                   help="league role: resume-journal path; loaded on start "
                        "when present, then autosaved periodically")
    p.add_argument("--league-autosave-s", type=float, default=30.0,
                   help="league resume-journal cadence (0 = league config "
                        "save_resume_freq_s)")
    p.add_argument("--lease-s", type=float, default=0.0,
                   help="coordinator role: lease TTL for registrations; "
                        "endpoints that stop heartbeating are evicted "
                        "(0 = leases disabled)")
    p.add_argument("--journal-dir", default="",
                   help="coordinator role: write-ahead-journal directory "
                        "(comm/ha.py) — every mutating route is journaled, "
                        "a restart replays it, and standbys can tail it "
                        "('' = in-memory broker, the pre-HA behavior)")
    p.add_argument("--ha-role", default="auto",
                   choices=("auto", "primary", "standby"),
                   help="coordinator HA role: auto probes --ha-peers and "
                        "joins a live primary as standby, else leads")
    p.add_argument("--ha-peers", default="",
                   help="comma list of peer coordinator host:port addrs "
                        "(the other members of the HA pair/set)")
    p.add_argument("--ha-port", type=int, default=0,
                   help="journal follower-feed TCP port (0 = ephemeral; "
                        "peers discover it via GET /coordinator/ha)")
    p.add_argument("--ha-advertise", default="",
                   help="host:port this coordinator advertises to peers "
                        "and clients (default 127.0.0.1:--port)")
    p.add_argument("--ha-takeover-grace-s", type=float, default=3.0,
                   help="standby promotes after this long without contact "
                        "from the primary's follower feed")
    p.add_argument("--league-addr", default="", help="host:port of the league server")
    p.add_argument("--coordinator-addr", default="",
                   help="host:port of the coordinator (HA fleets: a comma "
                        "list 'h1:p1,h2:p2' — clients follow leadership "
                        "across failovers)")
    p.add_argument("--plane", default="inline",
                   choices=("inline", "local", "remote"),
                   help="rollout inference plane backend (docs/serving.md): "
                        "inline = per-actor BatchedInference (legacy), "
                        "local = one shared in-process gateway per player, "
                        "remote = framed-TCP against a bin/serve gateway "
                        "(--plane-addr)")
    p.add_argument("--plane-addr", default="",
                   help="--plane remote target: one 'host:port' bin/serve "
                        "TCP frontend, a 'h1:p1,h2:p2' gateway fleet (rides "
                        "the serve.fleet session-affinity router), or "
                        "'discover' to build the fleet from the "
                        "coordinator's serve_gateway registrations "
                        "(needs --coordinator-addr)")
    p.add_argument("--plane-slots", type=int, default=0,
                   help="shared local engine lanes (0 = this job's env_num); "
                        "sessions reserve exact capacity, so size it for "
                        "every concurrent job on the host")
    p.add_argument("--replay", action="store_true",
                   help="--type all: route trajectories through an "
                        "in-process replay store (smoke config of the "
                        "store path) instead of the point-to-point shuttle")
    p.add_argument("--replay-addr", default="",
                   help="replay data-plane target: one 'host:port', a "
                        "comma-separated shard fleet (consistent-hash "
                        "routing + learner fan-in), or 'discover' to read "
                        "the fleet from the coordinator's replay_shard "
                        "registrations (default: the legacy shuttle path)")
    p.add_argument("--replay-shards", type=int, default=1,
                   help="--type all: stand up this many in-process replay "
                        "shards (actors route by consistent hash, the "
                        "learner fans in with per-shard rate limiting)")
    p.add_argument("--replay-fast-path", action="store_true",
                   help="--type all: zero-copy colocated store — actor "
                        "pushes and learner samples through a direct "
                        "in-process handle, no sockets, no serialization "
                        "(the Sebulba layout's data plane)")
    p.add_argument("--transport", default="auto",
                   choices=("auto", "shm", "tcp"),
                   help="data-plane transport for every colocated hop "
                        "(replay push/sample, rollout-plane remote): auto "
                        "negotiates shared-memory rings per connection "
                        "when client and server share a host (TCP stays "
                        "the control channel + fallback leg), shm is the "
                        "same policy, tcp refuses rings everywhere "
                        "(docs/data_plane.md transport negotiation)")
    p.add_argument("--no-replay-compress", dest="replay_compress",
                   action="store_false", default=True,
                   help="disable wire compression on replay data-plane "
                        "connections (negotiated per connection; servers "
                        "started with this flag refuse it for all peers)")
    p.add_argument("--replay-fanin", default="round_robin",
                   choices=("round_robin", "weighted"),
                   help="shard order for learner fan-in sampling: strict "
                        "rotation, or fullest-shard-first (weighted by "
                        "resident items)")
    p.add_argument("--replay-shard-id", default="",
                   help="--type replay: metrics/stats label for this fleet "
                        "member (default ':<port>')")
    p.add_argument("--replay-max-size", type=int, default=1024,
                   help="replay role: per-table item cap (FIFO eviction)")
    p.add_argument("--replay-spi", type=float, default=1.0,
                   help="replay role: samples-per-insert ratio enforced by "
                        "the rate limiter (<=0 disables ratio enforcement)")
    p.add_argument("--replay-min-size", type=int, default=0,
                   help="replay role: inserts required before sampling "
                        "starts (0 = the learner batch size)")
    p.add_argument("--replay-error-buffer", type=float, default=None,
                   help="replay role: limiter slack in sample units "
                        "(default max(1, spi) * batch size, so a whole "
                        "learner batch is always admissible)")
    p.add_argument("--replay-sampler", default="fifo",
                   choices=("fifo", "uniform", "prioritized"),
                   help="replay role: table sampler (fifo = consume-once "
                        "legacy semantics; prioritized = sum-tree PER)")
    p.add_argument("--replay-spill-dir", default="",
                   help="replay role: disk-spill directory; acked inserts "
                        "survive a store crash (empty = no durability)")
    p.add_argument("--replay-spill-max", type=int, default=4096,
                   help="replay role: spill ring bound (oldest dropped past it)")
    p.add_argument("--replay-max-staleness-s", type=float, default=0.0,
                   help="replay role: evict items older than this "
                        "(0 = no staleness eviction)")
    p.add_argument("--distill", action="store_true",
                   help="learner-hosting roles: run the student-tier "
                        "DISTILLATION learner instead of the RL teacher — "
                        "trains model.student_model_config on the same "
                        "trajectory batches via masked per-head KL against "
                        "the teacher logits already riding every flush, "
                        "publishes checkpoints under the 'student' "
                        "CheckpointManager role key, and exports the "
                        "distar_distill_* drift gauges "
                        "(docs/training_guide.md distillation quickstart)")
    p.add_argument("--arena-ckpt-dir", default="",
                   help="--type arena: checkpoint directory whose "
                        "CheckpointManager generations form the model roster")
    p.add_argument("--arena-roles", default="",
                   help="--type arena: comma-separated CheckpointManager "
                        "role keys to rate ('' = the default/teacher "
                        "lineage, shown as main)")
    p.add_argument("--arena-episodes", type=int, default=8,
                   help="--type arena: episodes per scheduled scenario batch")
    p.add_argument("--arena-batches", type=int, default=0,
                   help="--type arena: stop after N batches (0 = run forever)")
    p.add_argument("--arena-interval-s", type=float, default=5.0,
                   help="--type arena: idle sleep when no assignment is "
                        "available")
    p.add_argument("--arena-artifact", default="",
                   help="--type arena: write the ARENA_r*.json ledger "
                        "(matches/s + ratings, honesty flags in-band) here "
                        "on exit")
    p.add_argument("--arena-store", default="",
                   help="--type coordinator: host the durable ArenaStore, "
                        "journaled at this path (league-autosave idiom); "
                        "enables the /arena/* routes")
    p.add_argument("--player-id", default="MP0")
    p.add_argument("--league-players", default="MP0,EP0,ME0",
                   help="--type league-run: comma list of active league "
                        "player ids (prefix picks the class: MP main, EP "
                        "exploiter, ME main-exploiter, ...); one learner "
                        "subprocess is spawned per player")
    p.add_argument("--league-rounds", type=int, default=2,
                   help="league-run/league-learner: matchmade rounds per "
                        "learner (each: ask -> train -> report -> "
                        "checkpoint generation -> train-info)")
    p.add_argument("--league-iters-per-round", type=int, default=1,
                   help="optimizer steps per matchmade round")
    p.add_argument("--league-actors-per-player", type=int, default=0,
                   help="--type league-run: seed each player's elastic "
                        "actor-slot fleet with this many members and run "
                        "the payoff-driven reassigner over them (0 = no "
                        "actor fleets; the fused learners roll out "
                        "on-device)")
    p.add_argument("--pipeline", default="default",
                   help="learner implementation to run: 'default' or an "
                        "importable custom-pipeline module (plugins.py)")
    p.add_argument("--dist-method", default="single_node",
                   choices=["auto", "slurm", "single_node", "explicit"])
    p.add_argument("--dist-coordinator-address", default="",
                   help="host:port for jax.distributed (explicit mode)")
    p.add_argument("--dist-num-processes", type=int, default=None)
    p.add_argument("--dist-process-id", type=int, default=None)
    p.add_argument("--platform", default="auto", choices=("auto", "cpu", "tpu"),
                   help="jax backend; cpu must be pinned via jax.config "
                        "(this image selects the TPU at interpreter start, "
                        "so JAX_PLATFORMS=cpu alone is too late)")
    args = p.parse_args()
    if args.host_devices:
        # must precede ANY jax backend init (device query) in this process
        from ..parallel.executor import force_host_devices

        force_host_devices(args.host_devices,
                           cache_base="/tmp/jax_cache_distar_tpu")
    elif args.platform != "auto":
        import jax

        jax.config.update("jax_platforms", args.platform)
        from ..utils.compile_cache import configure as _cc
        _cc(jax, "/tmp/jax_cache_distar_tpu")
    _resolve_args(args)
    if args.dist_method == "explicit" and not (
        args.dist_coordinator_address
        and args.dist_num_processes is not None
        and args.dist_process_id is not None
    ):
        raise SystemExit(
            "--dist-method explicit requires --dist-coordinator-address, "
            "--dist-num-processes and --dist-process-id"
        )

    if args.anakin:
        if args.env and args.env != "jaxenv":
            raise SystemExit("--anakin requires --env jaxenv")
        run_anakin(args)
    elif args.type == "all":
        run_all(args)
    elif args.type == "league":
        league = League(read_config(args.config) if args.config else {})
        if args.league_resume:
            # pick the league up where the last journal left it — a broker
            # restart must not reset all payoff/ELO state
            if os.path.exists(args.league_resume):
                league.load_resume(args.league_resume)
            league.start_autosave(args.league_resume,
                                  interval_s=args.league_autosave_s or None)
        server = LeagueAPIServer(league, port=args.port)
        server.start()
        print(f"league serving on {server.host}:{server.port}", flush=True)
        while True:
            time.sleep(3600)
    elif args.type == "replay":
        run_replay(args)
    elif args.type == "coordinator":
        # the broker evaluates the FULL rulebook: shipped telemetry gives it
        # per-source learner/actor/serve series for the whole fleet
        _init_health(args, roles=("learner", "actor", "coordinator", "trace",
                                  "serve", "replay", "distill", "arena"),
                     source="coordinator")
        if args.arena_store:
            # host the skill ledger: reload the journal (ratings, payoff AND
            # the idempotency key set survive a broker restart), then keep
            # journaling on the autosave thread
            from ..arena import ArenaStore, set_arena_store

            store = ArenaStore(path=args.arena_store)
            if store.maybe_load():
                print(f"arena store resumed from {args.arena_store}",
                      flush=True)
            store.start_autosave(interval_s=args.league_autosave_s or 30.0)
            set_arena_store(store)
        co = Coordinator(default_lease_s=args.lease_s or None)
        server = CoordinatorServer(coordinator=co, port=args.port)
        ha_state = None
        if args.journal_dir:
            # HA broker: journal every mutating route, replay on restart,
            # serve the follower feed; with --ha-peers, lease-based
            # leadership + epoch fencing (docs/resilience.md)
            from ..comm.ha import HAState

            ha_state = HAState(
                co, args.journal_dir,
                advertise=args.ha_advertise or f"127.0.0.1:{server.port}",
                feed_port=args.ha_port,
                peers=[p for p in (args.ha_peers or "").split(",") if p],
                role=args.ha_role,
                takeover_grace_s=args.ha_takeover_grace_s,
            )
            ha_state.boot()
            server.attach_ha(ha_state)
            print(f"coordinator HA: role={ha_state.role} "
                  f"epoch={ha_state.epoch} journal={args.journal_dir} "
                  f"feed=:{ha_state.feed_port}", flush=True)
        server.start()
        print(f"coordinator serving on {server.host}:{server.port}", flush=True)
        if args.arena_store or ha_state is not None:
            # a drained broker must not lose the tail of the match ledger
            # or the journal: turn SIGTERM into SystemExit so the final
            # journaling below runs (SIGKILL is exactly what the WAL and
            # the arena autosave bound the damage of)
            import signal as _signal
            import sys as _sys

            _signal.signal(_signal.SIGTERM, lambda *_: _sys.exit(0))
        try:
            while True:
                time.sleep(3600)
        finally:
            if args.arena_store:
                store.save()
                print("arena store journaled on shutdown", flush=True)
            if ha_state is not None:
                ha_state.final_snapshot()
                ha_state.stop()
                print("coordinator journal snapshotted on shutdown", flush=True)
    elif args.type == "arena":
        if not (args.coordinator_addr and args.arena_ckpt_dir):
            raise SystemExit(
                "--type arena requires --coordinator-addr and --arena-ckpt-dir")
        run_arena(args)
    elif args.type == "league-run":
        run_league_run(args)
    elif args.type == "league-learner":
        if not args.coordinator_addr:
            raise SystemExit(
                "--type league-learner requires --coordinator-addr")
        run_league_learner(args)
    elif args.type == "learner":
        if not args.coordinator_addr:
            raise SystemExit("--type learner requires --coordinator-addr (and usually --league-addr)")
        run_learner(args)
    elif args.type == "actor":
        if not (args.league_addr and args.coordinator_addr):
            raise SystemExit("--type actor requires --league-addr and --coordinator-addr")
        run_actor(args)


if __name__ == "__main__":
    main()
