"""Play/eval launcher: agent-vs-agent matches, winrate report.

Role parity with the reference (reference: distar/bin/play.py:27-120 —
human/agent/bot matchups over the realtime env). The mock env stands in for
SC2; checkpoints load into either side. Human mode and the realtime SC2
window land with the env binding.
"""
from __future__ import annotations

import argparse
from collections import Counter

from ..actor import Actor
from ..envs import MockEnv
from ..utils.checkpoint import load_checkpoint


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--game-count", type=int, default=4)
    p.add_argument("--model1", default="", help="checkpoint for side 0 (optional)")
    p.add_argument("--model2", default="", help="checkpoint for side 1 (optional)")
    p.add_argument("--env-num", type=int, default=2)
    p.add_argument("--episode-game-loops", type=int, default=300)
    p.add_argument("--smoke-model", action="store_true", default=True)
    args = p.parse_args()

    from .rl_train import SMOKE_MODEL

    init_params = None
    if args.model1:
        init_params = load_checkpoint(args.model1)["state"].get("params")
    actor = Actor(
        cfg={"actor": {"env_num": args.env_num, "traj_len": 10 ** 9}},  # no traj push
        league=None,
        adapter=None,
        model_cfg=SMOKE_MODEL if args.smoke_model else {},
        env_fn=lambda: MockEnv(episode_game_loops=args.episode_game_loops),
        init_params=init_params,
    )
    results = actor.run_job(episodes=args.game_count)
    outcomes = Counter(
        "side0" if r["0"]["winloss"] > 0 else "side1" for r in results
    )
    n = max(len(results), 1)
    print(
        f"games={len(results)} side0_winrate={outcomes['side0'] / n:.2f} "
        f"side1_winrate={outcomes['side1'] / n:.2f}"
    )


if __name__ == "__main__":
    main()
