"""Play/eval launcher: agent-vs-agent / agent-vs-bot / human-vs-agent
matches on a real SC2 install, plus a game-free mock mode.

Role parity with the reference (reference: distar/bin/play.py:27-120):
resolves the SC2 install (SC2PATH), installs bundled maps, pins the matchup
by game_type, loads a checkpoint per side (native checkpoints or reference
torch .pth via ref_convert), runs realtime games, and reports winrates.
Human mode gives the human their own full-screen client (env.py:191-197);
the realtime clock is SC2's own.
"""
from __future__ import annotations

import argparse
import os
from collections import Counter

from ..actor import Actor
from ..envs import MockEnv
from ..utils.checkpoint import load_checkpoint

GAME_TYPES = ("agent_vs_agent", "agent_vs_bot", "human_vs_agent", "mock")


def find_sc2() -> str:
    """Locate the SC2 install via the platform run config (single source of
    truth for discovery, envs/sc2/run_configs.py)."""
    from ..envs.sc2 import run_configs

    data_dir = run_configs.get().data_dir
    if not os.path.isdir(data_dir):
        raise SystemExit(
            f"StarCraft II install not found at '{data_dir}': set the SC2PATH "
            "environment variable (or use --game_type mock for a game-free "
            "smoke run)."
        )
    return data_dir


def load_params(path: str, model_cfg):
    """Checkpoint -> Flax params; reference torch .pth checkpoints convert
    on the fly (model/ref_convert.convert_model)."""
    if path.endswith((".pth", ".pt")):
        import torch

        sd = torch.load(path, map_location="cpu")
        sd = sd.get("model", sd)
        from ..model.ref_convert import convert_model

        return convert_model(sd, model_cfg)
    from ..utils.checkpoint import load_params as load_native_params

    return load_native_params(path)


def side_name(path: str, default: str) -> str:
    if not path:
        return default
    return os.path.basename(path).rsplit(".", 1)[0] or default


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model1", default="", help="checkpoint for side 0")
    p.add_argument("--model2", default="", help="checkpoint for side 1 (or botN)")
    p.add_argument("--game_type", default="human_vs_agent", choices=GAME_TYPES)
    p.add_argument("--map", dest="map_name", default="KairosJunction")
    p.add_argument("--race1", default="zerg")
    p.add_argument("--race2", default="zerg")
    p.add_argument("--game-count", type=int, default=1)
    p.add_argument("--maps-dir", default="", help="bundled .SC2Map dir to auto-install")
    p.add_argument("--z-path", default="", help="Z strategy library for both sides")
    p.add_argument("--save-replay-episodes", type=int, default=0)
    p.add_argument("--replay-dir", default="replays")
    p.add_argument("--no-realtime", action="store_true",
                   help="lockstep stepping instead of wall-clock (agent games only)")
    p.add_argument("--episode-game-loops", type=int, default=300, help="mock mode only")
    p.add_argument("--env-num", type=int, default=1)
    p.add_argument("--smoke-model", action="store_true", default=None,
                   help="tiny model dims for fast smoke runs (default for "
                        "checkpoint-less mock games)")
    p.add_argument("--full-model", dest="smoke_model", action="store_false",
                   help="force full-scale model dims")
    p.add_argument("--platform", default="auto", choices=("auto", "cpu", "tpu"),
                   help="inference device; cpu works anywhere (the reference's "
                        "--cpu flag), auto uses the default jax backend")
    p.add_argument("--lan-host", action="store_true",
                   help="HUMAN side of a remote showmatch: host a LAN game "
                        "full-screen and print the handshake port for the "
                        "agent machine (role of reference play_vs_agent)")
    p.add_argument("--lan", default="",
                   help="AGENT side of a remote showmatch: host:port of the "
                        "human machine's handshake (reference lan_sc2_env)")
    args = p.parse_args()

    if args.lan_host:
        return run_lan_host(args)

    if args.platform == "cpu" or (args.platform == "auto" and args.game_type == "mock"):
        # pin before any backend init; the image's sitecustomize pins the
        # platform via jax.config, so an env var alone is too late
        import jax

        jax.config.update("jax_platforms", "cpu")
        from ..utils.compile_cache import configure as _cc
        _cc(jax, "/tmp/jax_cache_distar_tpu")

    from .rl_train import SMOKE_MODEL

    if args.smoke_model is None:
        # checkpoints require the full-scale dims; a checkpoint-less mock
        # smoke shouldn't compile the full model
        args.smoke_model = args.game_type == "mock" and not args.model1
    model_cfg = SMOKE_MODEL if args.smoke_model else {}

    if args.game_type == "mock":
        from ..model.config import default_model_config
        from ..utils.config import deep_merge_dicts

        cfg = deep_merge_dicts(default_model_config(), model_cfg)
        player_params = {}
        if args.model1:
            player_params["model1"] = load_params(args.model1, cfg)
        if args.model2:
            player_params["model2"] = load_params(args.model2, cfg)
        actor = Actor(
            cfg={"actor": {"env_num": args.env_num, "traj_len": 10 ** 9}},
            model_cfg=model_cfg,
            env_fn=lambda: MockEnv(episode_game_loops=args.episode_game_loops),
            player_params=player_params,
        )
        job = {
            "player_ids": ["model1", "model2"],
            "send_data_players": [],
            "update_players": [],
            "teacher_player_ids": ["none", "none"],
            "branch": "eval_test",
            "env_info": {"map_name": "mock"},
        }
        results = actor.run_job(episodes=args.game_count, job=job)
        report(results)
        return

    sc2_dir = find_sc2()
    # auto-install the bundled Ladder2019Season2 maps (or a user-supplied
    # dir) so offline hosts play without ad-hoc downloads (role of the
    # reference auto-install, rl_train.py:115-116); a read-only install dir
    # is fine — run_configs.map_data falls back to the bundle at load time
    from ..envs.sc2 import maps as map_registry

    try:
        map_registry.install_maps(args.maps_dir or None, sc2_dir)
    except OSError as e:
        import logging

        logging.warning(f"map auto-install into {sc2_dir} failed ({e!r}); "
                        "relying on the bundled-map fallback")

    from ..model.config import default_model_config
    from ..utils.config import deep_merge_dicts

    full_model_cfg = deep_merge_dicts(default_model_config(), model_cfg)

    if args.lan:
        # agent side of a remote showmatch: join the human's hosted game
        from ..envs.sc2.lan import LanSC2Env

        host, sep, port = args.lan.rpartition(":")
        if not sep or not port.isdigit():
            raise SystemExit(
                f"--lan expects host:port (the endpoint --lan-host printed), "
                f"got {args.lan!r}"
            )
        host = host or "127.0.0.1"
        name1 = side_name(args.model1, "model1")
        player_params = {}
        if args.model1:
            player_params[name1] = load_params(args.model1, full_model_cfg)
        job = {
            "player_ids": [name1],
            "send_data_players": [],
            "update_players": [],
            "teacher_player_ids": ["none"],
            "branch": "eval_test",
            "env_info": {"map_name": args.map_name},
            "z_path": [args.z_path] if args.z_path else [],
            "opponent_id": "remote_human",
        }
        actor = Actor(
            cfg={"actor": {"env_num": 1, "traj_len": 10 ** 9}},
            model_cfg=model_cfg,
            env_fn=lambda: LanSC2Env(host, int(port), agent_race=args.race1),
            player_params=player_params,
        )
        results = actor.run_job(episodes=1, job=job)
        report(results)
        return

    # matchup -> env player ids + the model-driven sides (reference
    # play.py:101-112)
    name1 = side_name(args.model1, "model1")
    realtime = not args.no_realtime
    player_params = {}
    if args.game_type == "agent_vs_agent":
        name2 = side_name(args.model2, "model2")
        if name2 == name1:
            name2 = name1 + "(1)"
        env_player_ids = [name1, name2]
        agent_ids = [name1, name2]
        if args.model2:
            player_params[name2] = load_params(args.model2, full_model_cfg)
    elif args.game_type == "agent_vs_bot":
        import re

        if args.model2 and not re.fullmatch(r"bot\d+", args.model2):
            raise SystemExit(
                f"agent_vs_bot expects --model2 botN (built-in bot level), "
                f"got {args.model2!r}; use --game_type agent_vs_agent for a "
                "checkpoint opponent"
            )
        bot = args.model2 or "bot10"
        env_player_ids = [name1, bot]
        agent_ids = [name1]
    else:  # human_vs_agent
        env_player_ids = [name1, "human"]
        agent_ids = [name1]
        realtime = True  # the human plays in wall-clock time
    if args.model1:
        player_params[name1] = load_params(args.model1, full_model_cfg)

    env_cfg = {
        "env": {
            "map_name": args.map_name,
            "player_ids": env_player_ids,
            "races": [args.race1, args.race2],
            "realtime": realtime,
            "save_replay_episodes": args.save_replay_episodes,
            "replay_dir": args.replay_dir,
        }
    }

    from ..envs.sc2.launcher import make_sc2_env

    z_paths = [args.z_path, args.z_path] if args.z_path else []
    job = {
        "player_ids": agent_ids,
        "send_data_players": [],
        "update_players": [],
        "teacher_player_ids": ["none"] * len(agent_ids),
        "branch": "eval_test",
        "env_info": {"map_name": args.map_name},
        "z_path": z_paths,
        "opponent_id": env_player_ids[-1],
    }
    actor = Actor(
        cfg={"actor": {"env_num": args.env_num, "traj_len": 10 ** 9}},
        model_cfg=model_cfg,
        env_fn=lambda: make_sc2_env(env_cfg),
        player_params=player_params,
    )
    results = actor.run_job(episodes=args.game_count, job=job)
    report(results)


def run_lan_host(args) -> None:
    """Human side of a remote showmatch: host the LAN game, print the
    handshake endpoint, then play full-screen until the game ends."""
    import socket
    import time

    find_sc2()
    from ..envs.sc2 import maps as map_registry
    from ..envs.sc2.lan import host_lan_game

    try:
        map_registry.install_maps(args.maps_dir or None)
    except OSError:
        pass
    controller, handshake_port, proc, join_thread = host_lan_game(
        args.map_name, race=args.race1, realtime=True
    )
    # the outward-facing address: a connected UDP socket reveals the local
    # interface IP without sending a packet (gethostbyname(hostname) often
    # resolves to 127.0.1.1 via /etc/hosts — useless to a remote machine)
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        probe.connect(("10.255.255.255", 1))
        ip = probe.getsockname()[0]
        probe.close()
    except OSError:
        ip = socket.gethostbyname(socket.gethostname())
    print(
        f"LAN game hosted. On the agent machine run:\n"
        f"  python -m distar_tpu.bin.play --lan {ip}:{handshake_port} "
        f"--model1 <ckpt> --race1 {args.race2}\n"
        f"(substitute this machine's reachable IP if {ip} is wrong)\n"
        f"Waiting for the agent to join...",
        flush=True,
    )
    join_thread.join()
    print("Agent joined — play! (this process exits when the game ends)", flush=True)
    try:
        while True:
            time.sleep(5)
            controller.ping()
    except Exception:
        pass
    finally:
        if proc is not None:
            proc.close()


def report(results) -> None:
    outcomes = Counter(
        "side0" if r["0"]["winloss"] > 0 else
        ("side1" if r["0"]["winloss"] < 0 else "tie")
        for r in results
    )
    n = max(len(results), 1)
    print(
        f"games={len(results)} side0_winrate={outcomes['side0'] / n:.2f} "
        f"side1_winrate={outcomes['side1'] / n:.2f} ties={outcomes['tie']}"
    )


if __name__ == "__main__":
    main()
