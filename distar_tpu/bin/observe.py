"""Observer: render a running game's raw observation without the SC2 UI.

Role of the reference's human renderer (reference: distar/pysc2/lib/
renderer_human.py — a 1.8k-LoC pygame window with camera controls and unit
overlays). The repo's deliberate divergence: realtime human PLAY uses SC2's
own UI (bin/play.py --human), so the renderer's remaining jobs are
observing and debugging — covered here with zero extra dependencies:

  * ``--interactive`` — curses UI with the reference renderer's observer
    affordances: camera pan (arrows/hjkl) + zoom (+/-), a cursor
    (WASD) that inspects the units under it (type/hp/orders overlay),
    pause, and a live HUD (loop, camera rect, unit counts)
  * ``--ascii``   — a downsampled live map in the terminal (own units 'o',
    enemies 'x', neutral '.', terrain shading by height)
  * ``--frames DIR`` — binary PPM (P6) images per observation, viewable by
    any image tool and easy to strip into a GIF later

Drives either an already-running client (``--endpoint host:port`` — works
against the fake server too) or a freshly launched one joined to a replay
via sc2_tools; reads raw protos only, so it never perturbs the game.
"""
from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional, Tuple

import numpy as np

ASCII_RAMP = " .:-=+*#%@"


def decode_terrain(game_info, map_size: Tuple[int, int]) -> np.ndarray:
    """start_raw.terrain_height ImageData -> [H,W] u8 (zeros when absent —
    the fake server ships no height map)."""
    W, H = map_size
    img = getattr(getattr(game_info, "start_raw", None), "terrain_height", None)
    data = getattr(img, "data", b"") if img is not None else b""
    if img is None or not data or img.bits_per_pixel != 8:
        return np.zeros((H, W), np.uint8)
    arr = np.frombuffer(data, np.uint8)
    if arr.size != img.size.x * img.size.y:
        return np.zeros((H, W), np.uint8)
    arr = arr.reshape(img.size.y, img.size.x)
    if arr.shape[0] >= H and arr.shape[1] >= W:
        return arr[:H, :W]
    return np.zeros((H, W), np.uint8)


def obs_to_grid(raw_obs, map_size: Tuple[int, int], own_player: int,
                terrain: Optional[np.ndarray] = None) -> dict:
    """Raw proto -> numpy layers: terrain [H,W] u8, own(+ally)/enemy/neutral
    unit masks (proto Alliance: Self=1, Ally=2, Neutral=3, Enemy=4)."""
    W, H = map_size
    if terrain is None:
        terrain = np.zeros((H, W), np.uint8)
    own = np.zeros((H, W), bool)
    enemy = np.zeros((H, W), bool)
    neutral = np.zeros((H, W), bool)
    for u in raw_obs.units:
        x = int(np.clip(u.pos.x, 0, W - 1))
        y = int(np.clip(u.pos.y, 0, H - 1))
        if u.alliance in (1, 2):  # self + allies
            own[y, x] = True
        elif u.alliance == 4:
            enemy[y, x] = True
        else:  # neutral: minerals, geysers, destructibles
            neutral[y, x] = True
    return {"terrain": terrain, "own": own, "enemy": enemy, "neutral": neutral}


def _glyph(grid: dict, ys: slice, xs: slice) -> str:
    """One character for a world rect: unit presence by precedence, else a
    terrain shade (the shared glyph language of every renderer here)."""
    if grid["own"][ys, xs].any():
        return "o"
    if grid["enemy"][ys, xs].any():
        return "x"
    if grid["neutral"][ys, xs].any():
        return "'"
    t = grid["terrain"][ys, xs]
    shade = int(t.mean()) * (len(ASCII_RAMP) - 1) // 255 if t.size else 0
    return ASCII_RAMP[shade] if shade else "."


def render_ascii(grid: dict, width: int = 64) -> str:
    H, W = grid["own"].shape
    step_x = max(W // width, 1)
    step_y = max(H // (width // 2), 1)
    rows = []
    for y in range(0, H, step_y):
        row = [
            _glyph(grid, slice(y, y + step_y), slice(x, x + step_x))
            for x in range(0, W, step_x)
        ]
        rows.append("".join(row))
    return "\n".join(rows)


def render_ppm(grid: dict, path: str) -> None:
    H, W = grid["own"].shape
    img = np.zeros((H, W, 3), np.uint8)
    img[..., :] = grid["terrain"][..., None] // 2 + 40  # terrain shading
    img[grid["neutral"]] = (180, 180, 90)
    img[grid["own"]] = (60, 220, 60)
    img[grid["enemy"]] = (220, 60, 60)
    img = img[::-1]  # y-up -> image row order
    with open(path, "wb") as f:
        f.write(f"P6 {W} {H} 255\n".encode())
        f.write(img.tobytes())


class CameraView:
    """Viewport math + character rendering for the interactive observer,
    kept curses-free so it is testable headlessly (the curses loop in
    ``run_interactive`` is a thin input shell around it).

    World coordinates are game cells (y-up); the view renders y-down. One
    character covers ``scale`` world cells horizontally and ``2*scale``
    vertically (terminal glyphs are ~2x taller than wide)."""

    MIN_SCALE = 0.25

    def __init__(self, map_size: Tuple[int, int], cols: int = 64, rows: int = 24):
        self.W, self.H = int(map_size[0]), int(map_size[1])
        self.cols, self.rows = max(cols, 8), max(rows, 4)
        self.cx, self.cy = self.W / 2.0, self.H / 2.0
        # start fully zoomed out: the whole map fits the view
        self.scale = max(self.W / self.cols, self.H / (2.0 * self.rows), self.MIN_SCALE)
        self.cur_col, self.cur_row = self.cols // 2, self.rows // 2

    # ------------------------------------------------------------- controls
    def pan(self, dx_chars: int, dy_chars: int) -> None:
        """Move the camera by character steps (dy_chars > 0 pans DOWN on
        screen = toward smaller world y)."""
        self.cx = float(np.clip(self.cx + dx_chars * self.scale, 0, self.W))
        self.cy = float(np.clip(self.cy - dy_chars * 2.0 * self.scale, 0, self.H))

    def zoom(self, factor: float) -> None:
        max_scale = max(self.W / self.cols, self.H / (2.0 * self.rows), self.MIN_SCALE)
        self.scale = float(np.clip(self.scale * factor, self.MIN_SCALE, max_scale))

    def move_cursor(self, d_col: int, d_row: int) -> None:
        self.cur_col = int(np.clip(self.cur_col + d_col, 0, self.cols - 1))
        self.cur_row = int(np.clip(self.cur_row + d_row, 0, self.rows - 1))

    # ------------------------------------------------------------- geometry
    def world_rect(self):
        """(x0, y0, x1, y1) world-cell bounds of the viewport."""
        half_w = self.cols * self.scale / 2.0
        half_h = self.rows * 2.0 * self.scale / 2.0
        return (self.cx - half_w, self.cy - half_h, self.cx + half_w, self.cy + half_h)

    def char_rect(self, col: int, row: int):
        """World rect covered by one character cell (row 0 = TOP = max y)."""
        x0, y0, x1, y1 = self.world_rect()
        cw = (x1 - x0) / self.cols
        ch = (y1 - y0) / self.rows
        cx0 = x0 + col * cw
        cy1 = y1 - row * ch
        return (cx0, cy1 - ch, cx0 + cw, cy1)

    # ------------------------------------------------------------ rendering
    def render(self, grid: dict) -> list:
        """Viewport -> list of row strings (the shared _glyph language,
        plus '+' for the cursor and blanks beyond the map edge)."""
        H, W = grid["own"].shape
        rows = []
        for r in range(self.rows):
            row = []
            for c in range(self.cols):
                x0, y0, x1, y1 = self.char_rect(c, r)
                xs = slice(max(int(x0), 0), max(int(np.ceil(x1)), 0))
                ys = slice(max(int(y0), 0), max(int(np.ceil(y1)), 0))
                out_of_map = xs.start >= W or ys.start >= H or x1 <= 0 or y1 <= 0
                if (r, c) == (self.cur_row, self.cur_col):
                    row.append("+")
                elif out_of_map:
                    row.append(" ")
                else:
                    row.append(_glyph(grid, ys, xs))
            rows.append("".join(row))
        return rows

    def inspect(self, raw_obs) -> list:
        """Units under the cursor's character cell, nearest first — the
        unit overlay (fields after the reference's select/overlay panel)."""
        x0, y0, x1, y1 = self.char_rect(self.cur_col, self.cur_row)
        mx, my = (x0 + x1) / 2.0, (y0 + y1) / 2.0
        hits = []
        for u in raw_obs.units:
            if x0 <= u.pos.x < x1 and y0 <= u.pos.y < y1:
                d = (u.pos.x - mx) ** 2 + (u.pos.y - my) ** 2
                orders = [o.ability_id for o in getattr(u, "orders", [])]
                hits.append((d, {
                    "tag": u.tag,
                    "unit_type": u.unit_type,
                    "alliance": u.alliance,
                    "health": float(u.health),
                    "health_max": float(u.health_max),
                    "pos": (float(u.pos.x), float(u.pos.y)),
                    "orders": orders,
                }))
        return [info for _, info in sorted(hits, key=lambda t: t[0])]


def hud_line(view: CameraView, loop: int, grid: dict, paused: bool) -> str:
    x0, y0, x1, y1 = view.world_rect()
    return (
        f"loop {loop}  cam[{x0:.0f},{y0:.0f}..{x1:.0f},{y1:.0f}] "
        f"x{view.scale:.2f}  own {int(grid['own'].sum())} "
        f"enemy {int(grid['enemy'].sum())}"
        + ("  [PAUSED]" if paused else "")
        + "  (q quit, arrows pan, +/- zoom, wasd cursor, space pause)"
    )


def run_interactive(controller, map_size, terrain, interval: float) -> None:
    """Curses shell: keyboard -> CameraView, one observe() per frame."""
    import curses

    def loop(scr):
        curses.curs_set(0)
        scr.nodelay(True)
        h, w = scr.getmaxyx()
        view = CameraView(map_size, cols=min(w - 2, 100), rows=max(h - 8, 6))
        paused = False
        obs = controller.observe()
        last = 0.0
        def put(row, text):
            # clamp to the window: short terminals / resize races must not
            # kill the observer with a curses.error
            if 0 <= row < h - 1:
                try:
                    scr.addnstr(row, 0, text, w - 1)
                except curses.error:
                    pass

        while True:
            now = time.time()
            if not paused and now - last >= interval:
                obs = controller.observe()
                last = now
            raw = obs.observation.raw_data
            grid = obs_to_grid(raw, map_size, 1, terrain)
            scr.erase()
            put(0, hud_line(view, obs.observation.game_loop, grid, paused))
            for i, row in enumerate(view.render(grid)):
                put(1 + i, row)
            for i, u in enumerate(view.inspect(raw)[:4]):
                put(
                    2 + view.rows + i,
                    f"> type {u['unit_type']} ally {u['alliance']} "
                    f"hp {u['health']:.0f}/{u['health_max']:.0f} "
                    f"at ({u['pos'][0]:.1f},{u['pos'][1]:.1f}) orders {u['orders']}",
                )
            scr.refresh()
            key = scr.getch()
            if key in (ord("q"), 27):
                return
            elif key == ord(" "):
                paused = not paused
            elif key in (curses.KEY_LEFT, ord("h")):
                view.pan(-4, 0)
            elif key in (curses.KEY_RIGHT, ord("l")):
                view.pan(4, 0)
            elif key in (curses.KEY_UP, ord("k")):
                view.pan(0, -2)
            elif key in (curses.KEY_DOWN, ord("j")):
                view.pan(0, 2)
            elif key in (ord("+"), ord("=")):
                view.zoom(0.5)
            elif key == ord("-"):
                view.zoom(2.0)
            elif key == ord("a"):
                view.move_cursor(-1, 0)
            elif key == ord("d"):
                view.move_cursor(1, 0)
            elif key == ord("w"):
                view.move_cursor(0, -1)
            elif key == ord("s"):
                view.move_cursor(0, 1)
            time.sleep(0.03)

    curses.wrapper(loop)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--endpoint", default="", help="host:port of a running client")
    p.add_argument("--player", type=int, default=1)
    p.add_argument("--interval", type=float, default=1.0, help="seconds between frames")
    p.add_argument("--count", type=int, default=0, help="frames to capture (0 = forever)")
    p.add_argument("--ascii", action="store_true", help="live terminal map")
    p.add_argument("--interactive", action="store_true",
                   help="curses UI: camera pan/zoom + unit-inspect cursor")
    p.add_argument("--frames", default="", help="directory for PPM frames")
    args = p.parse_args(argv)

    from ..envs.sc2.remote_controller import RemoteController

    if not args.endpoint:
        raise SystemExit("--endpoint host:port required (launch a client via "
                         "bin/sc2_tools or point at a live game)")
    host, _, port = args.endpoint.rpartition(":")
    controller = RemoteController(host or "127.0.0.1", int(port), timeout_seconds=30)
    gi = controller.game_info()
    map_size = (gi.start_raw.map_size.x, gi.start_raw.map_size.y)
    terrain = decode_terrain(gi, map_size)
    if args.interactive:
        run_interactive(controller, map_size, terrain, args.interval)
        return
    if args.frames:
        os.makedirs(args.frames, exist_ok=True)

    n = 0
    while args.count == 0 or n < args.count:
        obs = controller.observe()
        grid = obs_to_grid(obs.observation.raw_data, map_size, args.player, terrain)
        loop = obs.observation.game_loop
        if args.ascii:
            sys.stdout.write(f"\x1b[2J\x1b[Hloop {loop}\n{render_ascii(grid)}\n")
            sys.stdout.flush()
        if args.frames:
            render_ppm(grid, os.path.join(args.frames, f"frame_{n:05d}_loop{loop}.ppm"))
        n += 1
        if args.count == 0 or n < args.count:
            time.sleep(args.interval)
    if args.frames:
        print(f"{n} frames written to {args.frames}")


if __name__ == "__main__":
    main()
