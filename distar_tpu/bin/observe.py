"""Headless observer: render a running game's raw observation without the
SC2 UI.

Role of the reference's human renderer for *debugging* (reference:
distar/pysc2/lib/renderer_human.py — the repo's deliberate divergence keeps
SC2's own UI for realtime human play, but headless hosts still need a
visual). Two zero-dependency outputs:

  * ``--ascii``   — a downsampled live map in the terminal (own units 'o',
    enemies 'x', neutral '.', terrain shading by height)
  * ``--frames DIR`` — binary PPM (P6) images per observation, viewable by
    any image tool and easy to strip into a GIF later

Drives either an already-running client (``--endpoint host:port`` — works
against the fake server too) or a freshly launched one joined to a replay
via sc2_tools; reads raw protos only, so it never perturbs the game.
"""
from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional, Tuple

import numpy as np

ASCII_RAMP = " .:-=+*#%@"


def decode_terrain(game_info, map_size: Tuple[int, int]) -> np.ndarray:
    """start_raw.terrain_height ImageData -> [H,W] u8 (zeros when absent —
    the fake server ships no height map)."""
    W, H = map_size
    img = getattr(getattr(game_info, "start_raw", None), "terrain_height", None)
    data = getattr(img, "data", b"") if img is not None else b""
    if img is None or not data or img.bits_per_pixel != 8:
        return np.zeros((H, W), np.uint8)
    arr = np.frombuffer(data, np.uint8)
    if arr.size != img.size.x * img.size.y:
        return np.zeros((H, W), np.uint8)
    arr = arr.reshape(img.size.y, img.size.x)
    return arr[:H, :W] if arr.shape >= (H, W) else np.zeros((H, W), np.uint8)


def obs_to_grid(raw_obs, map_size: Tuple[int, int], own_player: int,
                terrain: Optional[np.ndarray] = None) -> dict:
    """Raw proto -> numpy layers: terrain [H,W] u8, own(+ally)/enemy/neutral
    unit masks (proto Alliance: Self=1, Ally=2, Neutral=3, Enemy=4)."""
    W, H = map_size
    if terrain is None:
        terrain = np.zeros((H, W), np.uint8)
    own = np.zeros((H, W), bool)
    enemy = np.zeros((H, W), bool)
    neutral = np.zeros((H, W), bool)
    for u in raw_obs.units:
        x = int(np.clip(u.pos.x, 0, W - 1))
        y = int(np.clip(u.pos.y, 0, H - 1))
        if u.alliance in (1, 2):  # self + allies
            own[y, x] = True
        elif u.alliance == 4:
            enemy[y, x] = True
        else:  # neutral: minerals, geysers, destructibles
            neutral[y, x] = True
    return {"terrain": terrain, "own": own, "enemy": enemy, "neutral": neutral}


def render_ascii(grid: dict, width: int = 64) -> str:
    H, W = grid["own"].shape
    step_x = max(W // width, 1)
    step_y = max(H // (width // 2), 1)
    rows = []
    for y in range(0, H, step_y):
        row = []
        for x in range(0, W, step_x):
            oy, ox = slice(y, y + step_y), slice(x, x + step_x)
            if grid["own"][oy, ox].any():
                row.append("o")
            elif grid["enemy"][oy, ox].any():
                row.append("x")
            elif grid["neutral"][oy, ox].any():
                row.append("'")
            else:
                t = grid["terrain"][oy, ox]
                shade = int(t.mean()) * (len(ASCII_RAMP) - 1) // 255 if t.size else 0
                row.append(ASCII_RAMP[shade] if shade else ".")
        rows.append("".join(row))
    return "\n".join(rows)


def render_ppm(grid: dict, path: str) -> None:
    H, W = grid["own"].shape
    img = np.zeros((H, W, 3), np.uint8)
    img[..., :] = grid["terrain"][..., None] // 2 + 40  # terrain shading
    img[grid["neutral"]] = (180, 180, 90)
    img[grid["own"]] = (60, 220, 60)
    img[grid["enemy"]] = (220, 60, 60)
    img = img[::-1]  # y-up -> image row order
    with open(path, "wb") as f:
        f.write(f"P6 {W} {H} 255\n".encode())
        f.write(img.tobytes())


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--endpoint", default="", help="host:port of a running client")
    p.add_argument("--player", type=int, default=1)
    p.add_argument("--interval", type=float, default=1.0, help="seconds between frames")
    p.add_argument("--count", type=int, default=0, help="frames to capture (0 = forever)")
    p.add_argument("--ascii", action="store_true", help="live terminal map")
    p.add_argument("--frames", default="", help="directory for PPM frames")
    args = p.parse_args(argv)

    from ..envs.sc2.remote_controller import RemoteController

    if not args.endpoint:
        raise SystemExit("--endpoint host:port required (launch a client via "
                         "bin/sc2_tools or point at a live game)")
    host, _, port = args.endpoint.rpartition(":")
    controller = RemoteController(host or "127.0.0.1", int(port), timeout_seconds=30)
    gi = controller.game_info()
    map_size = (gi.start_raw.map_size.x, gi.start_raw.map_size.y)
    terrain = decode_terrain(gi, map_size)
    if args.frames:
        os.makedirs(args.frames, exist_ok=True)

    n = 0
    while args.count == 0 or n < args.count:
        obs = controller.observe()
        grid = obs_to_grid(obs.observation.raw_data, map_size, args.player, terrain)
        loop = obs.observation.game_loop
        if args.ascii:
            sys.stdout.write(f"\x1b[2J\x1b[Hloop {loop}\n{render_ascii(grid)}\n")
            sys.stdout.flush()
        if args.frames:
            render_ppm(grid, os.path.join(args.frames, f"frame_{n:05d}_loop{loop}.ppm"))
        n += 1
        if args.count == 0 or n < args.count:
            time.sleep(args.interval)
    if args.frames:
        print(f"{n} frames written to {args.frames}")


if __name__ == "__main__":
    main()
