"""Deterministic, seedable fault injector for the chaos suite and CLI.

Verifying that a fleet self-heals requires making it sick on purpose. The
injector produces the faults a week-long league run actually sees —
connection drops/delays/resets on the comm fabric, role death, checkpoint
truncation/bit-flips, NaN losses — from a seeded RNG so a failing chaos run
replays bit-identically. Usable three ways:

* as a library / pytest fixture (``ChaosInjector``; tests/conftest.py's
  ``chaos`` fixture restores all patches on teardown),
* from the CLI (``tools/chaos.py``: corrupt checkpoints, reset live
  connections, kill processes, inspect ``latest`` pointers),
* as remediation-drill input: faults fire the PR 3 health rules whose
  alerts the ``AlertRemediator`` turns into supervised restarts.

Every injected fault is logged to ``self.events`` and the flight recorder,
so a post-mortem distinguishes injected faults from organic ones.
"""
from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Callable, List, Optional

from .policy import CommError


def _recorder():
    from ..obs import get_flight_recorder

    return get_flight_recorder()


class ChaosInjector:
    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.seed = seed
        self.events: List[dict] = []
        self._patches: List[tuple] = []
        self._lock = threading.Lock()

    def _log(self, kind: str, **fields) -> None:
        event = {"ts": time.time(), "kind": kind, "seed": self.seed, **fields}
        with self._lock:
            self.events.append(event)
        _recorder().record(f"chaos_{kind}", **fields)

    # ------------------------------------------------------------- callables
    def wrap(self, fn: Callable, op: str = "", drop_p: float = 0.0,
             delay_p: float = 0.0, delay_s: float = 0.05, reset_p: float = 0.0,
             max_faults: Optional[int] = None) -> Callable:
        """Return ``fn`` wrapped with probabilistic faults (seeded, so a
        given seed yields the same fault schedule): ``drop`` raises
        ``CommError`` before the call, ``reset`` raises
        ``ConnectionResetError`` after it (the work happened but the reply
        was lost — the at-least-once case retries must tolerate), ``delay``
        sleeps first. ``max_faults`` bounds total injections."""
        op = op or getattr(fn, "__name__", "call")
        state = {"faults": 0}

        def chaotic(*args, **kwargs):
            budget_left = max_faults is None or state["faults"] < max_faults
            if budget_left and delay_p > 0 and self.rng.random() < delay_p:
                state["faults"] += 1
                self._log("delay", op=op, delay_s=delay_s)
                time.sleep(delay_s)
                budget_left = max_faults is None or state["faults"] < max_faults
            if budget_left and drop_p > 0 and self.rng.random() < drop_p:
                state["faults"] += 1
                self._log("drop", op=op)
                raise CommError(f"chaos: dropped {op}", op=op)
            result = fn(*args, **kwargs)
            budget_left = max_faults is None or state["faults"] < max_faults
            if budget_left and reset_p > 0 and self.rng.random() < reset_p:
                state["faults"] += 1
                self._log("reset", op=op)
                raise ConnectionResetError(f"chaos: reset after {op}")
            return result

        chaotic.__name__ = f"chaotic_{op}"
        return chaotic

    def fail_n_calls(self, fn: Callable, n: int = 1,
                     exc_factory: Optional[Callable[[], BaseException]] = None,
                     op: str = "") -> Callable:
        """Deterministic variant: the first ``n`` invocations raise, the
        rest pass through — the canonical "crash exactly once" fixture."""
        op = op or getattr(fn, "__name__", "call")
        state = {"left": n}

        def flaky(*args, **kwargs):
            if state["left"] > 0:
                state["left"] -= 1
                self._log("fail_call", op=op, remaining=state["left"])
                raise (exc_factory() if exc_factory
                       else CommError(f"chaos: injected failure in {op}", op=op))
            return fn(*args, **kwargs)

        return flaky

    def patch(self, obj, name: str, wrapper: Callable) -> None:
        """Install ``wrapper`` over ``obj.name``, remembering the original
        for ``restore()`` (fixture teardown)."""
        original = getattr(obj, name)
        self._patches.append((obj, name, original))
        setattr(obj, name, wrapper)

    def restore(self) -> None:
        while self._patches:
            obj, name, original = self._patches.pop()
            setattr(obj, name, original)

    # ------------------------------------------------------------------ files
    def truncate(self, path: str, keep_frac: float = 0.5) -> int:
        """Truncate a file to ``keep_frac`` of its size (a writer killed
        mid-write); returns the new size."""
        size = os.path.getsize(path)
        keep = int(size * keep_frac)
        with open(path, "rb+") as f:
            f.truncate(keep)
        self._log("truncate", path=path, old_size=size, new_size=keep)
        return keep

    def bitflip(self, path: str, flips: int = 8) -> List[int]:
        """Flip ``flips`` random bits in place (storage rot / torn sectors);
        returns the flipped byte offsets."""
        with open(path, "rb+") as f:
            data = bytearray(f.read())
            if not data:
                return []
            offsets = [self.rng.randrange(len(data)) for _ in range(flips)]
            for off in offsets:
                data[off] ^= 1 << self.rng.randrange(8)
            f.seek(0)
            f.write(data)
        self._log("bitflip", path=path, offsets=offsets)
        return offsets

    def corrupt_checkpoint(self, path: str, mode: str = "truncate") -> None:
        assert mode in ("truncate", "bitflip"), mode
        if mode == "truncate":
            self.truncate(path)
        else:
            self.bitflip(path)

    # ------------------------------------------------------------------ roles
    def kill_role(self, role, sig: int = signal.SIGTERM, name: str = "") -> None:
        """Kill a role by whatever handle we have: an object with ``stop()``
        (in-process servers — coordinator, serve gateway, replay store), a
        Popen (terminate), or a pid (os.kill). ``name`` tags the event for
        post-mortems ("replay", "coordinator", ...) when the handle's class
        name alone is ambiguous."""
        if hasattr(role, "stop"):
            self._log("kill_role", role=name or type(role).__name__)
            role.stop()
        elif hasattr(role, "terminate"):
            self._log("kill_role", role=name, pid=getattr(role, "pid", None))
            role.terminate()
        else:
            self._log("kill_role", role=name, pid=int(role), signal=int(sig))
            os.kill(int(role), sig)

    def poison_loss(self, learner, n: int = 1, value: float = float("nan")) -> None:
        """Make the next ``n`` learner train steps report a non-finite
        ``total_loss`` (fires the ``learner_loss_nonfinite`` rule without
        touching real numerics). Restored by ``restore()``."""
        original = learner._train
        state = {"left": n}

        def poisoned(data):
            out = original(data)
            if state["left"] > 0:
                state["left"] -= 1
                out = dict(out)
                out["total_loss"] = value
                self._log("nan_loss", remaining=state["left"])
            return out

        self._patches.append((learner, "_train", original))
        learner._train = poisoned

    def poison_module(self, learner, module: str, n: int = 1,
                      value: float = float("nan")) -> None:
        """Inject ``value`` into one element of the named top-level module's
        params immediately BEFORE the next ``n`` train steps — a real
        numeric fault, not a cosmetic log edit: the dynamics tree's
        pre-step params census (obs/dynamics.py) must name exactly this
        module, and the black-box bundle + stepreplay must reproduce the
        resulting non-finite step. Restored by ``restore()``."""
        original = learner._train
        state = {"left": n}

        def poisoned(data):
            if state["left"] > 0:
                state["left"] -= 1
                import jax
                import jax.numpy as jnp

                params = learner._state["params"]
                inner = params.get("params", params)
                target = inner[module]
                leaves, treedef = jax.tree_util.tree_flatten(target)

                # flip element [0...] of the module's first float leaf via a
                # jitted scatter: the poisoned arrays are fresh XLA buffers,
                # safe under the step's donation (mutating in place is not)
                def poison(leaf):
                    flat = leaf.reshape(-1)
                    flat = flat.at[0].set(jnp.asarray(value, leaf.dtype))
                    return flat.reshape(leaf.shape)

                for i, leaf in enumerate(leaves):
                    if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
                        leaves[i] = jax.jit(poison)(leaf)
                        break
                else:
                    raise ValueError(f"module {module!r} has no float leaves")
                new_inner = dict(inner)
                new_inner[module] = jax.tree_util.tree_unflatten(treedef, leaves)
                if "params" in params and isinstance(params.get("params"), dict):
                    learner._state["params"] = {**params, "params": new_inner}
                else:
                    learner._state["params"] = new_inner
                self._log("poison_module", module=module, value=repr(value),
                          remaining=state["left"])
            return original(data)

        self._patches.append((learner, "_train", original))
        learner._train = poisoned

    # ----------------------------------------------------------- connections
    def reset_connection(self, host: str, port: int, count: int = 1,
                         timeout_s: float = 5.0) -> int:
        """Open ``count`` TCP connections to host:port and abort them with
        RST (SO_LINGER 0) — exercises peer read paths against hard resets.
        Returns how many connected."""
        import socket
        import struct

        done = 0
        for _ in range(count):
            try:
                s = socket.create_connection((host, port), timeout=timeout_s)
            except OSError:
                continue
            s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         struct.pack("ii", 1, 0))
            s.close()
            done += 1
        self._log("reset_connection", host=host, port=port, count=done)
        return done
