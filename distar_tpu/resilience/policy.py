"""Typed retry primitive: jittered exponential backoff, deadline budgets,
an error taxonomy, and circuit-breaker state.

The IMPALA/AlphaStar lesson (PAPERS.md): throughput-oriented off-policy
training only works at scale if every link tolerates peer death. Before this
module each link hand-rolled its own tolerance (``league/remote.py`` had a
loop, ``coordinator_request`` had nothing, the shuttle had nothing) — one
broker restart killed whichever caller hit it first. Every cross-process
call now goes through one primitive with one observable contract:

* ``RetryableError`` / ``FatalError`` taxonomy — transport faults retry,
  logic faults surface immediately. ``CommError`` (the typed wrapper every
  HTTP/socket helper raises instead of leaking ``URLError``/timeout)
  subclasses BOTH ``RetryableError`` and ``ConnectionError``, so legacy
  ``except OSError`` call sites keep working while new code catches the
  taxonomy.
* ``RetryPolicy`` — max attempts, jittered exponential backoff, and a
  per-call ``deadline_s`` budget shared across attempts (a retried call can
  never take longer than its budget, no matter the policy).
* ``CircuitBreaker`` — after ``failure_threshold`` consecutive failures the
  circuit opens and calls fail fast with ``CircuitOpenError`` (no connect
  storms against a dead peer); after ``reset_after_s`` one probe is let
  through (half-open) and a success closes it.
* Every retry/giveup/breaker transition is observable:
  ``distar_resilience_*`` metrics plus flight-recorder events
  (docs/resilience.md).
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type


class RetryableError(Exception):
    """A transient fault: the operation may succeed if repeated."""


class FatalError(Exception):
    """A permanent fault: retrying cannot help (bad request, logic bug)."""


class CommError(RetryableError, ConnectionError):
    """Typed transport failure (connect refused, timeout, truncated reply).

    Wraps ``URLError``/``socket.timeout``/JSON-decode faults so call sites
    never see raw transport exceptions; ``op`` names the failing call."""

    def __init__(self, message: str, op: str = "", cause: Optional[BaseException] = None):
        super().__init__(message)
        self.op = op
        self.cause = cause


class CircuitOpenError(RetryableError):
    """Fail-fast rejection while a circuit breaker is open."""

    def __init__(self, op: str, retry_after_s: float = 0.0):
        super().__init__(f"circuit open for {op!r} (retry in ~{retry_after_s:.1f}s)")
        self.op = op
        self.retry_after_s = retry_after_s


def _metrics():
    from ..obs import get_registry

    return get_registry()


def _recorder():
    from ..obs import get_flight_recorder

    return get_flight_recorder()


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff/deadline contract for one logical call.

    ``deadline_s`` is a budget across ALL attempts (including sleeps): once
    exceeded the call gives up even with attempts left, and a backoff sleep
    is truncated so it can never overshoot the budget. ``jitter`` is the
    fractional +/- spread on each sleep (0.5 = 50%), decorrelating retry
    storms from a fleet that failed in lockstep."""

    max_attempts: int = 4
    backoff_base_s: float = 0.1
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 5.0
    jitter: float = 0.5
    deadline_s: Optional[float] = None
    retry_on: Tuple[Type[BaseException], ...] = (RetryableError, ConnectionError, OSError)

    def backoff_s(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Sleep before retry number ``attempt`` (0-based)."""
        base = min(
            self.backoff_base_s * (self.backoff_multiplier ** attempt),
            self.backoff_max_s,
        )
        if self.jitter <= 0:
            return base
        r = rng.random() if rng is not None else random.random()
        return base * (1.0 + self.jitter * (2.0 * r - 1.0))


#: single attempt, no sleeping — the "without the resilience layer" contract
NO_RETRY = RetryPolicy(max_attempts=1, backoff_base_s=0.0, jitter=0.0)

#: broker/league RPCs: survive a several-second peer restart by default
DEFAULT_COMM_POLICY = RetryPolicy(
    max_attempts=5, backoff_base_s=0.2, backoff_max_s=3.0, deadline_s=30.0
)


class CircuitBreaker:
    """Thread-safe closed -> open -> half-open failure gate for one peer."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
    _LEVEL = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, op: str = "", failure_threshold: int = 5,
                 reset_after_s: float = 30.0):
        assert failure_threshold >= 1
        self.op = op or "anonymous"
        self.failure_threshold = failure_threshold
        self.reset_after_s = reset_after_s
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_ts = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _set_state(self, state: str, now: float) -> None:
        if state == self._state:
            return
        self._state = state
        if state == self.OPEN:
            self._opened_ts = now
            _metrics().counter(
                "distar_resilience_breaker_open_total",
                "circuit-breaker open transitions", op=self.op,
            ).inc()
            _recorder().record("breaker_open", op=self.op,
                               failures=self._failures)
        _metrics().gauge(
            "distar_resilience_breaker_state",
            "0 closed / 1 half-open / 2 open", op=self.op,
        ).set(self._LEVEL[state])

    def allow(self, now: Optional[float] = None) -> bool:
        """May a call proceed right now? Open circuits let one probe through
        once ``reset_after_s`` has elapsed (half-open)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._state == self.OPEN:
                if now - self._opened_ts >= self.reset_after_s:
                    self._set_state(self.HALF_OPEN, now)
                    return True
                return False
            return True

    def retry_after_s(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(0.0, self.reset_after_s - (now - self._opened_ts))

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._set_state(self.CLOSED, time.monotonic())

    def record_failure(self) -> None:
        now = time.monotonic()
        with self._lock:
            self._failures += 1
            if self._state == self.HALF_OPEN or self._failures >= self.failure_threshold:
                self._set_state(self.OPEN, now)


def retry_call(fn: Callable, *args, op: str = "", policy: Optional[RetryPolicy] = None,
               breaker: Optional[CircuitBreaker] = None,
               rng: Optional[random.Random] = None,
               sleep: Callable[[float], None] = time.sleep, **kwargs):
    """Invoke ``fn(*args, **kwargs)`` under ``policy``.

    Retries only exceptions matching ``policy.retry_on`` that are not
    ``FatalError``; everything else propagates untouched on the first
    occurrence. With a ``breaker``, an open circuit raises
    ``CircuitOpenError`` without consuming an attempt's worth of connect
    timeout. ``rng``/``sleep`` are injection points for deterministic tests
    (and the chaos harness)."""
    policy = policy or DEFAULT_COMM_POLICY
    op = op or getattr(fn, "__name__", "call")
    start = time.monotonic()
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        if breaker is not None and not breaker.allow():
            raise CircuitOpenError(op, breaker.retry_after_s()) from last
        try:
            result = fn(*args, **kwargs)
        except FatalError:
            if breaker is not None:
                breaker.record_failure()
            raise
        except policy.retry_on as e:
            if breaker is not None:
                breaker.record_failure()
            last = e
            elapsed = time.monotonic() - start
            out_of_budget = (
                policy.deadline_s is not None and elapsed >= policy.deadline_s
            )
            if attempt + 1 >= policy.max_attempts or out_of_budget:
                _metrics().counter(
                    "distar_resilience_giveups_total",
                    "calls abandoned after exhausting retries/deadline", op=op,
                ).inc()
                _recorder().record(
                    "retry_giveup", op=op, attempts=attempt + 1,
                    elapsed_s=round(elapsed, 3), error=repr(e),
                )
                raise
            pause = policy.backoff_s(attempt, rng)
            if policy.deadline_s is not None:
                pause = min(pause, max(0.0, policy.deadline_s - elapsed))
            _metrics().counter(
                "distar_resilience_retries_total", "retried call attempts", op=op,
            ).inc()
            _recorder().record(
                "retry", op=op, attempt=attempt + 1, backoff_s=round(pause, 3),
                error=repr(e),
            )
            if pause > 0:
                sleep(pause)
        else:
            if breaker is not None:
                breaker.record_success()
            return result
    raise RuntimeError(f"unreachable: retry_call({op}) fell through")  # pragma: no cover


def retryable(op: str = "", policy: Optional[RetryPolicy] = None,
              breaker: Optional[CircuitBreaker] = None):
    """Decorator form of ``retry_call`` for functions that are always
    retried under the same policy."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, op=op or fn.__name__, policy=policy,
                              breaker=breaker, **kwargs)

        return wrapped

    return deco
