"""Fault-tolerance layer: retry/backoff fabric, crash-resume supervision,
and a deterministic chaos-injection harness.

PR 3's health layer detects stalls and NaNs; this package is what survives
and remediates them — the self-healing half of the fleet. See
docs/resilience.md for the failure model and defaults.
"""
from .policy import (
    DEFAULT_COMM_POLICY,
    NO_RETRY,
    CircuitBreaker,
    CircuitOpenError,
    CommError,
    FatalError,
    RetryPolicy,
    RetryableError,
    retry_call,
    retryable,
)
from .supervisor import (
    AlertRemediator,
    RestartPolicy,
    Supervisor,
    TaskContext,
    supervise_call,
)
from .chaos import ChaosInjector

__all__ = [
    "DEFAULT_COMM_POLICY",
    "NO_RETRY",
    "CircuitBreaker",
    "CircuitOpenError",
    "CommError",
    "FatalError",
    "RetryPolicy",
    "RetryableError",
    "retry_call",
    "retryable",
    "AlertRemediator",
    "RestartPolicy",
    "Supervisor",
    "TaskContext",
    "supervise_call",
    "ChaosInjector",
]
