"""Role supervision: restart crashed fleet roles instead of losing the run.

The reference survives week-long league runs operationally (systemd/k8s
restart the worker, the worker resumes from its checkpoint); this module is
the in-process half of that contract for the threads/loops our launchers
own:

* ``Supervisor`` — named background tasks (actor loops, dataloader pumps)
  run on watchdog threads: a crash is recorded, backed off, and restarted,
  bounded by a ``RestartPolicy`` (max restarts per sliding window, then
  give up and escalate). Tasks receive a ``TaskContext`` for cooperative
  stop/restart — remediation can bounce a live-but-stalled loop without
  killing the process.
* ``supervise_call`` — foreground supervision for the role that owns the
  main thread (the learner): run, and on a crash invoke ``on_restart``
  (checkpoint resume) and run again under the same restart budget.
* ``AlertRemediator`` — the bridge from the PR 3 health layer: a firing
  ``stalled``/``nonfinite`` rule triggers a supervised restart of the
  mapped task, closing the detect -> remediate loop.

Every restart/giveup/remediation is observable (``distar_resilience_*``
metrics + flight-recorder events).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, Mapping, Optional

from .policy import RetryPolicy


def _metrics():
    from ..obs import get_registry

    return get_registry()


def _recorder():
    from ..obs import get_flight_recorder

    return get_flight_recorder()


@dataclass(frozen=True)
class RestartPolicy:
    """Restart budget: at most ``max_restarts`` within ``window_s`` (sliding),
    with exponential backoff between restarts."""

    max_restarts: int = 5
    window_s: float = 300.0
    backoff_base_s: float = 0.5
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 30.0

    def backoff_s(self, restart_no: int) -> float:
        return min(
            self.backoff_base_s * (self.backoff_multiplier ** restart_no),
            self.backoff_max_s,
        )


class TaskContext:
    """Cooperative control surface handed to every supervised target.

    Long-running targets should poll ``should_exit`` (stop requested OR
    restart requested) at loop boundaries; returning normally with a
    pending restart request re-enters the target instead of retiring the
    task."""

    def __init__(self):
        self._stop = threading.Event()
        self._restart = threading.Event()
        self.restart_reason: Optional[str] = None

    @property
    def stop_requested(self) -> bool:
        return self._stop.is_set()

    @property
    def restart_requested(self) -> bool:
        return self._restart.is_set()

    @property
    def should_exit(self) -> bool:
        return self._stop.is_set() or self._restart.is_set()

    def request_stop(self) -> None:
        self._stop.set()

    def request_restart(self, reason: str = "") -> None:
        self.restart_reason = reason or self.restart_reason
        self._restart.set()

    def sleep(self, seconds: float) -> bool:
        """Interruptible sleep; returns True when the task should exit."""
        return self._stop.wait(seconds) or self._restart.is_set()


class _Task:
    def __init__(self, name: str, target: Callable[[TaskContext], None],
                 policy: RestartPolicy,
                 on_restart: Optional[Callable[[BaseException], None]],
                 on_giveup: Optional[Callable[[BaseException], None]]):
        self.name = name
        self.target = target
        self.policy = policy
        self.on_restart = on_restart
        self.on_giveup = on_giveup
        self.ctx = TaskContext()
        self.thread: Optional[threading.Thread] = None
        self.restarts = 0
        self.gave_up = False
        self.finished = False
        self.last_error: Optional[str] = None
        self._restart_times: deque = deque()


class Supervisor:
    """Owns a set of supervised background tasks (one watchdog thread each)."""

    def __init__(self, policy: Optional[RestartPolicy] = None):
        self.default_policy = policy or RestartPolicy()
        self._tasks: Dict[str, _Task] = {}
        self._lock = threading.Lock()
        self._started = False

    # -------------------------------------------------------------- lifecycle
    def add(self, name: str, target: Callable[[TaskContext], None],
            policy: Optional[RestartPolicy] = None,
            on_restart: Optional[Callable[[BaseException], None]] = None,
            on_giveup: Optional[Callable[[BaseException], None]] = None) -> "Supervisor":
        with self._lock:
            assert name not in self._tasks, f"duplicate task {name!r}"
            task = _Task(name, target, policy or self.default_policy,
                         on_restart, on_giveup)
            self._tasks[name] = task
            if self._started:
                self._spawn(task)
        return self

    def start(self) -> "Supervisor":
        with self._lock:
            if self._started:
                return self
            self._started = True
            for task in self._tasks.values():
                self._spawn(task)
        return self

    def stop(self, timeout: float = 10.0) -> None:
        with self._lock:
            tasks = list(self._tasks.values())
            self._started = False
        for task in tasks:
            task.ctx.request_stop()
        deadline = time.monotonic() + timeout
        for task in tasks:
            t = task.thread
            if t is not None:
                t.join(max(0.0, deadline - time.monotonic()))

    def _spawn(self, task: _Task) -> None:
        task.thread = threading.Thread(
            target=self._run, args=(task,), name=f"supervised-{task.name}", daemon=True
        )
        task.thread.start()

    # ------------------------------------------------------------------- loop
    def _run(self, task: _Task) -> None:
        while not task.ctx.stop_requested:
            task.ctx._restart.clear()
            error: Optional[BaseException] = None
            try:
                task.target(task.ctx)
            except BaseException as e:
                error = e
            if task.ctx.stop_requested:
                break
            if error is None and not task.ctx.restart_requested:
                break  # clean retirement
            reason = (
                repr(error) if error is not None
                else f"remediation:{task.ctx.restart_reason or 'requested'}"
            )
            if not self._budget_ok(task):
                task.gave_up = True
                task.last_error = reason
                _metrics().counter(
                    "distar_resilience_task_giveups_total",
                    "supervised tasks abandoned (restart budget exhausted)",
                    task=task.name,
                ).inc()
                _recorder().record("task_giveup", task=task.name, reason=reason,
                                   restarts=task.restarts)
                if task.on_giveup is not None:
                    try:
                        task.on_giveup(error if error is not None
                                       else RuntimeError(reason))
                    except Exception:
                        pass
                break
            restart_no = task.restarts
            task.restarts += 1
            task.last_error = reason
            _metrics().counter(
                "distar_resilience_restarts_total", "supervised task restarts",
                task=task.name,
            ).inc()
            _recorder().record("task_restart", task=task.name, reason=reason,
                               restart_no=task.restarts)
            if task.on_restart is not None:
                try:
                    task.on_restart(error if error is not None
                                    else RuntimeError(reason))
                except Exception:
                    pass
            if task.ctx._stop.wait(task.policy.backoff_s(restart_no)):
                break
        task.finished = True

    def _budget_ok(self, task: _Task) -> bool:
        now = time.monotonic()
        window = task._restart_times
        while window and now - window[0] > task.policy.window_s:
            window.popleft()
        if len(window) >= task.policy.max_restarts:
            return False
        window.append(now)
        return True

    # ---------------------------------------------------------------- surface
    def restart(self, name: str, reason: str = "") -> bool:
        """Request a cooperative restart of a live task (remediation path)."""
        with self._lock:
            task = self._tasks.get(name)
        if task is None or task.gave_up or task.finished:
            return False
        task.ctx.request_restart(reason)
        return True

    def status(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "alive": task.thread.is_alive() if task.thread else False,
                    "restarts": task.restarts,
                    "gave_up": task.gave_up,
                    "last_error": task.last_error,
                }
                for name, task in self._tasks.items()
            }

    def join(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            tasks = list(self._tasks.values())
        for task in tasks:
            t = task.thread
            if t is None:
                continue
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))


def supervise_call(fn: Callable[[], None], op: str = "main",
                   policy: Optional[RestartPolicy] = None,
                   on_restart: Optional[Callable[[BaseException], None]] = None,
                   sleep: Callable[[float], None] = time.sleep) -> None:
    """Foreground supervision for the role owning the calling thread (the
    learner run loop): run ``fn``; on a crash call ``on_restart(error)``
    (checkpoint resume) and run again, bounded by ``policy``. The final
    failure re-raises so the process still dies loudly when the budget is
    exhausted (the flight recorder bundles the history)."""
    policy = policy or RestartPolicy()
    window: deque = deque()
    restart_no = 0
    while True:
        try:
            return fn()
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            now = time.monotonic()
            while window and now - window[0] > policy.window_s:
                window.popleft()
            if len(window) >= policy.max_restarts:
                _metrics().counter(
                    "distar_resilience_task_giveups_total",
                    "supervised tasks abandoned (restart budget exhausted)",
                    task=op,
                ).inc()
                _recorder().record("task_giveup", task=op, reason=repr(e),
                                   restarts=restart_no)
                raise
            window.append(now)
            _metrics().counter(
                "distar_resilience_restarts_total", "supervised task restarts",
                task=op,
            ).inc()
            _recorder().record("task_restart", task=op, reason=repr(e),
                               restart_no=restart_no + 1)
            if on_restart is not None:
                on_restart(e)
            sleep(policy.backoff_s(restart_no))
            restart_no += 1


class AlertRemediator:
    """Bridge PR 3 health alerts into supervised restarts.

    ``mapping`` routes a firing rule name to a supervised task name; when the
    ``HealthEvaluator`` emits a ``firing`` transition for a mapped rule the
    remediator requests a cooperative restart of that task (debounce lives in
    the rules engine — exactly one firing event per incident means exactly
    one remediation per incident)."""

    def __init__(self, supervisor: Supervisor, mapping: Mapping[str, str]):
        self.supervisor = supervisor
        self.mapping = dict(mapping)

    def attach(self, evaluator) -> "AlertRemediator":
        evaluator.add_transition_callback(self.on_event)
        return self

    def on_event(self, event: dict) -> None:
        if event.get("state") != "firing":
            return
        task = self.mapping.get(event.get("rule"))
        if task is None:
            return
        if self.supervisor.restart(task, reason=f"alert:{event.get('rule')}"):
            _metrics().counter(
                "distar_resilience_remediations_total",
                # analysis: allow(metric-label-cardinality) — rule names are bounded by the declarative rulebook (obs/health.py), never by request data
                "alert-triggered supervised restarts", rule=event.get("rule"),
            ).inc()
            _recorder().record("remediation", rule=event.get("rule"), task=task)
