"""Pipeline plugin registry: per-pipeline component resolution.

Role of the reference's agent plugin system (reference:
distar/agent/import_helper.py:1-19 resolves ``distar.agent.<pipeline>`` to
one of {Agent, RLLearner, SLLearner, ReplayDecoder}; distar/agent/template/
is the user-facing skeleton): every league player carries a ``pipeline``
name, and the Actor, the train CLIs, and the replay tooling resolve their
per-pipeline implementation through this one registry.

Pipelines:

- ``default`` (or empty) — the flagship TPU model stack in this package.
- ``bot`` — a built-in SC2 bot side; has no importable components.
- ``scripted.<name>`` — model-free scripted agents (actor/scripted.py);
  they provide only ``Agent``.
- any other name — an importable module path (``my_pkg.my_pipeline``).
  The module exposes the component classes by name, the reference's
  ``distar/agent/<name>/`` convention generalized to any module on
  ``sys.path`` so user code lives outside the installed package.

Custom-pipeline agents implement docs/agent_contract.md and OWN their
inference: the Actor's jitted fixed-shape lockstep batch is the default
pipeline's fast path, while a custom agent computes actions inside
``step(obs)`` however it likes (its own jitted model, a policy table, a
remote call). They ride the Actor's model-free path — no inference slot,
no teacher, no trajectory assembly unless the agent does its own.
"""
from __future__ import annotations

import importlib

COMPONENTS = ("Agent", "RLLearner", "SLLearner", "ReplayDecoder")

_DEFAULTS = {
    "Agent": ("distar_tpu.actor.agent", "Agent"),
    "RLLearner": ("distar_tpu.learner", "RLLearner"),
    "SLLearner": ("distar_tpu.learner", "SLLearner"),
    "ReplayDecoder": ("distar_tpu.envs.replay_decoder", "ReplayDecoder"),
}


def is_default(pipeline) -> bool:
    return pipeline in (None, "", "default")


def is_external(pipeline) -> bool:
    """True for user-module pipelines (not default/bot/scripted).

    Any ``scripted.*`` name classifies as scripted — including typos,
    which load_component diagnoses against the registry rather than
    treating as an importable module.
    """
    return not (
        is_default(pipeline)
        or pipeline == "bot"
        or str(pipeline).startswith("scripted.")
    )


def is_model_free(pipeline) -> bool:
    """Sides whose agent acts without the Actor's batched inference slots:
    scripted built-ins and all external pipelines (which own their
    inference, see module docstring)."""
    return not is_default(pipeline) and pipeline != "bot"


def load_component(pipeline, component: str):
    """Resolve a component class for a pipeline name.

    Mirrors reference import_helper.import_module(pipeline, name), with
    error messages that point at the contract instead of a bare
    AttributeError deep inside importlib.
    """
    if component not in COMPONENTS:
        raise ValueError(
            f"unknown component {component!r}; one of {COMPONENTS}"
        )
    if is_default(pipeline):
        mod_name, attr = _DEFAULTS[component]
        return getattr(importlib.import_module(mod_name), attr)
    if pipeline == "bot":
        raise ValueError("'bot' sides are played by the SC2 engine; "
                         "they have no importable components")

    from .actor.scripted import SCRIPTED_PIPELINES, is_scripted

    if is_scripted(pipeline):
        if component != "Agent":
            raise ValueError(
                f"scripted pipeline {pipeline!r} provides only Agent, "
                f"not {component}"
            )
        return SCRIPTED_PIPELINES[pipeline]
    if str(pipeline).startswith("scripted."):
        # typo'd scripted name: diagnose against the registry instead of
        # falling through to a misleading plugin-module ImportError
        raise ValueError(
            f"unknown scripted pipeline {pipeline!r}; "
            f"one of {sorted(SCRIPTED_PIPELINES)}"
        )

    try:
        module = importlib.import_module(pipeline)
    except ImportError as e:
        raise ImportError(
            f"pipeline {pipeline!r} is not importable ({e}); a custom "
            "pipeline is a module on sys.path exposing "
            f"{'/'.join(COMPONENTS)} classes (docs/agent_contract.md)"
        ) from e
    try:
        return getattr(module, component)
    except AttributeError:
        raise AttributeError(
            f"pipeline module {pipeline!r} defines no {component!r}; "
            "expose the class by that exact name (docs/agent_contract.md)"
        ) from None


def build_agent(pipeline, player_id: str, seed: int = 0, race=None):
    """Construct a model-free agent for an Actor side.

    Scripted built-ins and external agents share one construction
    convention: keyword args (player_id, seed, race), and the class must
    tolerate unknown kwargs (the contract's ``**kwargs``).
    """
    from .actor.scripted import build_scripted, is_scripted

    if is_scripted(pipeline):
        return build_scripted(pipeline, player_id, seed=seed, race=race)
    cls = load_component(pipeline, "Agent")
    return cls(player_id=player_id, seed=seed, race=race)
