"""Lock-discipline checker: what may happen inside a ``with self._lock:`` body.

Encodes two incidents and one classic hazard:

* PR 4's ``AlertRemediator`` lesson — user callbacks must be dispatched
  OUTSIDE the lock that guards the callback list (a callback that re-enters
  the subsystem deadlocks; one that blocks starves every other waiter) —
  rule ``lock-callback-dispatch``;
* the shuttle/serve deadline work — blocking calls (socket recv/accept,
  ``Event.wait``, ``sleep``, ``join``, comm/retry calls) while holding a lock
  turn a slow peer into a fleet-wide stall — rule ``lock-held-blocking``;
* inconsistent nested acquisition order of two named locks within one module
  is the textbook ABBA deadlock — rule ``lock-order-inversion`` (the dynamic
  witness is analysis/lockwatch.py).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Checker, Finding, ParsedModule, call_name, dotted_name, walk_scope

#: attribute/name spellings that mean "this is a lock/condition object"
LOCK_NAME_RE = re.compile(r"(^|_)(lock|locks|mutex|mu|cv|cond|condition)$")

#: terminal call names that block the calling thread
BLOCKING_CALLS = {
    "recv", "recv_into", "recvfrom", "accept", "connect", "sendall",
    "select", "sleep", "urlopen", "create_connection",
    # project comm/retry primitives: each one can ride a multi-second
    # backoff/deadline budget (resilience/policy.py) — never under a lock
    "coordinator_request", "retry_call", "league_request", "supervise_call",
    "ship_once",
}

#: called-name spellings that mean "user callback dispatch"
CALLBACK_RE = re.compile(r"(^|_)(callback|callbacks|cb|cbs|hook|hooks|listener|listeners)$")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_THREADISH_RE = re.compile(r"(^|_)(thread|threads|worker|workers|proc|procs|process)")


def _is_lock_expr(expr: ast.AST, known_locks: Set[str]) -> Optional[str]:
    """Dotted name when ``expr`` looks like a lock acquisition target."""
    dotted = dotted_name(expr)
    if not dotted:
        return None
    terminal = dotted.rsplit(".", 1)[-1]
    if LOCK_NAME_RE.search(terminal) or dotted in known_locks:
        return dotted
    return None


class LockChecker(Checker):
    name = "locks"
    rules = {
        "lock-held-blocking": "error",
        "lock-callback-dispatch": "error",
        "lock-order-inversion": "error",
    }

    def _known_locks(self, mod: ParsedModule) -> Tuple[Set[str], Set[str]]:
        """(lock attrs/names assigned from threading.Lock/RLock/Condition,
        thread attrs assigned from threading.Thread) in this module."""
        locks: Set[str] = set()
        threads: Set[str] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
                continue
            ctor = call_name(node.value)
            for tgt in node.targets:
                dotted = dotted_name(tgt)
                if not dotted:
                    continue
                if ctor in _LOCK_CTORS:
                    locks.add(dotted)
                elif ctor == "Thread":
                    threads.add(dotted)
        return locks, threads

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        known_locks, known_threads = self._known_locks(mod)
        findings: List[Finding] = []
        # edges: (class-scoped outer lock, inner lock) -> first line observed
        edges: Dict[Tuple[str, str], int] = {}

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            held = [
                d for item in node.items
                if (d := _is_lock_expr(item.context_expr, known_locks))
            ]
            if not held:
                continue
            cls = mod.enclosing_class(node)
            scope = cls.name if cls is not None else ""
            # one lexical level only: nested withs record their own edges
            for child in walk_scope(node):
                if isinstance(child, ast.With):
                    for item in child.items:
                        inner = _is_lock_expr(item.context_expr, known_locks)
                        if inner and inner not in held:
                            for h in held:
                                edges.setdefault(
                                    (f"{scope}:{h}", f"{scope}:{inner}"),
                                    child.lineno,
                                )
                    continue
                if not isinstance(child, ast.Call):
                    continue
                findings.extend(
                    self._check_call_under_lock(mod, child, held, known_threads)
                )

        for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
            if a >= b or (b, a) not in edges:
                continue
            an, bn = a.split(":", 1)[1], b.split(":", 1)[1]
            findings.append(self.finding(
                "lock-order-inversion", mod, line,
                f"locks {an!r} and {bn!r} are acquired in both orders in this "
                f"module (here and near line {edges[(b, a)]}) — pick one order "
                f"or merge the critical sections (ABBA deadlock)",
                ident=f"inversion {an} <-> {bn}",
            ))
        return findings

    def _check_call_under_lock(self, mod: ParsedModule, call: ast.Call,
                               held: List[str], known_threads: Set[str]
                               ) -> Iterable[Finding]:
        name = call_name(call)
        func = call.func
        receiver = dotted_name(func.value) if isinstance(func, ast.Attribute) else ""
        held_txt = "/".join(sorted(set(held)))

        # --- user-callback dispatch under the lock (PR 4's incident class)
        cb_target = ""
        if isinstance(func, ast.Name) and CALLBACK_RE.search(func.id):
            cb_target = func.id
        elif isinstance(func, ast.Attribute) and CALLBACK_RE.search(func.attr):
            cb_target = dotted_name(func)
        elif isinstance(func, ast.Subscript):
            sub = dotted_name(func.value)
            if sub and CALLBACK_RE.search(sub.rsplit(".", 1)[-1]):
                cb_target = sub + "[...]"
        if cb_target:
            yield self.finding(
                "lock-callback-dispatch", mod, call.lineno,
                f"user callback {cb_target!r} dispatched while holding "
                f"{held_txt} — snapshot the list under the lock, call outside "
                f"it (a re-entrant callback deadlocks here)",
                ident=f"callback {cb_target} under {held_txt}",
            )
            return

        # --- blocking primitives under the lock
        blocking = None
        if name in BLOCKING_CALLS:
            # ".connect(" on non-socket receivers (signal connect etc.) is
            # rare in this tree; accept the terminal-name heuristic and let
            # pragmas carry the exceptions.
            blocking = name
        elif name == "join":
            # str.join / os.path.join are not blocking; thread/process join is
            recv_term = receiver.rsplit(".", 1)[-1] if receiver else ""
            if receiver in known_threads or _THREADISH_RE.search(recv_term):
                blocking = "join"
        elif name in ("wait", "wait_for"):
            # cond.wait() on the HELD condition releases it while waiting —
            # that is the condition-variable idiom, not a hazard. Waiting on
            # anything else (an Event, another condition) holds our lock the
            # whole time.
            if receiver and receiver not in held:
                blocking = f"{receiver}.{name}"
        if blocking:
            yield self.finding(
                "lock-held-blocking", mod, call.lineno,
                f"blocking call {blocking!r} while holding {held_txt} — every "
                f"other thread contending this lock stalls for the full wait; "
                f"move the blocking call outside the critical section",
                ident=f"blocking {blocking} under {held_txt}",
            )
