"""Analyzer framework: parsed-module cache, checkers, pragmas, baseline, reports.

The three ad-hoc lints (no-print, metric-names, sockets) each walked the tree
and parsed every file themselves; every new invariant would have added another
full parse pass. Here the tree is parsed ONCE into ``ParsedModule`` objects
(AST + source lines + pragma index + parent links) and every checker visits
the shared cache. Checkers are small classes emitting ``Finding``s; the
framework owns suppression (``# analysis: allow(<rule>) — <why>`` pragmas),
the committed baseline of grandfathered findings (shrink-only: a baseline
entry that no longer fires is itself an error), and rendering (JSON + ranked
markdown). Exit-code contract (tools/analyze.py): 0 = clean,
1 = baselined-only, 2 = new findings or stale baseline entries.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning")

#: scoped suppression: ``# analysis: allow(rule-a,rule-b) — reason`` on the
#: offending line or the line directly above it. The reason is REQUIRED —
#: an unexplained suppression is itself a finding (pragma-no-reason).
PRAGMA_RE = re.compile(
    r"#\s*analysis:\s*allow\(\s*([A-Za-z0-9_,\s-]+?)\s*\)\s*(?:[—–-]+\s*(\S.*))?$"
)

#: legacy single-rule markers kept working so the pre-framework opt-outs
#: (and their documented syntax) never break: marker -> rules it suppresses
LEGACY_MARKERS = {
    "# lint: allow-print": ("no-print",),
    "# lint: allow-bare-except": ("socket-bare-except",),
    "# lint: allow-no-timeout": ("socket-no-timeout",),
}

SKIP_DIRS = {"__pycache__", "_proto_gen", ".git", ".claude"}


@dataclass
class Finding:
    """One rule violation. ``(rule, path, ident)`` is the baseline
    fingerprint — ``ident`` defaults to the message and must stay stable
    across unrelated edits (so never put line numbers in it)."""

    rule: str
    severity: str
    path: str  # repo-relative (posix) when under the repo, else absolute
    line: int
    message: str
    abspath: str = ""
    ident: str = ""

    def __post_init__(self):
        assert self.severity in SEVERITIES, self.severity
        if not self.ident:
            self.ident = self.message

    def fingerprint(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.ident)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}/{self.severity}] {self.message}"


class ParsedModule:
    """One parsed source file: AST, raw lines, pragma index, parent links.

    Parsed lazily exactly once and shared by every checker (the whole point
    of the framework: one parse pass instead of one per lint)."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.relpath = relpath  # forward-slash, repo-relative when possible
        with open(abspath, "rb") as f:
            self.source = f.read()
        self.text = self.source.decode("utf-8", errors="replace")
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.source, filename=abspath)
        except SyntaxError as e:
            self.syntax_error = e
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self._pragmas: Optional[Dict[int, List[Tuple[Tuple[str, ...], str]]]] = None

    # ------------------------------------------------------------------ pragmas
    @property
    def pragmas(self) -> Dict[int, List[Tuple[Tuple[str, ...], str]]]:
        """line -> [(rules, reason)] for every suppression comment."""
        if self._pragmas is None:
            out: Dict[int, List[Tuple[Tuple[str, ...], str]]] = {}
            for i, line in enumerate(self.lines, start=1):
                if "#" not in line:
                    continue
                m = PRAGMA_RE.search(line)
                if m:
                    rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
                    out.setdefault(i, []).append((rules, (m.group(2) or "").strip()))
                for marker, rules in LEGACY_MARKERS.items():
                    if marker in line:
                        out.setdefault(i, []).append((rules, "legacy lint marker"))
            self._pragmas = out
        return self._pragmas

    def pragma_for(self, line: int, rule: str) -> Optional[str]:
        """Reason string when ``rule`` is suppressed at ``line`` (same line or
        the line directly above); None otherwise. Empty reason returns ''."""
        for at in (line, line - 1):
            for rules, reason in self.pragmas.get(at, ()):
                if rule in rules:
                    return reason
        return None

    def line_text(self, line: int) -> str:
        return self.lines[line - 1] if 0 < line <= len(self.lines) else ""

    # ------------------------------------------------------------------ parents
    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            parents: Dict[ast.AST, ast.AST] = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        parents[child] = node
            self._parents = parents
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return a
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a
        return None


# ----------------------------------------------------------------- AST helpers
def call_name(node: ast.Call) -> str:
    """Terminal name of the called thing ('recv' for sock.recv(...))."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def dotted_name(expr: ast.AST) -> str:
    """Best-effort dotted rendering ('self._lock', 'jax.device_get');
    '' for anything that isn't a plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    elif not parts:
        return ""
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def walk_scope(node: ast.AST, skip_nested_defs: bool = True) -> Iterable[ast.AST]:
    """Walk ``node``'s subtree; when ``skip_nested_defs``, do not descend into
    nested function/lambda bodies (code there runs LATER, not here — a closure
    defined under a lock does not execute under it)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        yield cur
        if skip_nested_defs and isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(cur))


def is_library_path(relpath: str) -> bool:
    """True for files inside the distar_tpu package, excluding CLI
    entrypoints (bin/) — where the no-print rule applies."""
    parts = relpath.replace(os.sep, "/").split("/")
    if "distar_tpu" not in parts:
        return False
    after = parts[parts.index("distar_tpu") + 1:]
    return "bin" not in after


# --------------------------------------------------------------------- checker
class Checker:
    """Base checker: visit each parsed module, then a cross-module finalize.

    ``rules`` maps rule-id -> default severity (the framework's report
    groups by these). Checkers should emit findings through ``finding()`` so
    severity defaults stay in one place."""

    name = "checker"
    rules: Dict[str, str] = {}

    def finding(self, rule: str, mod: ParsedModule, line: int, message: str,
                ident: str = "", severity: Optional[str] = None) -> Finding:
        return Finding(
            rule=rule,
            severity=severity or self.rules[rule],
            path=mod.relpath,
            line=line,
            message=message,
            abspath=mod.abspath,
            ident=ident,
        )

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:  # pragma: no cover
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


# -------------------------------------------------------------------- analyzer
def collect_files(paths: Sequence[str], repo_root: Optional[str] = None) -> List[str]:
    """Expand files/dirs into a sorted list of .py files (skipping
    __pycache__/_proto_gen). Non-.py files named explicitly are ignored."""
    out: List[str] = []
    for p in paths:
        p = os.path.abspath(os.path.join(repo_root, p) if repo_root and not os.path.isabs(p) else p)
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)  # new (not baselined)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: List[Tuple[Finding, str]] = field(default_factory=list)  # (finding, reason)
    stale_baseline: List[dict] = field(default_factory=list)  # entries that no longer fire
    files: int = 0
    parse_errors: List[str] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        if self.findings or self.stale_baseline:
            return 2
        if self.baselined:
            return 1
        return 0

    def to_dict(self) -> dict:
        return {
            "files": self.files,
            "exit_code": self.exit_code,
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "suppressed": [
                dict(f.to_dict(), reason=reason) for f, reason in self.suppressed
            ],
            "stale_baseline": list(self.stale_baseline),
            "parse_errors": list(self.parse_errors),
        }


class Analyzer:
    """Run a set of checkers over a file list with one shared parse cache."""

    def __init__(self, repo_root: Optional[str] = None,
                 checkers: Optional[Sequence[Checker]] = None,
                 rules: Optional[Sequence[str]] = None):
        self.repo_root = os.path.abspath(repo_root or repo_root_of(__file__))
        self.checkers = list(checkers) if checkers is not None else default_checkers(self.repo_root)
        self.rules = set(rules) if rules else None
        self._cache: Dict[str, ParsedModule] = {}

    def parse(self, abspath: str) -> ParsedModule:
        mod = self._cache.get(abspath)
        if mod is None:
            try:
                rel = os.path.relpath(abspath, self.repo_root)
            except ValueError:  # different drive (windows); keep absolute
                rel = abspath
            relpath = abspath if rel.startswith("..") else rel.replace(os.sep, "/")
            mod = ParsedModule(abspath, relpath)
            self._cache[abspath] = mod
        return mod

    def run(self, files: Sequence[str],
            baseline: Optional[List[dict]] = None) -> AnalysisResult:
        result = AnalysisResult(files=len(files))
        mods: List[ParsedModule] = []
        for f in files:
            mod = self.parse(f)
            if mod.syntax_error is not None:
                result.parse_errors.append(f"{mod.relpath}: {mod.syntax_error}")
                continue
            mods.append(mod)
        raw: List[Finding] = []
        for checker in self.checkers:
            for mod in mods:
                raw.extend(checker.check_module(mod))
            raw.extend(checker.finalize())
        if self.rules is not None:
            raw = [f for f in raw if f.rule in self.rules]
        # pragma suppression (framework-owned so every checker gets it free)
        kept: List[Finding] = []
        for f in raw:
            mod = self._cache.get(f.abspath)
            reason = mod.pragma_for(f.line, f.rule) if mod is not None else None
            if reason is None:
                kept.append(f)
            elif reason == "":
                # an unexplained suppression is itself a finding: the pragma
                # contract is allow(<rule>) — <why>, and the why is the point
                kept.append(Finding(
                    rule="pragma-no-reason", severity="error", path=f.path,
                    line=f.line, abspath=f.abspath,
                    message=f"pragma suppressing {f.rule} has no reason — "
                            f"write `# analysis: allow({f.rule}) — <why>`",
                ))
            else:
                result.suppressed.append((f, reason))
        kept.sort(key=lambda f: (f.path, f.line, f.rule))
        new, matched, stale = apply_baseline(kept, baseline or [])
        result.findings = new
        result.baselined = matched
        result.stale_baseline = stale
        return result


def repo_root_of(anchor: str) -> str:
    """The repo root, assuming <root>/distar_tpu/analysis/core.py layout."""
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(anchor))))


def default_checkers(repo_root: str) -> List[Checker]:
    from .hygiene import HygieneChecker, MetricChecker
    from .jaxrules import JaxHazardChecker
    from .lifecycle import LifecycleChecker
    from .locks import LockChecker
    from .wire import WireChecker

    return [
        LockChecker(),
        LifecycleChecker(),
        WireChecker(),
        JaxHazardChecker(),
        HygieneChecker(),
        MetricChecker(repo_root),
    ]


# -------------------------------------------------------------------- baseline
def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", data) if isinstance(data, dict) else data
    assert isinstance(entries, list), f"baseline {path}: expected a list"
    return entries


def save_baseline(path: str, findings: Sequence[Finding], note: str = "") -> None:
    entries = [
        {"rule": f.rule, "path": f.path, "ident": f.ident}
        for f in sorted(findings, key=lambda f: (f.path, f.rule, f.ident))
    ]
    payload = {
        "note": note or (
            "Grandfathered findings. Shrink-only: entries that stop firing "
            "MUST be removed (tools/analyze.py exits 2 on stale entries)."
        ),
        "entries": entries,
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: Sequence[Finding], baseline: Sequence[dict]
                   ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Multiset-match findings against baseline entries on
    (rule, path, ident). Returns (new, baselined, stale_entries) — stale =
    baseline entries that matched nothing, which is an ERROR by contract:
    the baseline may only shrink, never silently hold dead debt."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in baseline:
        key = (e.get("rule", ""), e.get("path", ""), e.get("ident", e.get("message", "")))
        budget[key] = budget.get(key, 0) + 1
    new: List[Finding] = []
    matched: List[Finding] = []
    for f in findings:
        key = f.fingerprint()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            matched.append(f)
        else:
            new.append(f)
    stale = [
        {"rule": r, "path": p, "ident": i, "count": n}
        for (r, p, i), n in sorted(budget.items()) if n > 0
    ]
    return new, matched, stale


# --------------------------------------------------------------------- reports
def render_markdown(result: AnalysisResult, title: str = "analysis report") -> str:
    """Ranked markdown: findings by rule x severity (errors first, biggest
    families first), then the finding list, then baseline debt."""
    lines = [f"# {title}", ""]
    sev_rank = {"error": 0, "warning": 1}
    by_rule: Dict[Tuple[str, str], int] = {}
    for f in result.findings:
        by_rule[(f.rule, f.severity)] = by_rule.get((f.rule, f.severity), 0) + 1
    lines.append(
        f"files: {result.files} · new findings: {len(result.findings)} · "
        f"baselined debt: {len(result.baselined)} · "
        f"pragma-suppressed: {len(result.suppressed)} · "
        f"stale baseline entries: {len(result.stale_baseline)}"
    )
    lines.append("")
    if by_rule:
        lines += ["| rule | severity | count |", "|---|---|---|"]
        for (rule, sev), n in sorted(
            by_rule.items(), key=lambda kv: (sev_rank[kv[0][1]], -kv[1], kv[0][0])
        ):
            lines.append(f"| {rule} | {sev} | {n} |")
        lines.append("")
        for f in sorted(result.findings,
                        key=lambda f: (sev_rank[f.severity], f.path, f.line)):
            lines.append(f"- `{f.path}:{f.line}` **{f.rule}** ({f.severity}): {f.message}")
        lines.append("")
    if result.stale_baseline:
        lines.append("## stale baseline entries (remove them — shrink-only)")
        for e in result.stale_baseline:
            lines.append(f"- {e['path']}: {e['rule']}: {e['ident']} (x{e['count']})")
        lines.append("")
    if result.baselined:
        debt: Dict[str, int] = {}
        for f in result.baselined:
            debt[f.rule] = debt.get(f.rule, 0) + 1
        lines.append("## baselined debt by rule")
        for rule, n in sorted(debt.items(), key=lambda kv: -kv[1]):
            lines.append(f"- {rule}: {n}")
        lines.append("")
    if result.parse_errors:
        lines.append("## parse errors")
        lines += [f"- {e}" for e in result.parse_errors]
        lines.append("")
    verdict = {0: "CLEAN", 1: "BASELINED-ONLY", 2: "NEW FINDINGS"}[result.exit_code]
    lines.append(f"verdict: **{verdict}** (exit {result.exit_code})")
    return "\n".join(lines) + "\n"
