"""Lock-order sanitizer: the dynamic witness for the static lock rules.

Opt-in (``DISTAR_LOCKWATCH=1`` wires it into the test session via
tests/conftest.py): wraps ``threading.Lock``/``RLock`` construction so every
lock CREATED FROM distar_tpu code becomes a recording proxy, then watches

* the per-thread lock-order graph — an edge A→B is recorded whenever a
  thread acquires B while holding A (keyed by each lock's creation site);
  cycles in that graph are potential ABBA deadlocks even if the run never
  actually deadlocked — the dynamic analogue of the static
  ``lock-order-inversion`` rule;
* held-while-blocking — patched blocking primitives (``time.sleep``,
  ``Event.wait``, ``socket.recv/accept/connect/sendall``, ``select.select``)
  note every call made while the thread holds a watched lock — the dynamic
  analogue of ``lock-held-blocking``.

Locks created outside the filter (stdlib, jax, site-packages) get REAL lock
objects — zero overhead and no interference where we aren't looking.
``Condition`` integration is exact: the RLock proxy implements
``_acquire_restore``/``_release_save``/``_is_owned`` so ``cond.wait()``
correctly shows the lock as RELEASED while waiting.

Reports aggregate sites to file granularity for baseline stability
(tools/lockwatch_baseline.json: justified pairs only — the file may only
shrink, like the static baseline).
"""
from __future__ import annotations

import _thread
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["install", "uninstall", "reset", "report", "load_baseline",
           "unbaselined", "render_report", "installed"]

_state_lock = _thread.allocate_lock()  # raw: never recurses into proxies
_installed = False
_orig: Dict[str, object] = {}

#: path substrings a lock's creation site must match to be watched
_filters: Tuple[str, ...] = ("distar_tpu",)

# creation-site -> count of locks minted there
_created: Dict[str, int] = {}
# (site_a, site_b) -> count: thread acquired b while holding a
_edges: Dict[Tuple[str, str], int] = {}
# (held_site, blocker) -> [count, caller_site] — caller resolved only on
# the FIRST occurrence: the frame walk is far too expensive to run per
# recv chunk inside a client's request-lock hot loop
_blocking: Dict[Tuple[str, str], list] = {}

_tls = threading.local()


def _held() -> List["_LockProxy"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _site() -> Optional[str]:
    """file.py:lineno of the first frame outside threading/lockwatch."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not (fn.endswith("threading.py") or fn.endswith("lockwatch.py")):
            rel = fn
            for marker in ("/distar_tpu/", "/tests/", "/tools/"):
                i = fn.rfind(marker)
                if i >= 0:
                    rel = fn[i + 1:]
                    break
            return f"{rel}:{f.f_lineno}"
        f = f.f_back
    return None


def _watched_site() -> Optional[str]:
    site = _site()
    if site is None:
        return None
    if not any(flt in site for flt in _filters):
        return None
    return site


def _note_attempt(proxy: "_LockProxy") -> None:
    """Record order edges at acquisition ATTEMPT time: a genuine ABBA
    deadlock is exactly the case where the inner acquire never succeeds, so
    success-only recording would miss the one scenario that matters."""
    stack = _held()
    if stack:
        with _state_lock:
            for holder in stack:
                if holder.site != proxy.site:
                    key = (holder.site, proxy.site)
                    _edges[key] = _edges.get(key, 0) + 1


def _note_acquired(proxy: "_LockProxy") -> None:
    _held().append(proxy)


def _note_acquire(proxy: "_LockProxy") -> None:
    _note_attempt(proxy)
    _note_acquired(proxy)


def _note_release(proxy: "_LockProxy") -> None:
    stack = _held()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] is proxy:
            del stack[i]
            return


def _note_blocking(blocker: str) -> None:
    stack = _held()
    if not stack:
        return
    with _state_lock:
        fresh = [h.site for h in stack if (h.site, blocker) not in _blocking]
        for holder in stack:
            key = (holder.site, blocker)
            rec = _blocking.get(key)
            if rec is not None:
                rec[0] += 1
    if not fresh:
        return
    caller = _site() or "?"  # outside the state lock: the walk is slow
    with _state_lock:
        for site in fresh:
            key = (site, blocker)
            rec = _blocking.get(key)
            if rec is None:
                _blocking[key] = [1, caller]
            else:
                rec[0] += 1


class _LockProxy:
    """Recording wrapper around one real Lock."""

    _reentrant = False

    def __init__(self, inner, site: str):
        self._inner = inner
        self.site = site
        self._count = 0  # owner's recursion depth (RLock only)

    def acquire(self, blocking=True, timeout=-1):
        reentering = self._reentrant and self._owned()
        if blocking and not reentering:
            # edges record the INTENT to wait: try-locks (blocking=False)
            # are deadlock-free by construction and stay out of the graph
            _note_attempt(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            if reentering:
                self._count += 1
            else:
                self._count = 1
                _note_acquired(self)
        return got

    acquire_lock = acquire

    def release(self):
        if self._count <= 1:
            self._count = 0
            _note_release(self)
        else:
            self._count -= 1
        self._inner.release()

    release_lock = release

    def locked(self):
        return self._inner.locked()

    def _owned(self) -> bool:
        return any(p is self for p in _held())

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockwatch {type(self._inner).__name__} @ {self.site}>"


class _RLockProxy(_LockProxy):
    """RLock flavor: reentrancy + the Condition fast-path protocol."""

    _reentrant = True

    # threading.Condition prefers these when present; keeping our
    # bookkeeping inside them means cond.wait() shows the lock RELEASED
    # while waiting (no false held-while-blocking, no stale edges)
    def _release_save(self):
        state = self._inner._release_save()
        self._count = 0
        _note_release(self)
        return state

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        self._count = 1
        _note_acquire(self)

    def _is_owned(self):
        return self._inner._is_owned()


# ---------------------------------------------------------------- patching
def _make_lock_factory(real_factory, proxy_cls):
    def factory():
        inner = real_factory()
        site = _watched_site()
        if site is None:
            return inner  # outside the filter: zero overhead, zero risk
        with _state_lock:
            _created[site] = _created.get(site, 0) + 1
        return proxy_cls(inner, site)

    return factory


def _wrap_blocking(func, name):
    def wrapper(*args, **kwargs):
        _note_blocking(name)
        return func(*args, **kwargs)

    wrapper.__name__ = getattr(func, "__name__", name)
    wrapper._lockwatch_orig = func
    return wrapper


def install(filters: Tuple[str, ...] = ("distar_tpu",)) -> None:
    """Patch lock construction + blocking primitives. Idempotent."""
    global _installed, _filters
    import select
    import socket

    if _installed:
        return
    _filters = tuple(filters)
    _orig["Lock"] = threading.Lock
    _orig["RLock"] = threading.RLock
    threading.Lock = _make_lock_factory(_orig["Lock"], _LockProxy)
    threading.RLock = _make_lock_factory(_orig["RLock"], _RLockProxy)

    _orig["sleep"] = time.sleep
    time.sleep = _wrap_blocking(time.sleep, "time.sleep")
    _orig["Event.wait"] = threading.Event.wait

    def _event_wait(self, timeout=None, _orig_wait=_orig["Event.wait"]):
        # Thread.start() waits on the new thread's _started event — a
        # bounded in-process startup handshake, not the unbounded
        # peer-dependent wait this watch hunts; exempt exactly that caller
        caller = sys._getframe(1).f_code
        if not (caller.co_name == "start"
                and caller.co_filename.endswith("threading.py")):
            _note_blocking("Event.wait")
        return _orig_wait(self, timeout)

    threading.Event.wait = _event_wait
    _orig["select"] = select.select
    select.select = _wrap_blocking(select.select, "select.select")
    for meth in ("accept", "recv", "recv_into", "recvfrom", "sendall", "connect"):
        _orig[f"socket.{meth}"] = getattr(socket.socket, meth)
        setattr(socket.socket, meth,
                _wrap_blocking(getattr(socket.socket, meth), f"socket.{meth}"))
    _installed = True


def uninstall() -> None:
    global _installed
    import select
    import socket

    if not _installed:
        return
    threading.Lock = _orig["Lock"]
    threading.RLock = _orig["RLock"]
    time.sleep = _orig["sleep"]
    threading.Event.wait = _orig["Event.wait"]
    select.select = _orig["select"]
    for meth in ("accept", "recv", "recv_into", "recvfrom", "sendall", "connect"):
        setattr(socket.socket, meth, _orig[f"socket.{meth}"])
    _orig.clear()
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    with _state_lock:
        _created.clear()
        _edges.clear()
        _blocking.clear()


# ---------------------------------------------------------------- reporting
def _file_of(site: str) -> str:
    return site.rsplit(":", 1)[0]


def report() -> dict:
    """Aggregate the recorded graphs.

    ``inversions``: site pairs acquired in both orders (the actionable ABBA
    core; longer cycles reduce to at least one inverted pair across runs).
    ``cycles``: every cycle found by DFS over the site-level order graph.
    ``held_blocking``: blocking primitive calls under a held watched lock.
    """
    with _state_lock:
        edges = dict(_edges)
        blocking = dict(_blocking)
        created = dict(_created)

    inversions = []
    seen = set()
    for (a, b), n in edges.items():
        if (b, a) in edges and (b, a) not in seen and a != b:
            seen.add((a, b))
            inversions.append({
                "a": a, "b": b,
                "count_ab": n, "count_ba": edges[(b, a)],
            })

    # DFS cycle detection over the order graph (site granularity)
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    cycles: List[List[str]] = []
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []

    def dfs(n: str) -> None:
        color[n] = GRAY
        stack.append(n)
        for m in graph.get(n, ()):
            if color.get(m, WHITE) == GRAY:
                cycles.append(stack[stack.index(m):] + [m])
            elif color.get(m, WHITE) == WHITE:
                dfs(m)
        stack.pop()
        color[n] = BLACK

    for n in sorted(graph):
        if color.get(n, WHITE) == WHITE:
            dfs(n)

    held = [
        {"lock": lock, "blocker": blocker, "caller": rec[1], "count": rec[0]}
        for (lock, blocker), rec in sorted(blocking.items())
    ]
    return {
        "locks_watched": sum(created.values()),
        "lock_sites": len(created),
        "edges": len(edges),
        "inversions": sorted(inversions, key=lambda d: (d["a"], d["b"])),
        "cycles": cycles,
        "held_blocking": held,
    }


# ----------------------------------------------------------------- baseline
def load_baseline(path: str) -> dict:
    if not os.path.exists(path):
        return {"held_blocking": [], "inversions": []}
    with open(path) as f:
        data = json.load(f)
    data.setdefault("held_blocking", [])
    data.setdefault("inversions", [])
    return data


def unbaselined(rep: dict, baseline: dict) -> dict:
    """Pairs not covered by a justified baseline entry. Baseline matching is
    FILE-granular (line numbers drift): a held_blocking entry is
    {lock_file, blocker, why}; an inversion entry is {a_file, b_file, why}.
    Every entry must carry a non-empty ``why``."""
    hb_allowed = {
        (e.get("lock_file", ""), e.get("blocker", ""))
        for e in baseline["held_blocking"] if e.get("why")
    }
    inv_allowed = set()
    for e in baseline["inversions"]:
        if e.get("why"):
            inv_allowed.add((e.get("a_file", ""), e.get("b_file", "")))
            inv_allowed.add((e.get("b_file", ""), e.get("a_file", "")))
    bad_hb = [
        h for h in rep["held_blocking"]
        if (_file_of(h["lock"]), h["blocker"]) not in hb_allowed
    ]
    bad_inv = [
        i for i in rep["inversions"]
        if (_file_of(i["a"]), _file_of(i["b"])) not in inv_allowed
    ]
    # stale entries: baseline lines whose pair never fired (shrink-only)
    fired_hb = {(_file_of(h["lock"]), h["blocker"]) for h in rep["held_blocking"]}
    fired_inv = set()
    for i in rep["inversions"]:
        fired_inv.add((_file_of(i["a"]), _file_of(i["b"])))
        fired_inv.add((_file_of(i["b"]), _file_of(i["a"])))
    stale = [
        e for e in baseline["held_blocking"]
        if (e.get("lock_file", ""), e.get("blocker", "")) not in fired_hb
    ] + [
        e for e in baseline["inversions"]
        if (e.get("a_file", ""), e.get("b_file", "")) not in fired_inv
    ]
    return {"held_blocking": bad_hb, "inversions": bad_inv, "stale": stale}


def render_report(rep: dict, bad: Optional[dict] = None) -> str:
    lines = [
        f"lockwatch: {rep['locks_watched']} locks from {rep['lock_sites']} sites, "
        f"{rep['edges']} order edges, {len(rep['inversions'])} inversions, "
        f"{len(rep['held_blocking'])} held-while-blocking pairs",
    ]
    for i in rep["inversions"]:
        lines.append(
            f"  INVERSION {i['a']} <-> {i['b']} "
            f"(x{i['count_ab']}/x{i['count_ba']}) — potential ABBA deadlock")
    for h in rep["held_blocking"]:
        lines.append(
            f"  HELD-BLOCKING {h['blocker']} at {h['caller']} while holding "
            f"lock created {h['lock']} (x{h['count']})")
    if bad is not None:
        n = len(bad["held_blocking"]) + len(bad["inversions"])
        if n == 0 and not bad["stale"]:
            lines.append("  baseline: OK — every pair justified, nothing stale")
        else:
            lines.append(
                f"  baseline: {n} UNBASELINED pair(s), {len(bad['stale'])} "
                f"stale entr(ies) — fix the code or justify in "
                f"tools/lockwatch_baseline.json")
    return "\n".join(lines)
