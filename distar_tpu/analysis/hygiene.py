"""Hygiene checkers: the three legacy lints absorbed, plus metric-kind rules.

``HygieneChecker`` carries the no-print and socket-discipline rules exactly as
``tools/lint_no_print.py``/``tools/lint_sockets.py`` enforced them (those CLIs
are now thin shims over this module — one parse pass instead of three).

``MetricChecker`` carries the metric-name/documentation rules from
``tools/lint_metric_names.py`` and adds the v2 hygiene rules:

* ``metric-kind-misuse`` — ``.set()`` on a counter (counters are monotonic),
  a gauge/histogram named ``*_total`` (the suffix is the counter contract
  scrapers aggregate with ``rate()``), or a gauge that is only ever
  ``inc()``ed anywhere in the tree (it is a counter wearing the wrong type);
* ``metric-label-cardinality`` — a label value fed straight from request
  data (a subscript/``.get()``/f-string expression): labels are for BOUNDED
  dimensions; per-request values explode the series space until the
  registry/TSDB cap starves real series (docs/observability.md).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Checker, Finding, ParsedModule, call_name, dotted_name, is_library_path

METRIC_NAME_RE = re.compile(r"^distar_[a-z][a-z0-9_]*$")
REGISTER_METHODS = ("counter", "gauge", "histogram")

#: files allowed to register dynamically-built metric names, with every name
#: their dynamic path can produce (which must itself be documented). Keys are
#: posix paths relative to the distar_tpu package root (the shape the legacy
#: lint used).
DYNAMIC_ALLOW: Dict[str, List[str]] = {
    "utils/timing.py": ["distar_stopwatch_seconds"],
}

TIMEOUT_REQUIRED = ("urlopen", "create_connection")


def _pkg_relpath(relpath: str) -> Optional[str]:
    """Path relative to the distar_tpu package root, None when outside it."""
    parts = relpath.replace(os.sep, "/").split("/")
    if "distar_tpu" in parts:
        return "/".join(parts[parts.index("distar_tpu") + 1:])
    return None


class HygieneChecker(Checker):
    """no-print (library code only) + socket discipline (whole tree)."""

    name = "hygiene"
    rules = {
        "no-print": "error",
        "socket-bare-except": "error",
        "socket-no-timeout": "error",
    }

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        # relpath when scanning the repo; abspath covers package-rooted
        # scans (the legacy lint CLIs pass the distar_tpu dir itself)
        check_print = is_library_path(mod.relpath) or is_library_path(mod.abspath)
        for node in ast.walk(mod.tree):
            if (check_print and isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield self.finding(
                    "no-print", mod, node.lineno,
                    "bare print() in library code — route output through "
                    "TextLogger or the metrics registry "
                    "(docs/observability.md)",
                    ident="bare print",
                )
            elif isinstance(node, ast.ExceptHandler) and node.type is None:
                yield self.finding(
                    "socket-bare-except", mod, node.lineno,
                    "bare 'except:' — catch a typed error (resilience "
                    "taxonomy) or 'Exception'; bare swallows "
                    "KeyboardInterrupt/SystemExit",
                    ident="bare except",
                )
            elif isinstance(node, ast.Call) and call_name(node) in TIMEOUT_REQUIRED:
                if not any(kw.arg == "timeout" for kw in node.keywords):
                    yield self.finding(
                        "socket-no-timeout", mod, node.lineno,
                        f"{call_name(node)}() without an explicit timeout= — "
                        f"unbounded network wait (the week-long-run lesson "
                        f"behind the shuttle deadline fix)",
                        ident=f"{call_name(node)} no timeout",
                    )


class MetricChecker(Checker):
    """Metric naming/documentation + counter-vs-gauge + label cardinality."""

    name = "metrics"
    rules = {
        "metric-name": "error",
        "metric-undocumented": "error",
        "metric-dynamic-name": "error",
        "metric-kind-misuse": "error",
        "metric-label-cardinality": "warning",
    }

    def __init__(self, repo_root: str, docs_path: Optional[str] = None):
        self.repo_root = repo_root
        self.docs_path = docs_path or os.path.join(
            repo_root, "docs", "observability.md")
        self._documented: Optional[Set[str]] = None
        #: metric name -> set of ops observed anywhere in the tree, and one
        #: registration site per name (for the finalize-stage inc-only rule)
        self._gauge_ops: Dict[str, Set[str]] = {}
        self._gauge_sites: Dict[str, Tuple[ParsedModule, int]] = {}

    @property
    def documented(self) -> Set[str]:
        """Backticked ``distar_*`` names in docs/observability.md (table +
        prose both count — operators read the whole page)."""
        if self._documented is None:
            names: Set[str] = set()
            if os.path.exists(self.docs_path):
                with open(self.docs_path) as f:
                    text = f.read()
                for token in re.findall(r"`([^`\n]+)`", text):
                    m = re.match(r"(distar_[a-z0-9_]+)", token)
                    if m:
                        names.add(m.group(1))
            self._documented = names
        return self._documented

    # -------------------------------------------------------------- per-module
    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        pkg_rel = _pkg_relpath(mod.relpath)
        if pkg_rel is None:
            pkg_rel = _pkg_relpath(mod.abspath)
        if pkg_rel is None:
            return  # metric registration rules cover the package only
        # var (dotted) -> (kind, name) for instrument-variable tracking
        bound: Dict[str, Tuple[str, str]] = {}
        registrations: List[Tuple[ast.Call, str, Optional[str]]] = []  # (call, kind, name)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in REGISTER_METHODS and node.args:
                kind = node.func.attr
                first = node.args[0]
                name = first.value if (isinstance(first, ast.Constant)
                                       and isinstance(first.value, str)) else None
                registrations.append((node, kind, name))

        for call, kind, name in registrations:
            if name is None:
                allowed = DYNAMIC_ALLOW.get(pkg_rel)
                if allowed is None:
                    yield self.finding(
                        "metric-dynamic-name", mod, call.lineno,
                        "dynamically-named metric registration — declare its "
                        "names in distar_tpu/analysis/hygiene.py DYNAMIC_ALLOW",
                        ident="dynamic metric name",
                    )
                else:
                    for dyn in allowed:
                        if dyn not in self.documented:
                            yield self.finding(
                                "metric-undocumented", mod, call.lineno,
                                f"dynamic metric {dyn!r} missing from the "
                                f"docs/observability.md metric table",
                                ident=f"undocumented {dyn}",
                            )
                continue
            if not METRIC_NAME_RE.match(name):
                yield self.finding(
                    "metric-name", mod, call.lineno,
                    f"metric {name!r} violates the distar_<subsystem>_<name> "
                    f"convention",
                    ident=f"bad name {name}",
                )
            elif name not in self.documented:
                yield self.finding(
                    "metric-undocumented", mod, call.lineno,
                    f"metric {name!r} missing from the docs/observability.md "
                    f"metric table",
                    ident=f"undocumented {name}",
                )
            if kind in ("gauge", "histogram") and name.endswith("_total"):
                yield self.finding(
                    "metric-kind-misuse", mod, call.lineno,
                    f"{kind} named {name!r} — the _total suffix is the counter "
                    f"contract (scrapers rate() it); rename or make it a "
                    f"counter",
                    ident=f"_total {kind} {name}",
                )
            yield from self._check_labels(mod, call, name)
            if kind == "gauge":
                self._gauge_sites.setdefault(name, (mod, call.lineno))

        # instrument-variable op tracking (set on counter, inc-only gauges)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
                continue
            v = node.value
            if isinstance(v.func, ast.Attribute) and v.func.attr in REGISTER_METHODS \
                    and v.args and isinstance(v.args[0], ast.Constant) \
                    and isinstance(v.args[0].value, str):
                for tgt in node.targets:
                    d = dotted_name(tgt)
                    if d:
                        bound[d] = (v.func.attr, v.args[0].value)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            op = node.func.attr
            if op not in ("set", "inc", "dec", "observe"):
                continue
            target = node.func.value
            kind = name = None
            if isinstance(target, ast.Call) and isinstance(target.func, ast.Attribute) \
                    and target.func.attr in REGISTER_METHODS and target.args \
                    and isinstance(target.args[0], ast.Constant) \
                    and isinstance(target.args[0].value, str):
                kind, name = target.func.attr, target.args[0].value
            else:
                d = dotted_name(target)
                if d in bound:
                    kind, name = bound[d]
            if kind is None:
                continue
            if kind == "counter" and op in ("set", "dec"):
                yield self.finding(
                    "metric-kind-misuse", mod, node.lineno,
                    f".{op}() on counter {name!r} — counters are monotonic; "
                    f"use a gauge for values that move both ways",
                    ident=f"{op} on counter {name}",
                )
            if kind == "gauge":
                self._gauge_ops.setdefault(name, set()).add(op)
                self._gauge_sites.setdefault(name, (mod, node.lineno))

    def finalize(self) -> Iterable[Finding]:
        for name, ops in sorted(self._gauge_ops.items()):
            if ops == {"inc"}:
                mod, line = self._gauge_sites[name]
                yield self.finding(
                    "metric-kind-misuse", mod, line,
                    f"gauge {name!r} is only ever inc()ed across the tree — "
                    f"it is a counter wearing the wrong type (rate() queries "
                    f"and staleness handling differ); register it as a "
                    f"counter",
                    ident=f"inc-only gauge {name}",
                )
        self._gauge_ops = {}
        self._gauge_sites = {}

    # ----------------------------------------------------------------- labels
    def _check_labels(self, mod: ParsedModule, call: ast.Call, name: str
                      ) -> Iterable[Finding]:
        for kw in call.keywords:
            if kw.arg in (None, "help", "reservoir"):
                continue
            v = kw.value
            unbounded = (
                isinstance(v, ast.Subscript)
                or isinstance(v, ast.JoinedStr)
                or (isinstance(v, ast.Call) and call_name(v) in ("get", "format"))
            )
            if unbounded:
                yield self.finding(
                    "metric-label-cardinality", mod, v.lineno,
                    f"label {kw.arg}={ast.unparse(v)!r} on {name!r} is fed "
                    f"from request/payload data — label values must be "
                    f"BOUNDED (token, role, shard), or the series space "
                    f"grows until the registry/TSDB cap starves real series",
                    ident=f"label {kw.arg} on {name}",
                )
