"""Resource-lifecycle checker: everything opened must have a reachable close.

Encodes PR 11's ``SharedMemory`` teardown lesson (a segment or exported view
not released on every path BufferErrors the whole process at interpreter
shutdown) and the serve/replay ``stop()`` contract (PR 4: a stopped server
must actually release its listener and join its threads, or the next bind
fails and tests leak threads).

Rules:

* ``resource-unreleased`` — a ``self.X = socket/SharedMemory/open/Popen/...``
  attribute with NO release call (``close``/``unlink``/``shutdown``/...) on
  ``self.X`` anywhere in the class. Aliasing (``t = self.X``) and passing
  ``self.X`` to another call count as delegated cleanup — the rule targets
  resources that provably have no release path at all.
* ``thread-unjoined`` — a ``self.X = threading.Thread(...)`` attribute that is
  never ``join``ed: an error when the thread is non-daemon (it blocks
  interpreter exit), a finding even for daemon threads when the class has a
  ``stop``/``close``/``shutdown`` method (the class claims a lifecycle, so
  stop-then-return must not race the still-running thread).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Checker, Finding, ParsedModule, call_name, dotted_name

#: terminal constructor name -> (kind, release-verbs)
RESOURCE_CTORS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "socket": ("socket", ("close", "shutdown", "detach")),
    "create_connection": ("socket", ("close", "shutdown", "detach")),
    "SharedMemory": ("shared memory segment", ("close", "unlink")),
    "Popen": ("subprocess", ("wait", "terminate", "kill", "communicate")),
    "Timer": ("timer thread", ("cancel", "join")),
    "open": ("file handle", ("close",)),
}

_STOPPISH = {"stop", "close", "shutdown", "__exit__", "__del__", "stop_autosave", "drain"}


def _self_attr(expr: ast.AST) -> Optional[str]:
    if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


class LifecycleChecker(Checker):
    name = "lifecycle"
    rules = {
        "resource-unreleased": "error",
        "thread-unjoined": "warning",
    }

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(mod, node))
        return findings

    # ------------------------------------------------------------------ class
    def _check_class(self, mod: ParsedModule, cls: ast.ClassDef) -> Iterable[Finding]:
        # attr -> (kind, releases, line, is_thread, daemon)
        created: Dict[str, Tuple[str, Tuple[str, ...], int, bool, bool]] = {}
        for stmt in ast.walk(cls):
            if not isinstance(stmt, ast.Assign) or not isinstance(stmt.value, ast.Call):
                continue
            attr = next((a for t in stmt.targets if (a := _self_attr(t))), None)
            if attr is None:
                continue
            ctor = call_name(stmt.value)
            if ctor == "Thread":
                daemon = any(
                    kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in stmt.value.keywords
                )
                created[attr] = ("thread", ("join",), stmt.lineno, True, daemon)
            elif ctor in RESOURCE_CTORS:
                kind, rel = RESOURCE_CTORS[ctor]
                created[attr] = (kind, rel, stmt.lineno, False, False)
        if not created:
            return

        released: Set[str] = set()
        daemon_set: Set[str] = set()   # self.X.daemon = True after construction
        has_stop = any(
            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n.name in _STOPPISH
            for n in cls.body
        )
        for node in ast.walk(cls):
            if isinstance(node, ast.Call):
                # self.X.release()/join()/... — the direct path
                if isinstance(node.func, ast.Attribute):
                    attr = _self_attr(node.func.value)
                    if attr in created and node.func.attr in created[attr][1]:
                        released.add(attr)
                # delegated cleanup: self.X passed into any call
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    a = _self_attr(arg)
                    if a in created:
                        released.add(a)
                # getattr(self, "X") is how optional-attr teardown reads it
                if (call_name(node) == "getattr" and len(node.args) >= 2
                        and isinstance(node.args[1], ast.Constant)
                        and node.args[1].value in created):
                    released.add(node.args[1].value)
            # aliased cleanup: t = self.X — including the tuple-swap idiom
            # `sock, self._sock = self._sock, None` (assume aliases close)
            elif isinstance(node, ast.Assign):
                for sub in ast.walk(node.value):
                    a = _self_attr(sub)
                    if a in created:
                        released.add(a)
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute) and tgt.attr == "daemon"
                            and (sa := _self_attr(tgt.value)) is not None):
                        daemon_set.add(sa)
            # with self.X: — context-managed release
            elif isinstance(node, ast.With):
                for item in node.items:
                    a = _self_attr(item.context_expr)
                    if a in created:
                        released.add(a)

        for attr, (kind, rel, line, is_thread, daemon) in sorted(created.items()):
            if attr in released:
                continue
            daemon = daemon or attr in daemon_set
            if is_thread:
                if daemon and not has_stop:
                    continue  # fire-and-forget daemon helper: acceptable
                sev = "warning" if daemon else "error"
                why = (
                    "stop() returns while the thread may still run"
                    if daemon else
                    "a non-daemon thread with no join path blocks interpreter exit"
                )
                yield self.finding(
                    "thread-unjoined", mod, line,
                    f"{cls.name}.{attr} thread is never joined — {why}",
                    ident=f"{cls.name}.{attr} unjoined", severity=sev,
                )
            else:
                yield self.finding(
                    "resource-unreleased", mod, line,
                    f"{cls.name}.{attr} ({kind}) has no reachable release — "
                    f"call {'/'.join(rel)} in stop()/__exit__/finally "
                    f"(leaked handles strand peers and fail re-binds)",
                    ident=f"{cls.name}.{attr} unreleased",
                )
