"""Project-invariant static analyzer.

One parsed-module cache, per-checker AST visitors, a committed baseline of
grandfathered findings, and JSON + ranked-markdown reports — the mechanical
enforcement of the invariants this codebase learned the hard way (callbacks
dispatched outside locks, shm views released on every path, host numpy never
donated into jitted steps, typed wire errors instead of bare excepts). See
docs/analysis.md for the rule catalog and the incident each rule encodes.

Driver: ``python tools/analyze.py`` (tier-1 runs it via
tests/test_analysis.py::test_analysis_repo_clean). The dynamic witness for
the lock rules is ``analysis/lockwatch.py`` (``DISTAR_LOCKWATCH=1``).
"""
from .core import (  # noqa: F401
    Analyzer,
    AnalysisResult,
    Checker,
    Finding,
    ParsedModule,
    apply_baseline,
    collect_files,
    default_checkers,
    load_baseline,
    render_markdown,
    save_baseline,
)
