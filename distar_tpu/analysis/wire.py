"""Wire-error taxonomy checker: typed errors on every wire, always registered.

Encodes the serve/replay error contract (PR 2/PR 5): every failure a peer can
see crosses the wire as ``{"code": <registered>, ...}`` so clients dispatch on
the taxonomy instead of string-matching reprs, and PR 4's retry fabric: a
``RetryableError`` silently swallowed (no counter, no log, no re-raise) is an
outage you can never see.

Rules:

* ``wire-code-unregistered`` — an ``errors.py`` class defines ``code = "x"``
  but is absent from that module's ``_WIRE_CODES`` registry (and is never
  special-cased by ``.code`` reference), so ``error_from_wire`` can only
  rehydrate it as the degraded base class.
* ``wire-code-unknown`` — a string literal used as a wire error code (in a
  ``{"code": "x", ...}`` reply or a ``payload["code"] == "x"`` dispatch) that
  no errors-registry module registers.
* ``handler-boundary-swallow`` — an ``except Exception`` at a frontend
  handler boundary (do_GET/do_POST/_handle*) whose body neither answers the
  peer nor re-raises (pass-only / bare-raise-only): the connection dies or
  the bug disappears, both worse than a typed reply.
* ``retryable-swallowed`` — a handler catching the retryable taxonomy
  (CommError/RetryableError/RateLimitTimeout/...) and dropping it without a
  counter/log/re-raise.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Checker, Finding, ParsedModule, call_name, dotted_name, walk_scope

HANDLER_RE = re.compile(r"^(do_[A-Z]+|handle(_.*)?|_handle(_.*)?|_serve_conn.*|_conn_loop)$")

RETRYABLE_NAMES = {
    "RetryableError", "CommError", "RateLimitTimeout", "CircuitOpenError",
    "ShmError", "ShmPeerDeadError", "ShedError",
}

#: codes that are HTTP-ish plumbing, not taxonomy members
_IGNORED_CODES: Set[str] = set()

_LOGGING_CALLS = {
    "inc", "observe", "set", "record", "add_event", "warning", "error",
    "exception", "info", "debug", "log", "write", "append", "put", "emit",
}


def _handler_name(fn: ast.AST) -> bool:
    return isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) and bool(
        HANDLER_RE.match(fn.name)
    )


def _exc_names(type_node: Optional[ast.AST]) -> Set[str]:
    if type_node is None:
        return set()
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    out = set()
    for n in nodes:
        d = dotted_name(n)
        if d:
            out.add(d.rsplit(".", 1)[-1])
    return out


class WireChecker(Checker):
    name = "wire"
    rules = {
        "wire-code-unregistered": "error",
        "wire-code-unknown": "error",
        "handler-boundary-swallow": "error",
        "retryable-swallowed": "warning",
    }

    def __init__(self):
        #: code literal -> defining module (from every errors.py scanned)
        self._registered_codes: Dict[str, str] = {}
        #: deferred literal-usage sites, resolved once all registries are read
        self._code_uses: List[Tuple[ParsedModule, int, str, str]] = []

    # ---------------------------------------------------------------- per-file
    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        if mod.relpath.endswith("errors.py"):
            findings.extend(self._check_registry(mod))
        self._collect_code_uses(mod)
        findings.extend(self._check_handlers(mod))
        return findings

    # ------------------------------------------------------- errors.py registry
    def _check_registry(self, mod: ParsedModule) -> Iterable[Finding]:
        coded: Dict[str, Tuple[str, int]] = {}  # class -> (code, line)
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id == "code"
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)):
                    coded[node.name] = (stmt.value.value, stmt.lineno)
        if not coded:
            return
        registered: Set[str] = set()
        referenced: Set[str] = set()  # special-cased via ClassName.code
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "_WIRE_CODES"
                            for t in node.targets)):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name):
                        registered.add(sub.id)
            elif (isinstance(node, ast.Attribute) and node.attr == "code"
                    and isinstance(node.value, ast.Name)):
                referenced.add(node.value.id)
        for cls_name, (code, line) in sorted(coded.items()):
            self._registered_codes.setdefault(code, mod.relpath)
            if cls_name not in registered and cls_name not in referenced:
                yield self.finding(
                    "wire-code-unregistered", mod, line,
                    f"{cls_name} defines wire code {code!r} but is not in this "
                    f"module's _WIRE_CODES registry — error_from_wire() will "
                    f"degrade it to the base class on every peer",
                    ident=f"{cls_name} code {code}",
                )

    # ------------------------------------------------------ code-literal usage
    def _collect_code_uses(self, mod: ParsedModule) -> None:
        if mod.relpath.endswith("errors.py"):
            return  # registries define codes; usage rules apply elsewhere
        for node in ast.walk(mod.tree):
            # {"code": "literal", ...} replies
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant) and k.value == "code"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        self._code_uses.append(
                            (mod, v.lineno, v.value, "wire reply built with"))
            # payload["code"] == "literal" / payload.get("code") == "literal"
            elif isinstance(node, ast.Compare) and len(node.comparators) == 1:
                sides = [node.left, node.comparators[0]]
                lit = next((s.value for s in sides
                            if isinstance(s, ast.Constant)
                            and isinstance(s.value, str)), None)
                other = next((s for s in sides if not isinstance(s, ast.Constant)), None)
                if lit is None or other is None:
                    continue
                is_code_lookup = (
                    (isinstance(other, ast.Subscript)
                     and isinstance(other.slice, ast.Constant)
                     and other.slice.value == "code")
                    or (isinstance(other, ast.Call) and call_name(other) == "get"
                        and other.args
                        and isinstance(other.args[0], ast.Constant)
                        and other.args[0].value == "code")
                )
                if is_code_lookup:
                    self._code_uses.append((mod, node.lineno, lit, "dispatched on"))

    def finalize(self) -> Iterable[Finding]:
        known = set(self._registered_codes) | _IGNORED_CODES
        seen: Set[Tuple[str, int, str]] = set()
        for mod, line, code, how in self._code_uses:
            key = (mod.relpath, line, code)
            if key in seen or code in known:
                continue
            seen.add(key)
            yield self.finding(
                "wire-code-unknown", mod, line,
                f"wire error code {how} unregistered literal {code!r} — "
                f"register a typed class in the plane's errors.py so "
                f"error_from_wire() can rehydrate it",
                ident=f"unknown code {code}",
            )
        self._code_uses = []

    # -------------------------------------------------------- handler boundary
    def _check_handlers(self, mod: ParsedModule) -> Iterable[Finding]:
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            is_handler = _handler_name(fn)
            for node in walk_scope(fn, skip_nested_defs=True):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                names = _exc_names(node.type)
                body = node.body
                only_pass = all(isinstance(s, ast.Pass) for s in body)
                only_bare_raise = (
                    len(body) == 1 and isinstance(body[0], ast.Raise)
                    and body[0].exc is None
                )
                if is_handler and "Exception" in names and (only_pass or only_bare_raise):
                    what = "swallows it silently" if only_pass else "re-raises it bare"
                    yield self.finding(
                        "handler-boundary-swallow", mod, node.lineno,
                        f"frontend handler {fn.name}() catches Exception and "
                        f"{what} — answer the peer a typed wire error "
                        f"(see serve/errors.py) instead",
                        ident=f"{fn.name} broad except",
                    )
                    continue
                # teardown paths (close/stop/__exit__/__del__) legitimately
                # swallow typed errors: the resource may already be gone
                teardown = fn.name in ("close", "stop", "__exit__", "__del__",
                                       "shutdown", "unlink")
                if not teardown and names & RETRYABLE_NAMES and self._swallows(node):
                    dropped = "/".join(sorted(names & RETRYABLE_NAMES))
                    yield self.finding(
                        "retryable-swallowed", mod, node.lineno,
                        f"{dropped} caught and dropped with no counter, log or "
                        f"re-raise — a retryable failure that leaves no trace "
                        f"is an invisible outage; count it "
                        f"(registry.counter(...).inc()) or let it propagate",
                        ident=f"swallowed {dropped}",
                    )

    @staticmethod
    def _swallows(handler: ast.ExceptHandler) -> bool:
        """True when the except body leaves no trace AT ALL: no raise, no
        return (exiting the loop/thread is a reaction), and no call of any
        kind except a bare sleep — a fallback helper, a counter inc, a log
        line all count as handling. The rule targets ``except CommError:
        pass``-shaped drops, not every terse handler."""
        for node in walk_scope(handler, skip_nested_defs=True):
            if isinstance(node, (ast.Raise, ast.Return)):
                return False
            if isinstance(node, ast.Call) and call_name(node) != "sleep":
                return False
        return True
