"""JAX hazard checker: donation, device sync in loops, nondeterminism in jit.

Encodes three incidents:

* PR 2's heap corruption — donating a buffer that numpy's allocator owns
  ("corrupted double-linked list" aborts): a jit built with
  ``donate_argnums`` must never be fed host ``np.*`` arrays directly; restored
  state routes through ``_place_state``/``assemble_global``/``device_put``
  first — rule ``jax-donated-host-leaf``;
* PR 8's decollate regression — one ``jax.device_get`` per leaf per loop
  iteration serializes a device sync per element; fetch the whole pytree once
  outside the loop and hand out views — rule ``jax-device-get-in-loop``;
* trace-time nondeterminism — ``time.time()``/``random.*`` inside a jitted
  function or a ``pure_callback`` body bakes one trace-time value into the
  compiled program (or breaks cache keys) — rule ``jax-nondeterministic-jit``.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from .core import Checker, Finding, ParsedModule, call_name, dotted_name, walk_scope

NP_CTORS = {
    "asarray", "array", "zeros", "ones", "empty", "full", "stack",
    "concatenate", "copy", "frombuffer", "ascontiguousarray",
}
_NP_MODULES = {"np", "numpy"}

#: dotted prefixes that launder a host array into an XLA-owned buffer
PLACEMENT_CALLS = {"_place_state", "assemble_global", "device_put"}

NONDET_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.monotonic_ns", "datetime.now", "datetime.utcnow", "uuid.uuid4",
    "random.random", "random.randint", "random.choice", "random.uniform",
}
_NONDET_PREFIXES = ("np.random.", "numpy.random.")


def _is_np_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in NP_CTORS:
        root = dotted_name(func.value)
        return root.split(".", 1)[0] in _NP_MODULES
    return False


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The jit-constructing Call inside ``node``, unwrapping partial(...)."""
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if name == "jit":
        return node
    if name == "partial":
        for arg in node.args:
            if isinstance(arg, (ast.Attribute, ast.Name)) and \
                    dotted_name(arg).rsplit(".", 1)[-1] == "jit":
                return node
        for arg in node.args:
            inner = _jit_call(arg)
            if inner is not None:
                return inner
    return None


def _is_donated_jit(node: ast.AST) -> bool:
    call = _jit_call(node)
    return call is not None and any(
        kw.arg in ("donate_argnums", "donate_argnames") for kw in call.keywords
    )


class JaxHazardChecker(Checker):
    name = "jax"
    rules = {
        "jax-donated-host-leaf": "error",
        "jax-device-get-in-loop": "warning",
        "jax-nondeterministic-jit": "error",
    }

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        findings: List[Finding] = []
        donated: Set[str] = set()      # names/attrs bound to donated jits
        jitted_fns: List[ast.AST] = []  # function defs that trace under jit

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and _is_donated_jit(node.value):
                for tgt in node.targets:
                    d = dotted_name(tgt)
                    if d:
                        donated.add(d)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(
                    dotted_name(dec).rsplit(".", 1)[-1] == "jit"
                    or _jit_call(dec) is not None
                    for dec in node.decorator_list
                ):
                    jitted_fns.append(node)
            elif isinstance(node, ast.Call) and call_name(node) == "pure_callback":
                # jax.pure_callback(fn, ...) executes fn at trace/runtime on
                # host — its body must still be deterministic per input
                if node.args and isinstance(node.args[0], ast.Name):
                    target = node.args[0].id
                    for fn in ast.walk(mod.tree):
                        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                                and fn.name == target:
                            jitted_fns.append(fn)

        findings.extend(self._check_donated_calls(mod, donated))
        findings.extend(self._check_device_get_loops(mod))
        for fn in jitted_fns:
            findings.extend(self._check_nondet(mod, fn))
        return findings

    # ------------------------------------------------------------- donation
    def _check_donated_calls(self, mod: ParsedModule, donated: Set[str]
                             ) -> Iterable[Finding]:
        if not donated:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee not in donated:
                continue
            fn = mod.enclosing_function(node)
            np_locals = self._np_locals(fn) if fn is not None else set()
            for arg in node.args:
                hazard = None
                if _is_np_ctor(arg):
                    hazard = f"{dotted_name(arg.func)}(...) result"
                elif isinstance(arg, ast.Name) and arg.id in np_locals:
                    hazard = f"host array {arg.id!r}"
                if hazard:
                    yield self.finding(
                        "jax-donated-host-leaf", mod, node.lineno,
                        f"{callee} was built with donate_argnums and is called "
                        f"with {hazard} — donating a numpy-owned buffer is "
                        f"heap corruption (PR 2); route it through "
                        f"_place_state/assemble_global/device_put first",
                        ident=f"donated call {callee} host arg",
                    )

    @staticmethod
    def _np_locals(fn: ast.AST) -> Set[str]:
        """Names assigned from np constructors in this function, minus names
        later laundered through a placement call."""
        hosts: Set[str] = set()
        assigns = [n for n in walk_scope(fn, skip_nested_defs=True)
                   if isinstance(n, ast.Assign)]
        # source order matters: `x = np.zeros(...)` then `x = device_put(x)`
        # launders x — processing out of order would re-taint it
        for node in sorted(assigns, key=lambda n: n.lineno):
            if _is_np_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        hosts.add(tgt.id)
            elif isinstance(node.value, ast.Call) and \
                    call_name(node.value) in PLACEMENT_CALLS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        hosts.discard(tgt.id)
        return hosts

    # -------------------------------------------------------- device_get loops
    def _check_device_get_loops(self, mod: ParsedModule) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and call_name(node) == "device_get"):
                continue
            # nearest loop ancestor, unless a function boundary intervenes
            # (a closure called from a loop is the call site's problem)
            in_loop = False
            for a in mod.ancestors(node):
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    break
                if isinstance(a, (ast.For, ast.While)):
                    in_loop = True
                    break
            if in_loop:
                yield self.finding(
                        "jax-device-get-in-loop", mod, node.lineno,
                        "jax.device_get inside a loop — one device sync per "
                        "iteration (PR 8's per-leaf regression); fetch the "
                        "whole pytree once outside the loop and slice views",
                        ident="device_get in loop",
                    )

    # --------------------------------------------------------- nondeterminism
    def _check_nondet(self, mod: ParsedModule, fn: ast.AST) -> Iterable[Finding]:
        for node in walk_scope(fn, skip_nested_defs=False):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted in NONDET_CALLS or dotted.startswith(_NONDET_PREFIXES):
                yield self.finding(
                    "jax-nondeterministic-jit", mod, node.lineno,
                    f"{dotted}() inside a jitted/pure_callback body "
                    f"({fn.name}) — the value is baked in at trace time, not "
                    f"evaluated per step; pass it in as an argument or use "
                    f"jax.random with explicit keys",
                    ident=f"nondet {dotted} in {fn.name}",
                )
