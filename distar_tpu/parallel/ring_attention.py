"""Ring attention: exact attention over a sequence sharded across the mesh's
``sp`` axis.

Long-context design (SURVEY.md §5 "Long-context / sequence parallelism"):
the reference never shards sequence (its temporal context is an LSTM and its
set attention tops out at 512 entities), but this framework treats context
parallelism as first-class — the mesh declares an ``sp`` axis and this op
makes attention over sequences far beyond one chip's HBM exact and
communication-efficient.

Algorithm (Liu et al., Ring Attention, 2023): each device holds a query
shard and a K/V shard. Over ``sp_size`` steps, every device attends its
queries against the resident K/V block while the K/V blocks rotate one hop
around the ring (`jax.lax.ppermute` over ICI); a running online-softmax
(max/denominator carried per row, flash-attention style) makes the result
exactly softmax over the full sequence. Compute and the ppermute overlap
naturally under XLA's async collective scheduling.

Use inside shard_map with the sequence dim sharded over 'sp':
    out = shard_map(partial(ring_attention, axis_name="sp", axis_size=S),
                    mesh, in_specs=..., out_specs=...)(q, k, v, mask)
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e9


def ring_attention(
    q: jnp.ndarray,  # [B, H, Nq_local, D]
    k: jnp.ndarray,  # [B, H, Nk_local, D]
    v: jnp.ndarray,  # [B, H, Nk_local, D]
    mask: Optional[jnp.ndarray] = None,  # [B, Nk_local] key validity
    *,
    axis_name: str = "sp",
    axis_size: int,
) -> jnp.ndarray:
    """Per-shard body (call under shard_map)."""
    B, H, Nq, D = q.shape
    scale = 1.0 / (D ** 0.5)
    if mask is None:
        mask = jnp.ones(k.shape[:1] + k.shape[2:3], bool)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, _):
        k_blk, v_blk, m_blk, acc, denom, row_max = carry
        score = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        score = jnp.where(m_blk[:, None, None, :], score, NEG_INF)
        blk_max = score.max(axis=-1)  # [B, H, Nq]
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(score - new_max[..., None])
        acc = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        denom = denom * correction + p.sum(axis=-1)
        # rotate the K/V/mask block one hop around the ring
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        m_blk = jax.lax.ppermute(m_blk, axis_name, perm)
        return (k_blk, v_blk, m_blk, acc, denom, new_max), None

    # accumulators derive from q so shard_map marks them sp-varying (a bare
    # jnp.zeros would be typed replicated and fail the scan carry check)
    zero_rows = q[..., 0] * 0.0  # [B, H, Nq]
    init = (
        k,
        v,
        mask,
        q * 0.0,
        zero_rows,
        zero_rows + NEG_INF,
    )
    (k, v, mask, acc, denom, _), _ = jax.lax.scan(step, init, None, length=axis_size)
    return acc / jnp.maximum(denom, 1e-20)[..., None]


def ring_self_attention(
    q: jnp.ndarray,  # [B, H, N, D] global
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray],  # [B, N]
    mesh: Mesh,
) -> jnp.ndarray:
    """Convenience wrapper: shard the sequence over the mesh's sp axis and
    run ring attention; output sharded like q."""
    try:  # top-level export landed in newer jax; this image predates it
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    sp = mesh.shape["sp"]
    assert q.shape[2] % sp == 0, f"sequence {q.shape[2]} not divisible by sp={sp}"
    spec_qkv = P(None, None, "sp", None)
    spec_mask = P(None, "sp")
    fn = shard_map(
        functools.partial(ring_attention, axis_name="sp", axis_size=sp),
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_mask),
        out_specs=spec_qkv,
    )
    if mask is None:
        mask = jnp.ones((q.shape[0], q.shape[2]), bool)
    return fn(q, k, v, mask)
