"""Sharded batch feeding: host batches -> global device arrays on the mesh.

Role of the reference's rank-local DataLoader + async CUDA copy (reference:
distar/agent/default/rl_training/rl_dataloader.py:45-167 — each NCCL rank
pulls its own batch and copies it to its own GPU): under GSPMD there is ONE
logical batch sharded over the mesh, so the feeder owns the two halves of
that contract:

* ``assemble_global`` — turn a process-local host array into a global
  ``jax.Array`` with the requested ``NamedSharding``. Single-process runs
  (one host owns every mesh device) take the ``device_put`` fast path — the
  runtime slices the batch onto the addressable devices and streams H2D
  asynchronously. Multi-process runs (a pod: each host's dataloader pulled
  only its own batch shard) go through
  ``jax.make_array_from_process_local_data``, which assembles the global
  array from per-host locals without ever materialising the full batch on
  any single host.

* ``ShardFeeder`` — the double-buffer: a background thread pulls the next
  host batch from the dataloader (collate happens there), places it via
  ``place_fn`` (the learner's sharding-aware placement), and banks up to
  ``depth`` placed batches. Because ``device_put``/``make_array...`` are
  asynchronous, the H2D transfer of batch N+1 overlaps the device step of
  batch N; the learner's ``next()`` only waits when the host side cannot
  keep up — and that wait is the headline starvation metric
  (``distar_feeder_wait_seconds``; the smoke contract is feeder wait <
  device step time).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding

from ..obs import get_registry
from .mesh import MeshConfigError

_SENTINEL = object()


def assemble_global(x, sharding: NamedSharding):
    """Host array -> global device array under ``sharding``.

    Raises ``MeshConfigError`` when a sharded dimension doesn't divide its
    mesh extent — at the call site with shapes in the message, instead of an
    opaque XLA error from inside the jitted step.
    """
    x = np.asarray(x) if not hasattr(x, "dtype") else x
    _check_divisible(x, sharding)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    # pod path: ``x`` is this host's batch shard; every process contributes
    # its local rows and jax glues them into one global Array
    return jax.make_array_from_process_local_data(sharding, np.asarray(x))


def _check_divisible(x, sharding) -> None:
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None:
        return
    shape = getattr(x, "shape", ())
    for dim, names in enumerate(spec):
        if names is None or dim >= len(shape):
            continue
        names = names if isinstance(names, tuple) else (names,)
        extent = 1
        for n in names:
            extent *= mesh.shape[n]
        if extent > 1 and shape[dim] % extent:
            raise MeshConfigError(
                f"array dim {dim} of size {shape[dim]} does not divide the "
                f"mesh axes {names} (extent {extent}); global shape {shape} "
                f"cannot shard as {spec}"
            )


class ShardFeeder:
    """Wraps a host-batch iterator; yields placed (sharded) batches.

    Supersedes ``learner.prefetch.DevicePrefetcher`` on the learner path:
    same double-buffer semantics (bounded queue, error propagation through
    ``__next__``, sentinel shutdown) plus the mesh-aware placement contract
    and the ``distar_feeder_*`` instrumentation. ``place_fn`` receives the
    raw host batch and returns the device-placed batch — for learners that
    is ``_place_batch`` (entity cap + per-leaf ``assemble_global``).
    """

    def __init__(self, dataloader, place_fn: Callable, depth: int = 2,
                 token: str = "feeder"):
        if depth < 1:
            raise ValueError(f"ShardFeeder depth must be >= 1, got {depth}")
        self._it = iter(dataloader)
        self._place = place_fn
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._depth = depth
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        # host-side running totals for cheap in-process assertions/reports
        # (the registry histograms carry the cross-process view)
        self.batches = 0
        self.total_wait_s = 0.0
        self.total_place_s = 0.0
        reg = get_registry()
        self._m_batches = reg.counter(
            "distar_feeder_batches_total",
            "host batches placed onto the mesh", token=token,
        )
        self._m_wait = reg.histogram(
            "distar_feeder_wait_seconds",
            "consumer-side starvation: wall-clock next() blocked on the feeder",
            token=token,
        )
        self._m_place = reg.histogram(
            "distar_feeder_place_seconds",
            "host pull + collate + device placement time per batch",
            token=token,
        )
        self._m_occ = reg.gauge(
            "distar_feeder_occupancy",
            "placed-batch share of the double buffer (0..1)", token=token,
        )
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="shard-feeder"
        )
        self._thread.start()

    # ------------------------------------------------------------- producer
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                t0 = time.monotonic()
                try:
                    batch = next(self._it)
                except StopIteration:
                    return
                placed = self._place(batch)
                dt = time.monotonic() - t0
                self.total_place_s += dt
                self._m_place.observe(dt)
                while not self._stop.is_set():
                    try:
                        self._q.put(placed, timeout=0.2)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on the consumer side
            self._err = e
        finally:
            self._q.put(_SENTINEL)

    # ------------------------------------------------------------- consumer
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        t0 = time.monotonic()
        item = self._q.get()
        waited = time.monotonic() - t0
        if item is _SENTINEL:
            if self._err is not None:
                raise self._err
            raise StopIteration
        self.batches += 1
        self.total_wait_s += waited
        self._m_batches.inc()
        self._m_wait.observe(waited)
        self._m_occ.set(self._q.qsize() / self._depth)
        return item

    def occupancy(self) -> float:
        return self._q.qsize() / self._depth

    def stats(self) -> dict:
        """Host-side totals for smoke assertions (the prefetch-overlap
        contract: mean wait << mean step time when the host keeps up)."""
        n = max(self.batches, 1)
        return {
            "batches": self.batches,
            "wait_s_mean": self.total_wait_s / n,
            "place_s_mean": self.total_place_s / n,
            "wait_s_total": self.total_wait_s,
        }

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        # reap the producer: close() returning while it may still be mid
        # pull/collate/place races learner teardown (it would touch freed
        # device state); the drain above unblocked any pending put. Short
        # bound: a producer blocked in next(self._it) can't be interrupted
        # — waiting longer buys nothing (it dies with the process as before)
        self._thread.join(timeout=0.5)
