from .mesh import (
    MeshConfigError,
    MeshSpec,
    make_mesh,
    batch_sharding,
    check_batch_divisible,
    replicated_sharding,
    param_sharding,
    fsdp_param_sharding,
    set_context_mesh,
    get_context_mesh,
)
from .ring_attention import ring_attention, ring_self_attention
from .grad_clip import GradClipConfig, build_grad_clip
from .optimizer import build_optimizer
from .feeder import ShardFeeder, assemble_global

__all__ = [
    "MeshConfigError",
    "MeshSpec",
    "make_mesh",
    "batch_sharding",
    "check_batch_divisible",
    "replicated_sharding",
    "param_sharding",
    "fsdp_param_sharding",
    "set_context_mesh",
    "get_context_mesh",
    "GradClipConfig",
    "build_grad_clip",
    "build_optimizer",
    "ring_attention",
    "ring_self_attention",
    "ShardFeeder",
    "assemble_global",
]
