"""Gradient clipping zoo as optax transforms.

Role of the reference's clip zoo (reference: distar/ctools/torch_utils/
grad_clip.py): 'norm' (global L2 clip), 'value', 'max_norm' (clip against an
EMA of recent grad norms x threshold — the reference's adaptive mode), and
'momentum_norm' (per-parameter norm clip against an EMA of per-param norms).
Each returns an optax GradientTransformation so they chain with the
optimizer; the observed pre-clip global norm is exposed in the state for
logging (the reference logs `gradient` per iter).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


@dataclasses.dataclass(frozen=True)
class GradClipConfig:
    type: str = "none"  # none | value | norm | max_norm | momentum_norm
    threshold: float = 1.0
    norm_type: int = 2
    momentum: float = 0.999
    begin_step: int = 100  # steps before the EMA is trusted (max_norm)


class _EMAState(NamedTuple):
    ema: jnp.ndarray
    step: jnp.ndarray
    last_norm: jnp.ndarray


def _global_norm(updates):
    return optax.global_norm(updates)


def build_grad_clip(cfg: GradClipConfig) -> optax.GradientTransformation:
    if cfg.type in (None, "none"):
        return optax.identity()
    if cfg.type == "value":
        return optax.clip(cfg.threshold)
    if cfg.type == "norm":
        return optax.clip_by_global_norm(cfg.threshold)

    if cfg.type == "max_norm":
        # clip to min(threshold * ema_norm, hard threshold during warmup)
        def init(params):
            del params
            return _EMAState(jnp.zeros(()), jnp.zeros((), jnp.int32), jnp.zeros(()))

        def update(updates, state, params=None):
            del params
            norm = _global_norm(updates)
            warm = state.step < cfg.begin_step
            ema = jnp.where(
                state.step == 0, norm, cfg.momentum * state.ema + (1 - cfg.momentum) * norm
            )
            limit = jnp.where(warm, cfg.threshold, cfg.threshold * ema)
            scale = jnp.minimum(1.0, limit / (norm + 1e-6))
            updates = jax.tree.map(lambda g: g * scale, updates)
            return updates, _EMAState(ema, state.step + 1, norm)

        return optax.GradientTransformation(init, update)

    if cfg.type == "momentum_norm":
        # per-parameter EMA of norms; clip each param's grad to ema * threshold
        def init(params):
            zeros = jax.tree.map(lambda p: jnp.zeros(()), params)
            return _EMAState(zeros, jnp.zeros((), jnp.int32), jnp.zeros(()))

        def update(updates, state, params=None):
            del params
            norms = jax.tree.map(lambda g: jnp.sqrt(jnp.sum(g * g)), updates)
            ema = jax.tree.map(
                lambda e, n: jnp.where(state.step == 0, n, cfg.momentum * e + (1 - cfg.momentum) * n),
                state.ema,
                norms,
            )
            def clip_one(g, n, e):
                limit = jnp.where(state.step < cfg.begin_step, cfg.threshold, cfg.threshold * e)
                return g * jnp.minimum(1.0, limit / (n + 1e-6))

            updates = jax.tree.map(clip_one, updates, norms, ema)
            return updates, _EMAState(ema, state.step + 1, _global_norm(updates))

        return optax.GradientTransformation(init, update)

    raise NotImplementedError(cfg.type)


def clip_activation(grads, global_norm, clip_type: str, threshold: float):
    """In-jit clip-activation stats for the training-dynamics tree
    (obs/dynamics.py): how much of the gradient signal the configured clip
    removed this step.

    Returns ``(fraction, active)`` as f32 scalars:

      * ``norm``  — fraction of the global L2 norm removed,
        ``max(0, 1 - threshold/||g||)``; active when ``||g|| > threshold``;
      * ``value`` — fraction of gradient *elements* with ``|g| > threshold``
        (each is individually clamped); active when any element clipped;
      * ``none``  — zeros (nothing to clip).

    The EMA modes (``max_norm``/``momentum_norm``) keep their limit inside
    the optimizer state, which the diagnostics tree cannot see without
    threading opt_state through — they report zeros rather than a guess.
    """
    f32 = jnp.float32
    if clip_type == "norm":
        frac = jnp.maximum(
            0.0, 1.0 - threshold / jnp.maximum(global_norm, 1e-12)
        ).astype(f32)
        return frac, (global_norm > threshold).astype(f32)
    if clip_type == "value":
        clipped = total = jnp.zeros((), f32)
        for leaf in jax.tree_util.tree_leaves(grads):
            leaf = leaf.astype(f32)
            clipped = clipped + jnp.sum(jnp.abs(leaf) > threshold).astype(f32)
            total = total + float(leaf.size)
        frac = clipped / jnp.maximum(total, 1.0)
        return frac, (clipped > 0).astype(f32)
    return jnp.zeros((), f32), jnp.zeros((), f32)


def leaf_norms(tree, prefix: str):
    """Per-parameter L2 norms keyed by pytree path.

    Role of the reference's ``save_grad`` per-parameter grad/param-norm TB
    dumps (reference: distar/agent/default/rl_learner.py:35-47,118-130):
    computed inside the jitted step (a handful of scalar reductions is
    noise next to the model matmuls) and folded into the step's info dict,
    so the existing one-batched-D2H log path ships them.
    """
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )
        out[f"{prefix}/{name}"] = jnp.sqrt(
            jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        )
    return out
