"""Executed multi-chip sharded training: mesh spec in, trained steps out.

This is the subsystem entry the rest of the repo drives:

* ``__graft_entry__.dryrun_multichip`` is a thin wrapper over
  ``run_sharded_training`` (the "dryrun" IS the production path now — same
  learner, same feeder, same shardings);
* ``bench.py``'s MULTICHIP case calls it at dp=1/2/4 for the
  scaling-efficiency report;
* ``tools/chaos.py multichip-drill`` runs it as kill/resume children with
  sharded checkpoints across DIFFERENT mesh shapes;
* ``tests/test_parallel_exec.py`` runs it as the tier-1 smoke.

``force_host_devices`` is the one place that knows how to stand up the
virtual n-device CPU platform on this image (the sitecustomize pins the
axon TPU tunnel via jax.config at interpreter start, so env vars alone are
too late — see tests/conftest.py).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional, Union

from .mesh import MeshSpec

# tiny flagship-shaped model: compiles in seconds on CPU, exercises every
# head/encoder the full model has (same shape tests/conftest.py exports)
SMOKE_MODEL = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}


def force_host_devices(n_devices: int, cache_base: Optional[str] = None) -> None:
    """Pin a virtual n-device CPU platform BEFORE any jax backend init.

    Must run before the first device query in the process. Raises when the
    backend was already initialised with fewer devices (the caller forked
    too late)."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags
        )
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    if cache_base:
        from ..utils.compile_cache import configure as _configure_cache

        _configure_cache(jax, cache_base)
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"virtual CPU mesh has {len(jax.devices())} devices, need "
            f"{n_devices}; the jax backend was initialised before "
            "force_host_devices ran"
        )


def run_sharded_training(
    mesh_spec: Union[str, MeshSpec],
    *,
    iters: int = 2,
    batch_size: Optional[int] = None,
    unroll_len: int = 2,
    model_cfg: Optional[dict] = None,
    experiment_name: str = "sharded_executor",
    save_dir: str = "",
    sharded_ckpt: bool = True,
    save_freq: int = 10 ** 9,
    resume: bool = False,
    kill_after_iter: Optional[int] = None,
    assert_fsdp: bool = False,
    assert_tp: bool = False,
    max_devices: Optional[int] = None,
) -> Dict[str, Any]:
    """Build a live mesh from ``mesh_spec``, train an RLLearner on it with
    the full executed path (GSPMD jitted step, ShardFeeder double-buffered
    feeding, sharded checkpoints), and return a structural report.

    ``resume`` restores from the save_dir's durable latest pointer first —
    across DIFFERENT mesh shapes (the resharding restore). ``kill_after_iter``
    is the chaos hook: after that iteration's hooks ran, force a durable
    sharded save and ``os._exit(137)`` — the parent supervises the restart.
    """
    import jax

    from ..learner import RLLearner
    from .mesh import make_mesh

    spec = MeshSpec.parse(mesh_spec) if not isinstance(mesh_spec, MeshSpec) else mesh_spec
    if max_devices is None and spec.dp != -1:
        # explicit spec: claim exactly the devices it names ("dp=2" on an
        # 8-device host is a 2-chip mesh, not a config error)
        max_devices = spec.dp * spec.fsdp * spec.tp * spec.sp
    devices = jax.devices()[:max_devices] if max_devices else None
    mesh = make_mesh(spec, devices)
    n_dp = mesh.shape["dp"] * mesh.shape["fsdp"]
    B = batch_size if batch_size is not None else max(n_dp, 2)
    cfg = {
        "common": {"experiment_name": experiment_name,
                   **({"save_path": save_dir} if save_dir else {})},
        "learner": {
            "batch_size": B,
            "unroll_len": unroll_len,
            "save_freq": save_freq,
            "log_freq": 10 ** 9,
            "sharded_ckpt": sharded_ckpt,
        },
        "model": model_cfg if model_cfg is not None else SMOKE_MODEL,
    }
    learner = RLLearner(cfg, mesh=mesh)

    report: Dict[str, Any] = {
        "mesh": dict(learner.mesh.shape),
        "batch_size": B,
        "unroll_len": unroll_len,
        "devices": len(jax.devices()),
        "sharded_ckpt": sharded_ckpt,
        "resumed_from": None,
        "start_iter": 0,
    }
    if assert_fsdp:
        specs = [str(x.sharding.spec) for x in jax.tree.leaves(learner.state["params"])]
        if not any("fsdp" in s for s in specs):
            raise AssertionError("no param leaf sharded over fsdp")
    if assert_tp:
        flat = jax.tree_util.tree_flatten_with_path(learner.state["params"])[0]
        tp_leaves = [
            "/".join(getattr(p, "key", str(p)) for p in path)
            for path, x in flat
            if "tp" in str(x.sharding.spec)
        ]
        if not tp_leaves:
            raise AssertionError("no param leaf sharded over tp")
        if not any("Attention" in p for p in tp_leaves):
            raise AssertionError(
                f"no attention weight sharded over tp (tp leaves: {tp_leaves[:5]})"
            )
        report["tp_leaves"] = len(tp_leaves)

    if resume:
        resumed = learner.resume_latest()
        report["resumed_from"] = resumed
        report["start_iter"] = learner.last_iter.val

    # per-iteration device step wall time, measured around the learner's
    # _train itself (the run loop's log_buffer is drained by the log hook
    # before any later hook could read it)
    step_times = []
    orig_train = learner._train

    def timed_train(data):
        t0 = time.monotonic()
        out = orig_train(data)  # blocks on the device step's D2H log fetch
        step_times.append(time.monotonic() - t0)
        return out

    learner._train = timed_train

    if kill_after_iter is not None:
        from ..learner.hooks import LambdaHook

        def _chaos_kill(lrn):
            if lrn.last_iter.val >= kill_after_iter:
                # the chaos moment: durable sharded save, then die like a
                # preempted pod worker (no teardown, no atexit)
                lrn.save(lrn.checkpoint_path(), sync=True)
                os._exit(137)

        learner.hooks.add(LambdaHook("executor_chaos_kill", "after_iter", _chaos_kill))

    t0 = time.monotonic()
    learner.run(max_iterations=iters)
    wall_s = time.monotonic() - t0

    feeder = learner._dataloader
    feeder_stats = feeder.stats() if hasattr(feeder, "stats") else {}
    try:
        loss = float(learner.variable_record.get("total_loss").val)
    except KeyError:  # resumed at/past the target: zero fresh iterations
        loss = None
    report.update(
        iters=learner.last_iter.val,
        loss=loss,
        wall_s=round(wall_s, 3),
        step_times_s=[round(t, 4) for t in step_times],
        # steady-state step time: drop the first measured iter (it eats the
        # compile) when there is anything after it
        step_time_s=(
            round(min(step_times[1:] or step_times), 4) if step_times else None
        ),
        feeder=feeder_stats,
    )
    if save_freq < 10 ** 9 or kill_after_iter is not None:
        report["checkpoint_dir"] = os.path.join(learner.save_dir, "checkpoints")
    return report


def main_cli(argv=None) -> int:
    """``python -m distar_tpu.parallel.executor --mesh dp=4,fsdp=2 ...`` —
    the child-process surface the chaos multichip drill and bench MULTICHIP
    case drive. Prints one ``REPORT {json}`` line."""
    import argparse
    import json

    p = argparse.ArgumentParser()
    p.add_argument("--mesh", default="dp=-1")
    p.add_argument("--iters", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--unroll-len", type=int, default=2)
    p.add_argument("--host-devices", type=int, default=0,
                   help="force a virtual n-device CPU platform (0 = use "
                        "the real backend)")
    p.add_argument("--save-dir", default="")
    p.add_argument("--save-freq", type=int, default=10 ** 9)
    p.add_argument("--no-sharded-ckpt", dest="sharded_ckpt",
                   action="store_false")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--kill-after", type=int, default=None)
    p.add_argument("--experiment-name", default="sharded_executor")
    args = p.parse_args(argv)
    if args.host_devices:
        force_host_devices(args.host_devices,
                           cache_base="/tmp/jax_cache_distar_tpu")
    report = run_sharded_training(
        args.mesh,
        iters=args.iters,
        batch_size=args.batch_size,
        unroll_len=args.unroll_len,
        experiment_name=args.experiment_name,
        save_dir=args.save_dir,
        sharded_ckpt=args.sharded_ckpt,
        save_freq=args.save_freq,
        resume=args.resume,
        kill_after_iter=args.kill_after,
    )
    print("REPORT " + json.dumps(report), flush=True)  # lint: allow-print (CLI surface)
    return 0


if __name__ == "__main__":
    raise SystemExit(main_cli())
