"""Multi-host runtime initialisation.

Role of the reference dist_init (reference: distar/ctools/utils/
dist_helper.py:321-344 — NCCL process-group setup with SLURM / single-node /
torch env discovery): on TPU pods the analogue is jax.distributed.initialize,
after which every host sees the global device set and pjit programs run SPMD
with gradient collectives over ICI/DCN scheduled by XLA. Env discovery covers
SLURM (SLURM_PROCID/SLURM_NTASKS, dist_helper.py:329-334), TPU-VM metadata
(jax's own autodetection), and explicit addresses.
"""
from __future__ import annotations

import os
from typing import Optional


def dist_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    method: str = "auto",  # auto | slurm | single_node | explicit
) -> dict:
    """Initialise the multi-host jax runtime; returns rank/world_size info.

    single_node is a no-op (one process owns all local devices). On Cloud
    TPU VMs 'auto' lets jax autodetect the pod topology from metadata.
    """
    import jax

    if method == "single_node":
        return {"rank": 0, "world_size": 1}
    if method == "slurm" or (method == "auto" and "SLURM_PROCID" in os.environ):
        process_id = int(os.environ["SLURM_PROCID"])
        num_processes = int(os.environ["SLURM_NTASKS"])
        if coordinator_address is None:
            nodelist = os.environ.get("SLURM_STEP_NODELIST", "localhost")
            head = nodelist.split(",")[0].split("[")[0]
            coordinator_address = f"{head}:12355"
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif method == "explicit":
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    else:  # auto on TPU VMs: jax reads the pod metadata itself
        try:
            jax.distributed.initialize()
        except Exception:
            return {"rank": 0, "world_size": 1}
    return {"rank": jax.process_index(), "world_size": jax.process_count()}
