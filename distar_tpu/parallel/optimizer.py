"""Optimizer factory.

The reference RL learner uses plain Adam with betas=(0, 0.99), eps=1e-5
(reference: distar/agent/default/rl_learner.py:73-79) plus an external grad
clip; its SL learner uses adam/adamw with in-optimizer clipping modes
(reference: distar/ctools/torch_utils/optimizer_util.py:44-110). Here both
are one optax chain: clip transform -> adam/adamw -> lr schedule.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import optax

from .grad_clip import GradClipConfig, build_grad_clip


def build_optimizer(
    learning_rate: float = 1e-5,
    betas: Tuple[float, float] = (0.0, 0.99),
    eps: float = 1e-5,
    weight_decay: float = 0.0,
    clip: Optional[GradClipConfig] = None,
    warmup_steps: int = 0,
    decay_boundaries: Sequence[int] = (),
    decay_rate: float = 1.0,
) -> optax.GradientTransformation:
    if decay_boundaries:
        schedule = optax.piecewise_constant_schedule(
            learning_rate, {int(b): decay_rate for b in decay_boundaries}
        )
    else:
        schedule = learning_rate
    if warmup_steps > 0:
        base = schedule if callable(schedule) else (lambda _: learning_rate)
        schedule = optax.join_schedules(
            [optax.linear_schedule(0.0, learning_rate, warmup_steps), base], [warmup_steps]
        )
    if weight_decay > 0.0:
        opt = optax.adamw(schedule, b1=betas[0], b2=betas[1], eps=eps, weight_decay=weight_decay)
    else:
        opt = optax.adam(schedule, b1=betas[0], b2=betas[1], eps=eps)
    return optax.chain(build_grad_clip(clip or GradClipConfig()), opt)
