"""Distributed (sharded) checkpoints with restore-time resharding.

Role of the reference's rank-0 ``torch.save`` (reference: distar/ctools/
torch_utils/checkpoint_helper.py:125-140 — the whole replicated model
funnels through one process): under fsdp/tp the parameters are 1/N-sized
per device and gathering them to one host defeats the sharding. Here a
checkpoint is a DIRECTORY:

    <path>/
      sharding.json        layout manifest (written LAST: its presence
                           implies every blob below it landed)
      skeleton.msgpack     the state pytree with array leaves replaced by
                           shard references (+ all non-array leaves)
      leaf00042.o0_128.shard   one self-CRC'd blob per parameter shard

Each shard blob carries a 16-byte header (magic, crc32, payload size) so
every host can write its own shards without a cross-host CRC exchange; the
manifest lists the GLOBAL shard layout (derived deterministically from the
saved array's sharding), so verification and restore know exactly which
files must exist. In a multi-process run every host writes only the shards
it owns with ``replica_id == 0`` (no duplicate replicated bytes) and
process 0 writes the manifest + skeleton.

Restore-time resharding: ``restore_sharded`` reassembles host-global arrays
from the shard blobs — the mesh the checkpoint was SAVED on is irrelevant
to the result, so a ``dp=4,fsdp=2`` checkpoint restores bit-identically
onto ``dp=8``, a single serve/eval chip, or any other layout; the caller
(``BaseLearner._place_state``) re-pins the host arrays onto ITS compiled
shardings through the donation-safe jitted materialization.

Composes with PR 4's durability layer: ``utils.checkpoint.verify_checkpoint``
and ``load_checkpoint`` route directories with a ``sharding.json`` here, so
the ``CheckpointManager`` generation pointer, corrupt-generation fallback
and ``verify=True`` contract apply unchanged — a single bit-flipped shard
fails the whole generation typed (``CheckpointCorruptError``) and resume
falls back to the previous one.
"""
from __future__ import annotations

import json
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..utils import storage
from ..utils.checkpoint import CheckpointCorruptError, _to_serialisable, _partial_restore

try:
    from flax import serialization
except Exception:  # pragma: no cover
    serialization = None

MANIFEST = "sharding.json"
SKELETON = "skeleton.msgpack"
_SHARD_MAGIC = b"DTSH"
_HEADER = struct.Struct("<4sIQ")  # magic, crc32, payload bytes
_REF_KEY = "__shard_ref__"


def _join(path: str, name: str) -> str:
    return path.rstrip("/") + "/" + name


def manifest_path(path: str) -> str:
    return _join(path, MANIFEST)


def is_sharded_checkpoint(path: str) -> bool:
    try:
        return storage.exists(manifest_path(path))
    except (OSError, ValueError):
        return False


# ------------------------------------------------------------------ snapshot

def _offsets(index: Tuple[slice, ...], shape: Tuple[int, ...]) -> List[int]:
    starts = []
    for dim, sl in enumerate(index):
        starts.append(0 if sl.start is None else int(sl.start))
    # scalars / fully-replicated: index can be shorter than shape
    starts += [0] * (len(shape) - len(starts))
    return starts


def _shard_layout(arr) -> List[Dict]:
    """The GLOBAL shard layout of ``arr``: one entry per distinct global
    index (replicas collapse). Deterministic across hosts — every process
    derives the same layout from the sharding, so the manifest written by
    process 0 names exactly the files the other hosts write."""
    shape = tuple(arr.shape)
    sharding = getattr(arr, "sharding", None)
    if sharding is None:  # plain host array: one shard covers everything
        return [{"offsets": [0] * len(shape), "shape": list(shape)}]
    seen = {}
    for _dev, index in sharding.devices_indices_map(shape).items():
        starts = tuple(_offsets(index, shape))
        if starts in seen:
            continue
        sub_shape = []
        for dim in range(len(shape)):
            sl = index[dim] if dim < len(index) else slice(None)
            start = 0 if sl.start is None else int(sl.start)
            stop = shape[dim] if sl.stop is None else int(sl.stop)
            sub_shape.append(stop - start)
        seen[starts] = {"offsets": list(starts), "shape": sub_shape}
    return [seen[k] for k in sorted(seen)]


def snapshot_sharded(state: Any) -> Dict:
    """Device->host copy of every shard this process must write, plus the
    skeleton/layout. This is the only part of a save that must complete
    before donated buffers are reused — call it synchronously; the byte
    writing can ride a background thread.

    ``np.asarray(shard.data)`` is copied via ``np.array``: a snapshot that
    aliases a donated device buffer is corrupted by the next train step
    (same hazard utils.checkpoint._host_snapshot documents)."""
    leaves_meta: Dict[str, Dict] = {}
    local_blobs: Dict[str, np.ndarray] = {}
    counter = [0]

    def visit(x):
        if not hasattr(x, "shape"):
            return x  # scalars/strings stay in the skeleton
        leaf_id = f"leaf{counter[0]:05d}"
        counter[0] += 1
        arr = x
        shape = tuple(arr.shape)
        dtype = np.dtype(getattr(arr, "dtype", np.asarray(arr).dtype))
        layout = _shard_layout(arr)
        shards = []
        for entry in layout:
            fname = f"{leaf_id}.o{'_'.join(str(o) for o in entry['offsets'])}.shard"
            shards.append({**entry, "file": fname})
        leaves_meta[leaf_id] = {
            "shape": list(shape),
            # dtype.name, not .str: extension dtypes (bfloat16) stringify as
            # opaque '<V2' via .str and would not round-trip through np.dtype
            "dtype": dtype.name,
            "spec": str(getattr(getattr(arr, "sharding", None), "spec", "")),
            "shards": shards,
        }
        if hasattr(arr, "addressable_shards"):
            for s in arr.addressable_shards:
                if s.replica_id != 0:
                    continue  # another device/host owns this copy
                starts = _offsets(s.index, shape)
                fname = f"{leaf_id}.o{'_'.join(str(o) for o in starts)}.shard"
                local_blobs[fname] = np.array(s.data)
        else:
            fname = shards[0]["file"]
            local_blobs[fname] = np.array(arr)
        return {_REF_KEY: leaf_id}

    skeleton = jax.tree.map(visit, state)
    return {
        "skeleton": skeleton,
        "leaves": leaves_meta,
        "blobs": local_blobs,
        "process_index": jax.process_index(),
        "mesh_shape": _state_mesh_shape(state),
    }


def _state_mesh_shape(state) -> Optional[Dict[str, int]]:
    """The mesh the state is resident on (from the leaves' own shardings;
    falls back to the context mesh for host-only trees)."""
    for leaf in jax.tree.leaves(state):
        mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        if mesh is not None and hasattr(mesh, "shape"):
            return dict(mesh.shape)
    from .mesh import get_context_mesh

    mesh = get_context_mesh()
    return dict(mesh.shape) if mesh is not None else None


# --------------------------------------------------------------------- write

def _pack_blob(data: np.ndarray) -> bytes:
    payload = np.ascontiguousarray(data).tobytes()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return _HEADER.pack(_SHARD_MAGIC, crc, len(payload)) + payload


def _unpack_blob(path: str, blob: bytes) -> bytes:
    if len(blob) < _HEADER.size:
        raise CheckpointCorruptError(f"{path}: shard blob shorter than header")
    magic, crc, size = _HEADER.unpack_from(blob)
    payload = blob[_HEADER.size:]
    if magic != _SHARD_MAGIC:
        raise CheckpointCorruptError(f"{path}: bad shard magic {magic!r}")
    if len(payload) != size:
        raise CheckpointCorruptError(
            f"{path}: shard payload {len(payload)} B != header {size} B "
            "(truncated write?)"
        )
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise CheckpointCorruptError(f"{path}: shard crc mismatch (bit rot?)")
    return payload


def write_sharded(path: str, snap: Dict, metadata: Optional[Dict] = None) -> str:
    """Write a ``snapshot_sharded`` result as a sharded checkpoint directory.
    Blob writes ride utils/storage (atomic tmp+fsync+rename locally); the
    manifest goes LAST so its presence implies a complete checkpoint."""
    from ..obs import get_registry

    reg = get_registry()
    writes = reg.counter(
        "distar_ckpt_shard_writes_total", "parameter-shard blobs written"
    )
    shard_bytes = reg.counter(
        "distar_ckpt_shard_bytes_total", "bytes written as shard blobs"
    )
    for fname, data in snap["blobs"].items():
        blob = _pack_blob(data)
        storage.write_bytes(_join(path, fname), blob)
        writes.inc()
        shard_bytes.inc(len(blob))
    if snap.get("process_index", 0) == 0:
        skel_blob = serialization.msgpack_serialize(
            _to_serialisable(snap["skeleton"])
        )
        storage.write_bytes(_join(path, SKELETON), skel_blob)
        manifest = {
            "format": "distar-sharded-v1",
            "metadata": metadata or {},
            "mesh_shape": snap.get("mesh_shape"),
            "skeleton": {
                "file": SKELETON,
                "crc32": zlib.crc32(skel_blob) & 0xFFFFFFFF,
                "size": len(skel_blob),
            },
            "leaves": snap["leaves"],
            "ts": time.time(),
        }
        storage.write_bytes(
            manifest_path(path), json.dumps(manifest, indent=1).encode()
        )
    return path


def save_sharded(path: str, state: Any, metadata: Optional[Dict] = None) -> str:
    """Synchronous sharded save (snapshot + write in one call)."""
    return write_sharded(path, snapshot_sharded(state), metadata)


# -------------------------------------------------------------------- verify

def _read_manifest(path: str) -> Dict:
    try:
        return json.loads(storage.read_bytes(manifest_path(path)))
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable shard manifest: {e!r}") from e


def verify_sharded(path: str) -> None:
    """Raise ``CheckpointCorruptError`` unless every shard blob named by the
    manifest exists and passes its self-CRC. One flipped bit in one shard
    fails the whole generation — the manager then falls back."""
    manifest = _read_manifest(path)
    skel = manifest.get("skeleton", {})
    skel_blob = storage.read_bytes(_join(path, skel.get("file", SKELETON)))
    if len(skel_blob) != int(skel.get("size", -1)) or (
        zlib.crc32(skel_blob) & 0xFFFFFFFF
    ) != int(skel.get("crc32", -1)):
        raise CheckpointCorruptError(f"{path}: skeleton blob fails manifest CRC")
    for leaf_id, meta in manifest.get("leaves", {}).items():
        for shard in meta["shards"]:
            fpath = _join(path, shard["file"])
            try:
                blob = storage.read_bytes(fpath)
            except OSError as e:
                raise CheckpointCorruptError(
                    f"{fpath}: missing shard blob: {e!r}"
                ) from e
            try:
                _unpack_blob(fpath, blob)
            except CheckpointCorruptError:
                from ..obs import get_registry

                get_registry().counter(
                    "distar_ckpt_shard_corrupt_total",
                    "shard blobs failing CRC/size verification",
                ).inc()
                raise


# ------------------------------------------------------------------- restore

def _assemble_leaf(path: str, meta: Dict) -> np.ndarray:
    shape = tuple(meta["shape"])
    dtype = np.dtype(meta["dtype"])
    out = np.empty(shape, dtype)
    for shard in meta["shards"]:
        fpath = _join(path, shard["file"])
        try:
            blob = storage.read_bytes(fpath)
        except OSError as e:
            raise CheckpointCorruptError(f"{fpath}: missing shard blob: {e!r}") from e
        payload = _unpack_blob(fpath, blob)
        sub_shape = tuple(shard["shape"])
        expect = int(np.prod(sub_shape, dtype=np.int64)) * dtype.itemsize
        if len(payload) != expect:
            raise CheckpointCorruptError(
                f"{fpath}: shard payload {len(payload)} B != "
                f"{expect} B implied by shape {sub_shape} {dtype}"
            )
        data = np.frombuffer(payload, dtype).reshape(sub_shape)
        index = tuple(
            slice(o, o + s) for o, s in zip(shard["offsets"], sub_shape)
        )
        if shape == ():
            out = data.reshape(())
        else:
            out[index] = data
    return out


def _resolve_refs(node, path: str, leaves: Dict[str, Dict], cache: Dict):
    if isinstance(node, dict):
        if set(node.keys()) == {_REF_KEY}:
            leaf_id = node[_REF_KEY]
            if leaf_id not in cache:
                if leaf_id not in leaves:
                    raise CheckpointCorruptError(
                        f"{path}: skeleton references unknown leaf {leaf_id}"
                    )
                cache[leaf_id] = _assemble_leaf(path, leaves[leaf_id])
            return cache[leaf_id]
        return {k: _resolve_refs(v, path, leaves, cache) for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        return type(node)(_resolve_refs(v, path, leaves, cache) for v in node)
    return node


def restore_sharded(path: str, target: Any = None, verify: bool = True) -> Dict:
    """Load a sharded checkpoint into host-global numpy arrays.

    Mesh-agnostic by construction: the shard layout in the manifest fully
    describes each global array, so restore works on any device topology —
    including none at all (serve/eval on one chip). Returns
    ``{"state", "metadata", "sharding_layout"}``; with ``target`` the state
    is overlaid onto the target structure (partial-match, same semantics as
    ``utils.checkpoint.load_checkpoint``)."""
    manifest = _read_manifest(path)
    if verify:
        verify_sharded(path)
    skel_blob = storage.read_bytes(
        _join(path, manifest.get("skeleton", {}).get("file", SKELETON))
    )
    try:
        skeleton = serialization.msgpack_restore(skel_blob)
    except Exception as e:
        raise CheckpointCorruptError(f"{path}: undecodable skeleton: {e!r}") from e
    state = _resolve_refs(skeleton, path, manifest.get("leaves", {}), {})
    if target is not None:
        state = _partial_restore(target, state)
    return {
        "state": state,
        "metadata": manifest.get("metadata", {}),
        "sharding_layout": {
            "mesh_shape": manifest.get("mesh_shape"),
            "leaves": {
                k: {"spec": v.get("spec", ""), "shards": len(v["shards"])}
                for k, v in manifest.get("leaves", {}).items()
            },
        },
    }


def saved_mesh_shape(path: str) -> Optional[Dict[str, int]]:
    """The mesh the checkpoint was written under (None for pre-mesh saves).
    Restoring onto a different shape is the resharding path — counted by
    the caller via ``distar_ckpt_reshards_total``."""
    try:
        return _read_manifest(path).get("mesh_shape")
    except CheckpointCorruptError:
        return None
