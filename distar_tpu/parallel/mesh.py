"""Device-mesh abstraction for the learner.

Role of the reference's NCCL data-parallel plumbing (reference:
distar/ctools/utils/dist_helper.py:321-439 — manual per-param allreduce
`DistModule.sync_gradients`): here data parallelism is one axis of a general
`jax.sharding.Mesh`, the gradient allreduce is an XLA-scheduled psum over ICI
inserted by the partitioner, and rank-0-only logic maps to
`jax.process_index() == 0`.

The mesh is declared with up to four logical axes — dp (data), fsdp
(parameter shard), tp (tensor), sp (sequence/context) — so wider shardings
(tensor-parallel heads, ring-attention over a long time axis) slot in without
touching the learner. The reference model (~50-100M params) only needs dp;
the other axes default to size 1 but stay first-class in every pjit spec.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "tp", "sp")


class MeshConfigError(ValueError):
    """A mesh/batch configuration cannot be realised on the available
    devices (axes don't factor the device count, or a batch doesn't divide
    the data-parallel extent). Raised at config/compile time with the
    offending numbers, instead of surfacing later as an opaque XLA
    sharding error."""

# The mesh model-internal sharded ops (ring attention over sp) resolve at
# trace time. Modules can't take a Mesh constructor arg without threading it
# through every config layer, so the learner declares it here before tracing.
_CONTEXT_MESH: Optional[Mesh] = None


def set_context_mesh(mesh: Optional[Mesh]) -> None:
    global _CONTEXT_MESH
    _CONTEXT_MESH = mesh


def get_context_mesh() -> Optional[Mesh]:
    return _CONTEXT_MESH


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    dp: int = -1  # -1: all remaining devices
    fsdp: int = 1
    tp: int = 1
    sp: int = 1

    def sizes(self, n_devices: int) -> Sequence[int]:
        fixed = self.fsdp * self.tp * self.sp
        if fixed <= 0 or (self.dp != -1 and self.dp <= 0):
            raise MeshConfigError(
                f"mesh axes must be positive (got dp={self.dp}, "
                f"fsdp={self.fsdp}, tp={self.tp}, sp={self.sp})"
            )
        dp = self.dp if self.dp != -1 else n_devices // fixed
        if dp * fixed != n_devices:
            raise MeshConfigError(
                f"mesh dp={dp} x fsdp={self.fsdp} x tp={self.tp} x "
                f"sp={self.sp} = {dp * fixed} does not factor the "
                f"{n_devices} available devices; adjust the axis sizes "
                f"(--mesh dp=K,fsdp=M,tp=N,sp=S must multiply to "
                f"{n_devices}, or leave dp unset to absorb the remainder)"
            )
        return (dp, self.fsdp, self.tp, self.sp)

    @classmethod
    def parse(cls, spec: str) -> "MeshSpec":
        """CLI surface: ``"dp=4,fsdp=2,tp=1"`` -> MeshSpec. Unlisted axes
        default (dp=-1 absorbs the remaining devices). Typed errors on
        unknown axes / non-integer sizes."""
        if isinstance(spec, cls):
            return spec
        kwargs = {}
        for part in filter(None, (p.strip() for p in str(spec).split(","))):
            axis, _, value = part.partition("=")
            axis = axis.strip()
            if axis not in AXES:
                raise MeshConfigError(
                    f"unknown mesh axis {axis!r} in --mesh {spec!r} "
                    f"(axes: {', '.join(AXES)})"
                )
            try:
                kwargs[axis] = int(value)
            except ValueError:
                raise MeshConfigError(
                    f"mesh axis {axis} needs an integer size, got {value!r} "
                    f"(--mesh {spec!r})"
                ) from None
        return cls(**kwargs)


def make_mesh(spec: MeshSpec = MeshSpec(), devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.sizes(len(devices))
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, AXES)


def dp_axes(mesh: Mesh):
    """The mesh axes the batch dimension shards over. When fsdp > 1 the
    fsdp axis doubles as extra data parallelism (ZeRO semantics: every
    device holds a distinct batch shard AND a distinct parameter shard)."""
    return ("dp", "fsdp") if mesh.shape["fsdp"] > 1 else "dp"


def dp_extent(mesh: Mesh) -> int:
    """Number of ways the batch dimension is split (dp, x fsdp when > 1)."""
    return mesh.shape["dp"] * mesh.shape["fsdp"]


def check_batch_divisible(mesh: Mesh, batch_size: int, what: str = "batch") -> None:
    """Typed compile-time guard: a batch that doesn't divide the mesh's
    data-parallel extent would otherwise die deep inside XLA with an opaque
    sharding error (or worse, silently pad)."""
    extent = dp_extent(mesh)
    if batch_size % extent:
        raise MeshConfigError(
            f"{what} size {batch_size} is not divisible by the mesh's "
            f"data-parallel extent dp x fsdp = {mesh.shape['dp']} x "
            f"{mesh.shape['fsdp']} = {extent}; pick a batch that is a "
            f"multiple of {extent} or a narrower mesh"
        )


def batch_sharding(mesh: Mesh, batch_axis: int = 0,
                   batch_size: Optional[int] = None) -> NamedSharding:
    """Shard the batch dimension over dp (and fsdp if >1), replicate the rest.
    With ``batch_size`` the divisibility is validated here (typed
    ``MeshConfigError`` at spec-construction time, not an XLA error later)."""
    if batch_size is not None:
        check_batch_divisible(mesh, batch_size)
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = dp_axes(mesh)
    return NamedSharding(mesh, P(*spec))


def time_batch_sharding(mesh: Mesh) -> NamedSharding:
    """[T, B, ...] arrays: shard B (axis 1) over dp; T stays whole (or moves
    to sp when a sequence-parallel mesh is configured)."""
    if mesh.shape["sp"] > 1:
        return NamedSharding(mesh, P("sp", dp_axes(mesh)))
    return NamedSharding(mesh, P(None, dp_axes(mesh)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_sharding(mesh: Mesh, tree):
    """Parameter partition specs over the mesh's fsdp AND tp axes.

    tp (tensor parallelism, Megatron-style over ICI): attention QKV kernels
    shard the head/output dimension ("Dense_0" under an Attention module),
    attention output projections shard the input dimension ("Dense_1"), and
    any other large-enough kernel shards its largest tp-divisible dimension.
    GSPMD propagates the activation shardings and inserts the all-reduces the
    reference would hand-place with NCCL.

    fsdp (ZeRO-3): after tp placement, the largest still-unsharded
    fsdp-divisible dimension is sharded over fsdp; params (and, via
    ``jnp.zeros_like`` inheritance, Adam moments) live 1/fsdp-sized per
    device, with the all-gather before use and reduce-scatter after the
    backward inserted by the partitioner (role of the reference's manual
    per-param NCCL allreduce, dist_helper.py:369-431).

    ``tree`` may hold arrays or ShapeDtypeStructs; returns a matching tree
    of NamedShardings.
    """
    ntp = mesh.shape["tp"]
    nfsdp = mesh.shape["fsdp"]

    def spec_for(path, x) -> NamedSharding:
        shape = getattr(x, "shape", ())
        if not shape:  # scalars replicate
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        names = [getattr(p, "key", str(p)) for p in path]
        if ntp > 1:
            tp_dim = None
            in_attention = any(str(n).startswith("Attention") for n in names)
            if in_attention and names and str(names[-1]) == "kernel" and len(shape) == 2:
                # Megatron split: QKV projection over heads (columns), output
                # projection over the contracted (row) dimension
                cand = 1 if any(str(n) == "Dense_0" for n in names) else 0
                if shape[cand] % ntp == 0 and shape[cand] >= 2 * ntp:
                    tp_dim = cand
            if tp_dim is None:
                best = None
                for i, d in enumerate(shape):
                    if d % ntp == 0 and d >= 2 * ntp and (best is None or d > shape[best]):
                        best = i
                tp_dim = best
            if tp_dim is not None:
                spec[tp_dim] = "tp"
        if nfsdp > 1:
            best = None
            for i, d in enumerate(shape):
                if spec[i] is None and d % nfsdp == 0 and d >= 2 * nfsdp and (
                    best is None or d > shape[best]
                ):
                    best = i
            if best is not None:
                spec[best] = "fsdp"
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def fsdp_param_sharding(mesh: Mesh, tree):
    """Back-compat name: fsdp-only callers get the general placement (on a
    tp=1 mesh the tp rules are inert, so behaviour is unchanged)."""
    return param_sharding(mesh, tree)


def shrink_dp(mesh: Mesh, batch_size: int) -> Mesh:
    """Return a mesh whose batch-sharding axes (dp, and fsdp when > 1 —
    see ``dp_axes``) divide ``batch_size``, preserving tp/sp (small debug
    batches on wide meshes). No-op when the batch already fits."""
    import math

    dp, fsdp = mesh.shape["dp"], mesh.shape["fsdp"]
    if batch_size % (dp * fsdp) == 0:
        return mesh
    # shrink fsdp first only as far as divisibility demands, then dp
    new_fsdp = math.gcd(batch_size, fsdp)
    new_dp = math.gcd(batch_size // new_fsdp, dp)
    spec = MeshSpec(
        dp=new_dp, fsdp=new_fsdp, tp=mesh.shape["tp"], sp=mesh.shape["sp"]
    )
    devices = mesh.devices.reshape(-1)[: new_dp * spec.fsdp * spec.tp * spec.sp]
    return make_mesh(spec, devices)
