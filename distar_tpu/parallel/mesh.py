"""Device-mesh abstraction for the learner.

Role of the reference's NCCL data-parallel plumbing (reference:
distar/ctools/utils/dist_helper.py:321-439 — manual per-param allreduce
`DistModule.sync_gradients`): here data parallelism is one axis of a general
`jax.sharding.Mesh`, the gradient allreduce is an XLA-scheduled psum over ICI
inserted by the partitioner, and rank-0-only logic maps to
`jax.process_index() == 0`.

The mesh is declared with up to four logical axes — dp (data), fsdp
(parameter shard), tp (tensor), sp (sequence/context) — so wider shardings
(tensor-parallel heads, ring-attention over a long time axis) slot in without
touching the learner. The reference model (~50-100M params) only needs dp;
the other axes default to size 1 but stay first-class in every pjit spec.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    dp: int = -1  # -1: all remaining devices
    fsdp: int = 1
    tp: int = 1
    sp: int = 1

    def sizes(self, n_devices: int) -> Sequence[int]:
        fixed = self.fsdp * self.tp * self.sp
        dp = self.dp if self.dp != -1 else n_devices // fixed
        assert dp * fixed == n_devices, (
            f"mesh {dp}x{self.fsdp}x{self.tp}x{self.sp} != {n_devices} devices"
        )
        return (dp, self.fsdp, self.tp, self.sp)


def make_mesh(spec: MeshSpec = MeshSpec(), devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.sizes(len(devices))
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, AXES)


def batch_sharding(mesh: Mesh, batch_axis: int = 0) -> NamedSharding:
    """Shard the batch dimension over dp (and fsdp if >1), replicate the rest."""
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = ("dp", "fsdp") if mesh.shape["fsdp"] > 1 else "dp"
    return NamedSharding(mesh, P(*spec))


def time_batch_sharding(mesh: Mesh) -> NamedSharding:
    """[T, B, ...] arrays: shard B (axis 1) over dp; T stays whole (or moves
    to sp when a sequence-parallel mesh is configured)."""
    if mesh.shape["sp"] > 1:
        return NamedSharding(mesh, P("sp", "dp"))
    return NamedSharding(mesh, P(None, "dp"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shrink_dp(mesh: Mesh, batch_size: int) -> Mesh:
    """Return a mesh whose dp axis divides ``batch_size``, preserving the
    fsdp/tp/sp axes (small debug batches on wide meshes). No-op when the
    batch already divides dp."""
    import math

    dp = mesh.shape["dp"]
    if batch_size % dp == 0:
        return mesh
    new_dp = math.gcd(batch_size, dp)
    spec = MeshSpec(
        dp=new_dp, fsdp=mesh.shape["fsdp"], tp=mesh.shape["tp"], sp=mesh.shape["sp"]
    )
    devices = mesh.devices.reshape(-1)[: new_dp * spec.fsdp * spec.tp * spec.sp]
    return make_mesh(spec, devices)
