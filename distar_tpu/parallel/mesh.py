"""Device-mesh abstraction for the learner.

Role of the reference's NCCL data-parallel plumbing (reference:
distar/ctools/utils/dist_helper.py:321-439 — manual per-param allreduce
`DistModule.sync_gradients`): here data parallelism is one axis of a general
`jax.sharding.Mesh`, the gradient allreduce is an XLA-scheduled psum over ICI
inserted by the partitioner, and rank-0-only logic maps to
`jax.process_index() == 0`.

The mesh is declared with up to four logical axes — dp (data), fsdp
(parameter shard), tp (tensor), sp (sequence/context) — so wider shardings
(tensor-parallel heads, ring-attention over a long time axis) slot in without
touching the learner. The reference model (~50-100M params) only needs dp;
the other axes default to size 1 but stay first-class in every pjit spec.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    dp: int = -1  # -1: all remaining devices
    fsdp: int = 1
    tp: int = 1
    sp: int = 1

    def sizes(self, n_devices: int) -> Sequence[int]:
        fixed = self.fsdp * self.tp * self.sp
        dp = self.dp if self.dp != -1 else n_devices // fixed
        assert dp * fixed == n_devices, (
            f"mesh {dp}x{self.fsdp}x{self.tp}x{self.sp} != {n_devices} devices"
        )
        return (dp, self.fsdp, self.tp, self.sp)


def make_mesh(spec: MeshSpec = MeshSpec(), devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.sizes(len(devices))
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, AXES)


def dp_axes(mesh: Mesh):
    """The mesh axes the batch dimension shards over. When fsdp > 1 the
    fsdp axis doubles as extra data parallelism (ZeRO semantics: every
    device holds a distinct batch shard AND a distinct parameter shard)."""
    return ("dp", "fsdp") if mesh.shape["fsdp"] > 1 else "dp"


def batch_sharding(mesh: Mesh, batch_axis: int = 0) -> NamedSharding:
    """Shard the batch dimension over dp (and fsdp if >1), replicate the rest."""
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = dp_axes(mesh)
    return NamedSharding(mesh, P(*spec))


def time_batch_sharding(mesh: Mesh) -> NamedSharding:
    """[T, B, ...] arrays: shard B (axis 1) over dp; T stays whole (or moves
    to sp when a sequence-parallel mesh is configured)."""
    if mesh.shape["sp"] > 1:
        return NamedSharding(mesh, P("sp", dp_axes(mesh)))
    return NamedSharding(mesh, P(None, dp_axes(mesh)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_param_sharding(mesh: Mesh, tree):
    """Parameter shardings for the fsdp axis: every large-enough leaf is
    sharded on its largest fsdp-divisible dimension; small or indivisible
    leaves stay replicated.

    This is ZeRO-3-style parameter sharding done the XLA way: params (and,
    via ``jnp.zeros_like`` inheritance, Adam moments) live sharded over the
    fsdp axis, and GSPMD inserts the all-gather before use and the
    reduce-scatter after the backward — the role the reference fills with
    manual per-param NCCL allreduce (dist_helper.py:369-431), except the
    optimizer state is also 1/fsdp-sized per device.

    ``tree`` may hold arrays or ShapeDtypeStructs; returns a matching tree
    of NamedShardings.
    """
    n = mesh.shape["fsdp"]

    def spec_for(x) -> NamedSharding:
        if n <= 1 or not getattr(x, "shape", ()):  # scalars replicate
            return NamedSharding(mesh, P())
        shape = x.shape
        best = None
        for i, d in enumerate(shape):
            if d % n == 0 and d >= 2 * n and (best is None or d > shape[best]):
                best = i
        if best is None:
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        spec[best] = "fsdp"
        return NamedSharding(mesh, P(*spec))

    return jax.tree.map(spec_for, tree)


def shrink_dp(mesh: Mesh, batch_size: int) -> Mesh:
    """Return a mesh whose batch-sharding axes (dp, and fsdp when > 1 —
    see ``dp_axes``) divide ``batch_size``, preserving tp/sp (small debug
    batches on wide meshes). No-op when the batch already fits."""
    import math

    dp, fsdp = mesh.shape["dp"], mesh.shape["fsdp"]
    if batch_size % (dp * fsdp) == 0:
        return mesh
    # shrink fsdp first only as far as divisibility demands, then dp
    new_fsdp = math.gcd(batch_size, fsdp)
    new_dp = math.gcd(batch_size // new_fsdp, dp)
    spec = MeshSpec(
        dp=new_dp, fsdp=new_fsdp, tp=mesh.shape["tp"], sp=mesh.shape["sp"]
    )
    devices = mesh.devices.reshape(-1)[: new_dp * spec.fsdp * spec.tp * spec.sp]
    return make_mesh(spec, devices)
