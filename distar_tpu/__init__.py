"""distar_tpu — a TPU-native (JAX/XLA/Pallas/pjit) framework with the
capabilities of opendilab/DI-star.

Built from scratch against the structural blueprint in /root/repo/SURVEY.md:
an AlphaStar-style distributed RL training platform — supervised learning from
replays, league self-play RL (V-trace/UPGO/TD-lambda), PFSP matchmaking, an
actor fleet feeding TPU learners, and play/eval tooling — re-architected for
TPU rather than ported from the reference's PyTorch/CUDA implementation.

Layer map (mirrors reference layers, see SURVEY.md §1):
  distar_tpu.bin       CLI entry points (rl_train, sl_train, play)
  distar_tpu.league    control plane: players, PFSP, payoff, ELO
  distar_tpu.learner   training runtime: hook-driven learners on pjit meshes
  distar_tpu.actor     CPU actor fleet + batched jitted inference
  distar_tpu.serve     inference gateway: micro-batching, sticky sessions,
                       versioned hot-swap registry, HTTP/TCP frontends
  distar_tpu.obs       metrics registry, exporters, trace spans, profiler
  distar_tpu.resilience retry/backoff fabric, circuit breakers, role
                       supervision + crash-resume, chaos injection
  distar_tpu.model     Flax policy/value network (encoders, LSTM core, heads)
  distar_tpu.ops       TPU compute primitives (pallas kernels, scan RNN, rl ops)
  distar_tpu.losses    RL and SL losses as pure jnp functions
  distar_tpu.parallel  mesh/sharding abstraction, optimizer, grad clip
  distar_tpu.comm      coordinator broker + TCP adapter data plane
  distar_tpu.envs      env interface + mock env (SC2 binary optional)
  distar_tpu.lib       feature/action data contract shared by all layers
  distar_tpu.utils     config cascade, logging/meters, timing, checkpoint
"""

__version__ = "0.1.0"
