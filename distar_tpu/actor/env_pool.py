"""Async env worker pool: each environment steps in its own thread.

Role of the reference's process-per-env fan-out (reference: distar/actor/
actor.py:301-319 forks one process per env; the GPU batch-inference loop
:268-299 serves whichever envs have filled their shared-memory slots). Real
SC2 steps are slow (~0.25s) with high variance — a lockstep fleet stalls the
whole batch on the slowest env. Here each env blocks in its own thread and
the actor batches inference over the READY set (active-mask partial batches,
which inference.BatchedInference already supports).

Results are epoch-tagged: `reset(e)` bumps the env's epoch so in-flight step
results from the abandoned episode are dropped instead of corrupting the new
one (the league-reset path restarts every episode mid-flight).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Tuple

from ..obs import get_registry

RESET = "reset"
STEP = "step"
CLOSE = "close"


class EnvWorkerPool:
    def __init__(self, env_fns: List[Callable]):
        self.num = len(env_fns)
        self._in: List[queue.Queue] = [queue.Queue() for _ in range(self.num)]
        self._out: queue.Queue = queue.Queue()
        self._epoch = [0] * self.num
        self._threads = []
        # instrument handles resolved once (workers hammer these per step);
        # the registry's own locks make the updates thread-safe
        reg = get_registry()
        self._m_steps = reg.counter("distar_env_steps_total", "env steps completed")
        self._m_resets = reg.counter("distar_env_resets_total", "env episode resets")
        self._m_errors = reg.counter("distar_env_errors_total", "env worker exceptions")
        self._m_step_time = reg.histogram("distar_env_step_seconds", "single env.step latency")
        self._m_rate = reg.gauge(
            "distar_actor_env_step_rate", "pool-wide env steps per second since start"
        )
        self._t0 = time.monotonic()
        for e, fn in enumerate(env_fns):
            t = threading.Thread(
                target=self._worker, args=(e, fn), daemon=True, name=f"env-worker-{e}"
            )
            t.start()
            self._threads.append(t)

    # ---------------------------------------------------------------- worker
    def _worker(self, e: int, env_fn: Callable) -> None:
        env = env_fn()
        try:
            while True:
                cmd, epoch, payload = self._in[e].get()
                if cmd == CLOSE:
                    return
                try:
                    if cmd == RESET:
                        obs = env.reset()
                        self._m_resets.inc()
                        self._out.put((e, epoch, RESET, obs))
                    else:
                        t_start = time.perf_counter()
                        result = env.step(payload)
                        self._m_step_time.observe(time.perf_counter() - t_start)
                        self._m_steps.inc()
                        elapsed = time.monotonic() - self._t0
                        if elapsed > 0:
                            self._m_rate.set(self._m_steps.value / elapsed)
                        self._out.put((e, epoch, STEP, result))
                except Exception as err:
                    self._m_errors.inc()
                    self._out.put((e, epoch, "error", err))
        finally:
            try:
                env.close()
            except Exception:
                pass

    # ------------------------------------------------------------------ api
    def reset(self, e: int) -> None:
        """Start a fresh episode on env ``e``; stale in-flight results from
        the previous epoch will be dropped."""
        self._epoch[e] += 1
        self._in[e].put((RESET, self._epoch[e], None))

    def submit(self, e: int, actions: dict) -> None:
        self._in[e].put((STEP, self._epoch[e], actions))

    def ready(self, timeout: Optional[float] = None) -> List[Tuple[int, str, object]]:
        """Block until at least one result is available (up to ``timeout``),
        then drain everything currently ready. Stale-epoch results are
        dropped; worker errors re-raise here."""
        out = []
        while not out:
            try:
                item = self._out.get(timeout=timeout)
            except queue.Empty:
                return out
            out.extend(self._accept(item))
            if timeout is not None and not out:
                continue
            break
        while True:
            try:
                item = self._out.get_nowait()
            except queue.Empty:
                break
            out.extend(self._accept(item))
        return out

    def _accept(self, item):
        e, epoch, kind, payload = item
        if epoch != self._epoch[e]:
            return []  # abandoned episode
        if kind == "error":
            raise RuntimeError(f"env worker {e} failed") from payload
        return [(e, kind, payload)]

    def close(self) -> None:
        for e in range(self.num):
            self._epoch[e] += 1  # drop anything still in flight
            self._in[e].put((CLOSE, self._epoch[e], None))
        for t in self._threads:
            t.join(timeout=5.0)
