"""Batched jitted inference for the actor fleet.

Role of the reference's gpu_batch_inference (reference: distar/agent/default/
agent.py:715-739 and actor.py:268-299 — shared-memory slots + spin-wait
signals feeding one GPU forward): here every env slot's prepared observation
is stacked into ONE fixed-shape device batch and a single jitted
``sample_action`` serves all slots; teacher logits batch the same way. No
shared memory, no signal tensors — the batch IS the protocol, and fixed
shapes mean one compilation.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..lib import features as F
from ..model import Model


def decollate(tree, idx: int):
    """Slice one slot out of a batched output pytree.

    The whole pytree is fetched to host in ONE ``jax.device_get`` (a single
    transfer covering every leaf) and the slot is handed out as views of
    that host copy — never one ``np.asarray`` device sync per leaf per
    slot, which cost num_slots x num_leaves transfers per step."""
    host = jax.device_get(tree)
    return jax.tree.map(lambda x: x[idx], host)


class BatchedInference:
    """Owns params + hidden states for all slots of one player_id.

    Also owns the (optional) frozen-teacher side of the rollout contract:
    ``teacher_params`` plus one teacher LSTM carry per slot, advanced by
    ``teacher_step`` and zeroed alongside the policy carry in
    ``reset_slot`` — so an engine built on this object holds the COMPLETE
    per-slot recurrent state server-side (the serve plane's session-per-slot
    contract, docs/serving.md)."""

    def __init__(self, model: Model, params, num_slots: int, seed: int = 0,
                 teacher_params=None):
        self.model = model
        self.params = params
        self.num_slots = num_slots
        cfg = model.cfg
        self._hidden_size = cfg["encoder"]["core_lstm"]["hidden_size"]
        self._num_layers = cfg["encoder"]["core_lstm"]["num_layers"]
        self.hidden = self._zero_hidden()
        self.teacher_params = teacher_params
        self.teacher_hidden = self._zero_hidden()
        self._rng = jax.random.PRNGKey(seed)

        self._sample = jax.jit(
            lambda p, d, h, r: model.apply(
                p, d["spatial_info"], d["entity_info"], d["scalar_info"], d["entity_num"],
                h, r, method=model.sample_action,
            )
        )
        self._teacher = jax.jit(
            lambda p, d, h, a, n: model.apply(
                p, d["spatial_info"], d["entity_info"], d["scalar_info"], d["entity_num"],
                h, a, n, method=model.teacher_logits,
            )
        )

    def _zero_hidden(self):
        z = jnp.zeros((self.num_slots, self._hidden_size))
        return tuple((z, z) for _ in range(self._num_layers))

    def set_params(self, params) -> None:
        """Install new weights (serve-plane hot swap). The pytree structure
        and leaf shapes must match the old params, so the jitted forward is
        reused — a swap never recompiles. A forward already executing keeps
        the params reference it was called with; the swap takes effect from
        the next ``sample``."""
        self.params = params

    def set_teacher_params(self, params) -> None:
        """Install (or replace) the frozen teacher weights. Same shape-
        stability contract as ``set_params``: the jitted teacher forward is
        reused, never recompiled."""
        self.teacher_params = params

    def warmup(self, template_obs: dict, params=None) -> None:
        """One throwaway batched forward on scratch hidden state.

        Compiles (first call) or exercises the jitted ``sample_action``
        without touching ``self.params``, ``self.hidden`` or the RNG — safe
        to run concurrently with serving traffic, which is the point: the
        registry warms a freshly loaded checkpoint off the serving path
        before atomically swapping it in."""
        batch = jax.tree.map(jnp.asarray, F.batch_tree([template_obs] * self.num_slots))
        self._sample(
            params if params is not None else self.params,
            batch, self._zero_hidden(), jax.random.PRNGKey(0),
        )

    def reset_slot(self, idx: int) -> None:
        """Zero one slot's policy AND teacher hidden state (episode
        boundary — the slot's whole recurrent state restarts together)."""
        self.hidden = tuple(
            (h.at[idx].set(0.0), c.at[idx].set(0.0)) for h, c in self.hidden
        )
        self.teacher_hidden = tuple(
            (h.at[idx].set(0.0), c.at[idx].set(0.0)) for h, c in self.teacher_hidden
        )

    def hidden_for_slot(self, idx: int):
        # slice on device, then ONE host fetch for the whole carry tuple
        return jax.device_get(tuple((h[idx], c[idx]) for h, c in self.hidden))

    def sample(self, prepared: List[dict], active: Optional[List[bool]] = None) -> List[dict]:
        """One batched forward over all slots; returns per-slot outputs.

        ``active`` marks slots that are actually acting this cycle (variable
        per-agent delays mean some slots carry stale observations as batch
        filler): inactive slots keep their previous hidden state and their
        outputs must be ignored by the caller. The batch shape stays static —
        inactive-lane compute is the price of one compiled program.
        """
        assert len(prepared) == self.num_slots
        batch = jax.tree.map(jnp.asarray, F.batch_tree(prepared))
        self._rng, key = jax.random.split(self._rng)
        old_hidden = self.hidden
        out = self._sample(self.params, batch, self.hidden, key)
        self.hidden = self._merge_hidden(out["hidden_state"], old_hidden, active)
        # ONE device->host transfer for the whole batched output pytree;
        # per-slot dicts are views of that host copy (satellite of the
        # rollout plane: the old per-leaf np.asarray cost one sync each)
        host = jax.device_get({k: v for k, v in out.items() if k != "hidden_state"})
        return [jax.tree.map(lambda x: x[i], host) for i in range(self.num_slots)]

    def _merge_hidden(self, new, old, active: Optional[List[bool]]):
        if active is None or all(active):
            return new
        mask = jnp.asarray(np.asarray(active, bool))[:, None]
        return jax.tree.map(lambda n, o: jnp.where(mask, n, o), new, old)

    def teacher_logits(
        self, teacher_params, prepared: List[dict], teacher_hidden, outputs: List[dict],
        active: Optional[List[bool]] = None,
    ):
        """Teacher-forced logits for the freshly sampled actions; returns
        (per-slot logit dicts, new teacher hidden — inactive slots keep the
        old carry)."""
        batch = jax.tree.map(jnp.asarray, F.batch_tree(prepared))
        action_info = jax.tree.map(
            jnp.asarray, F.batch_tree([o["action_info"] for o in outputs])
        )
        sun = jnp.asarray(np.stack([np.asarray(o["selected_units_num"]) for o in outputs]))
        out = self._teacher(teacher_params, batch, teacher_hidden, action_info, sun)
        merged = self._merge_hidden(out["hidden_state"], teacher_hidden, active)
        host_logit = jax.device_get(out["logit"])  # one transfer, slots view it
        per_slot = [jax.tree.map(lambda x: x[i], host_logit) for i in range(self.num_slots)]
        return per_slot, merged

    def teacher_step(
        self, prepared: List[dict], outputs: List[dict],
        active: Optional[List[bool]] = None,
    ) -> List[dict]:
        """Stateful teacher forward over the instance's own frozen teacher
        weights and per-slot teacher carries (advanced here; inactive slots
        keep theirs). Requires ``teacher_params`` to be installed."""
        if self.teacher_params is None:
            raise RuntimeError(
                "teacher_step: no teacher params installed "
                "(set_teacher_params / teacher_params ctor arg)"
            )
        per_slot, self.teacher_hidden = self.teacher_logits(
            self.teacher_params, prepared, self.teacher_hidden, outputs, active
        )
        return per_slot
