"""Scripted (model-free) agents implementing docs/agent_contract.md.

Role of the reference's scripted demo agents (reference:
distar/pysc2/agents/random_agent.py, scripted_agent.py, base_agent.py):
cheap league opponents and smoke fixtures that plug into the Actor by
pipeline name — no network, no inference batch slot, no trajectories.

Actions are drawn from the 327-entry ACTIONS table and respect each
action's per-head applicability masks (lib/actions.py), so every emitted
dict is a structurally valid env action; RandomAgent additionally applies
the per-race legality mask (lib/stat.ACTION_RACE_MASK) when constructed
with a ``race``.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..lib import features as F
from ..lib.actions import (
    ACTIONS,
    QUEUED_MASK,
    SELECTED_UNITS_MASK,
    TARGET_LOCATION_MASK,
    TARGET_UNIT_MASK,
)


class ScriptedAgent:
    """Base scripted agent: the Actor-facing duck type with no model.

    Subclasses implement ``act(obs) -> action dict``; everything else
    (reset/z handling, episode stats, trajectory hooks) is inert here.
    """

    HAS_MODEL = False

    def __init__(self, player_id: str = "scripted", seed: int = 0, **_kwargs):
        self.player_id = player_id
        self.model_last_iter = 0
        self.collect_trajectories = False
        self._output = None  # the Actor's collect-on-receipt guard stays off
        self._rng = np.random.default_rng(seed)
        self._steps = 0

    # ------------------------------------------------------------- contract
    def reset(self, z: Optional[dict] = None) -> None:
        self._steps = 0

    def act(self, obs: dict) -> dict:  # pragma: no cover - abstract
        raise NotImplementedError

    def step(self, obs: dict) -> dict:
        self._steps += 1
        return self.act(obs)

    def collect_data(self, *a, **k):  # scripted agents never emit trajectories
        return None

    def episode_stats(self) -> dict:
        """Schema-compatible stats for league meters (all-zero: a scripted
        opponent has no Z target or behaviour stats to report)."""
        from ..lib.stat import CUM_DICT

        return {
            "bo_distance": 0.0,
            "cum_distance": 0.0,
            "bo_reward_total": 0.0,
            "cum_reward_total": 0.0,
            "battle_reward_total": 0.0,
            "cumulative_stat": [0] * len(CUM_DICT),
            "unit_num": {},
        }

    # --------------------------------------------------------------- helpers
    def _valid_units(self, obs: dict) -> int:
        n = int(np.asarray(obs.get("entity_num", 1)))
        return max(1, min(n, F.MAX_ENTITY_NUM))

    def _noop(self) -> dict:
        return {
            "action_type": 0,
            "delay": int(self._rng.integers(1, 16)),
            "queued": 0,
            "selected_units": np.zeros(F.MAX_SELECTED_UNITS_NUM, np.int64),
            "selected_units_num": 0,
            "target_unit": 0,
            "target_location": 0,
        }


class IdleAgent(ScriptedAgent):
    """Always no-op — the cheapest possible opponent / smoke fixture."""

    def act(self, obs: dict) -> dict:
        return self._noop()


class RandomAgent(ScriptedAgent):
    """Uniform-random structurally-valid actions (role of the reference
    pysc2/agents/random_agent.py): a random applicable action type (drawn
    from the race-legal set when ``race`` is given — lib/stat
    ACTION_RACE_MASK, the same gate play mode applies to model logits),
    random valid unit selections, random map target."""

    def __init__(self, player_id: str = "random", seed: int = 0,
                 noop_prob: float = 0.25, race: Optional[str] = None, **kwargs):
        super().__init__(player_id, seed, **kwargs)
        self.noop_prob = noop_prob
        if race is not None:
            from ..lib.stat import ACTION_RACE_MASK

            self._action_ids = np.flatnonzero(ACTION_RACE_MASK[race])
        else:
            self._action_ids = np.arange(len(ACTIONS))

    def act(self, obs: dict) -> dict:
        if self._rng.random() < self.noop_prob:
            return self._noop()
        at = int(self._rng.choice(self._action_ids))
        n_valid = self._valid_units(obs)
        act = self._noop()
        act["action_type"] = at
        if QUEUED_MASK[at]:
            act["queued"] = int(self._rng.integers(0, 2))
        if SELECTED_UNITS_MASK[at]:
            k = int(self._rng.integers(1, min(F.MAX_SELECTED_UNITS_NUM, n_valid) + 1))
            sel = self._rng.choice(n_valid, size=k, replace=False).astype(np.int64)
            act["selected_units"][: len(sel)] = sel
            act["selected_units_num"] = int(len(sel))
        if TARGET_UNIT_MASK[at]:
            act["target_unit"] = int(self._rng.integers(0, n_valid))
        if TARGET_LOCATION_MASK[at]:
            act["target_location"] = int(
                self._rng.integers(0, F.SPATIAL_SIZE[0] * F.SPATIAL_SIZE[1])
            )
        return act


SCRIPTED_PIPELINES = {
    "scripted.random": RandomAgent,
    "scripted.idle": IdleAgent,
}


def is_scripted(pipeline: Optional[str]) -> bool:
    return bool(pipeline) and pipeline in SCRIPTED_PIPELINES


def build_scripted(pipeline: str, player_id: str, seed: int = 0,
                   race: Optional[str] = None) -> ScriptedAgent:
    """Agent-by-pipeline-name (role of the reference import_helper
    agent registry, distar/agent/import_helper.py:11-14)."""
    return SCRIPTED_PIPELINES[pipeline](player_id=player_id, seed=seed, race=race)
