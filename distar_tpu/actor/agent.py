"""Stateful per-episode agent.

Role parity with the reference Agent (reference: distar/agent/default/
agent.py:92-750): Z-target sampling and conditioning, observation
augmentation with last-action fields (_pre_process :257-304), action decode
(_post_process :347-393), pseudo-rewards against the target strategy Z via
levenshtein/hamming (_update_fake_reward :619-713 with the time-decay factor
:741-750), and trajectory assembly incl. teacher logits (collect_data
:475-607).

TPU-first split: the agent holds NO network — the Actor batches all envs'
prepared observations into one jitted forward on fixed-shape device buffers
(replacing the reference's shared-memory GPU slot protocol, agent.py:715-739).
The agent is the pure-Python per-slot state machine around that.
"""
from __future__ import annotations

import copy
from collections import deque
from functools import partial
from typing import Dict, List, Optional

import numpy as np

from ..lib import actions as ACT
from ..lib import features as F
from ..ops.metric import hamming_distance, l2_distance, levenshtein_distance

BO_NORM = 20.0
CUM_NORM = 30.0
BATTLE_NORM = 30.0


def time_decay_factor(game_step: float) -> float:
    """Pseudo-reward decay over game time (reference agent.py:741-750)."""
    if game_step < 10_000:
        return 1.0
    if game_step < 20_000:
        return 0.5
    if game_step < 30_000:
        return 0.25
    return 0.0


def sample_fake_z(rng: Optional[np.random.Generator] = None) -> dict:
    """A synthetic target strategy with the real Z-entry schema (stand-in for
    the map/race/born-location-keyed Z json libraries, agent.py:176-243;
    real libraries load via lib.z_library.ZLibrary)."""
    rng = rng or np.random.default_rng(0)
    n_bo = int(rng.integers(5, F.BEGINNING_ORDER_LENGTH))
    bo = rng.integers(1, ACT.NUM_BEGINNING_ORDER_ACTIONS, n_bo).tolist()
    loc = rng.integers(0, F.SPATIAL_SIZE[0] * F.SPATIAL_SIZE[1], n_bo).tolist()
    cum_idx = sorted(
        set(rng.integers(1, ACT.NUM_CUMULATIVE_STAT_ACTIONS, 20).tolist())
    )
    return {
        "beginning_order": bo,
        "bo_location": loc,
        "cumulative_stat": cum_idx,  # slot indices (Z-entry convention)
        "bo_norm": max(len(bo), 1),
        "cum_norm": max(len(cum_idx), 1),
    }


class Agent:
    HAS_MODEL = True

    def __init__(
        self,
        player_id: str,
        z: Optional[dict] = None,
        traj_len: int = 16,
        use_bo_reward: bool = True,
        use_cum_reward: bool = True,
        clip_bo: bool = False,
        seed: int = 0,
        max_entities: Optional[int] = None,
    ):
        self.player_id = player_id
        self._traj_len = traj_len
        # pad-to-bucket entity cap: slice the obs BEFORE it reaches the
        # model/trajectory so sampled indices, end-token detection, and the
        # stored learner data all agree on the capped entity set
        # (learner/data.cap_entities contract)
        self._max_entities = max_entities
        self.use_bo_reward = use_bo_reward
        self.use_cum_reward = use_cum_reward
        self._clip_bo = clip_bo
        self._rng = np.random.default_rng(seed)
        self._z = z or sample_fake_z(self._rng)
        self.model_last_iter = 0
        # eval agents keep stats/pseudo-rewards but assemble no trajectories
        # (the reference's eval job_type skips the data buffer entirely)
        self.collect_trajectories = True
        self.reset()

    # ----------------------------------------------------------------- reset
    def reset(self, z: Optional[dict] = None) -> None:
        if z is not None:
            self._z = z
        bo = list(self._z["beginning_order"])[: F.BEGINNING_ORDER_LENGTH]
        loc = list(self._z["bo_location"])[: F.BEGINNING_ORDER_LENGTH]
        pad = F.BEGINNING_ORDER_LENGTH - len(bo)
        self._target_building_order = bo
        self._target_bo_location = loc
        self._target_z_bo = np.asarray(bo + [0] * pad, dtype=np.int64)
        self._target_z_loc = np.asarray(loc + [0] * pad, dtype=np.int64)
        # Z entries carry cumulative stats as slot indices; densify
        cum = np.asarray(self._z["cumulative_stat"], dtype=np.int64)
        if cum.ndim == 1 and cum.shape[0] == ACT.NUM_CUMULATIVE_STAT_ACTIONS:
            self._target_cumulative_stat = cum
        else:
            dense = np.zeros(ACT.NUM_CUMULATIVE_STAT_ACTIONS, dtype=np.int64)
            if cum.size:
                dense[np.clip(cum, 0, ACT.NUM_CUMULATIVE_STAT_ACTIONS - 1)] = 1
            self._target_cumulative_stat = dense
        # per-entry reward normalisers + gates (agent.py:238-239,211-221)
        self._bo_norm = float(self._z.get("bo_norm", BO_NORM))
        self._cum_norm = float(self._z.get("cum_norm", CUM_NORM))
        if "use_bo_reward" in self._z:
            self.use_bo_reward = bool(self._z["use_bo_reward"])
        if "use_cum_reward" in self._z:
            self.use_cum_reward = bool(self._z["use_cum_reward"])

        self._behaviour_building_order: List[int] = []
        self._behaviour_bo_location: List[int] = []
        self._behaviour_cumulative_stat = np.zeros(
            ACT.NUM_CUMULATIVE_STAT_ACTIONS, dtype=np.int64
        )
        self._old_bo_reward = (
            -levenshtein_distance(np.asarray([]), np.asarray(self._target_building_order))
            / self._bo_norm
        )
        self._old_cum_reward = (
            -hamming_distance(self._behaviour_cumulative_stat, self._target_cumulative_stat)
            / self._cum_norm
        )
        self._bo_zergling_count = 0
        self._total_bo_reward = 0.0
        self._total_cum_reward = 0.0
        self._total_battle_reward = 0.0
        self._exceed_flag = True
        self._last_action = {k: 0 for k in F.ACTION_HEADS}
        self._battle_score = 0.0
        self._opponent_battle_score = 0.0
        self._game_step = 0
        from ..lib.stat import Stat

        self._stat = Stat(self._z.get("race", "zerg"))
        self._data_buffer: deque = deque()
        self._observation: Optional[dict] = None
        self._value_feature: Optional[dict] = None
        self._output: Optional[dict] = None
        self._hidden_state_backup = None  # set by actor at traj starts
        self._result = 0

    # ------------------------------------------------------------ pre-process
    def pre_process(self, obs: dict) -> dict:
        """Augment a feature-level obs with last-action fields and the Z
        conditioning targets (reference _pre_process :257-304)."""
        obs = copy.copy(obs)
        n = self._max_entities
        self._capped_end = None
        if n and next(iter(obs["entity_info"].values())).shape[0] > n:
            raw_num = int(np.asarray(obs["entity_num"]))
            obs["entity_info"] = {k: v[:n] for k, v in obs["entity_info"].items()}
            obs["entity_num"] = np.minimum(np.asarray(obs["entity_num"]), n)
            if raw_num > n:
                # the model's end token (index == capped entity_num) aliases
                # a REAL tag index in the env's uncapped tag list: remember
                # it so post_process can strip it from the env action
                self._capped_end = int(np.asarray(obs["entity_num"]))
        scalar = dict(obs["scalar_info"])
        scalar["last_action_type"] = np.asarray(self._last_action["action_type"], np.int16)
        scalar["last_delay"] = np.asarray(self._last_action["delay"], np.int16)
        scalar["last_queued"] = np.asarray(self._last_action["queued"], np.int16)
        scalar["beginning_order"] = self._target_z_bo.astype(np.int16)
        scalar["bo_location"] = self._target_z_loc.astype(np.int16)
        scalar["cumulative_stat"] = self._target_cumulative_stat.astype(np.uint8)
        obs["scalar_info"] = scalar
        self._game_step = float(np.asarray(scalar["time"]))
        self._observation = {
            "spatial_info": obs["spatial_info"],
            "entity_info": obs["entity_info"],
            "scalar_info": scalar,
            "entity_num": obs["entity_num"],
        }
        if "value_feature" in obs:
            # centralized-critic features ride alongside (learner-only; the
            # model input above stays actor-shaped). The critic also sees
            # this side's behaviour Z (reference agent.py:563-564).
            self._value_feature = {**obs["value_feature"], **self.get_behavior_z()}
        else:
            self._value_feature = None
        self._raw_obs = obs
        return self._observation

    # ----------------------------------------------------------- post-process
    def post_process(self, output: dict) -> dict:
        """Store the model output, return the env-facing action dict
        (reference _post_process :347-393 — tag mapping happens in the real
        env binding; the feature-level contract passes indices through)."""
        self._output = output
        a = output["action_info"]
        self._last_action = {k: int(np.asarray(a[k]).reshape(-1)[0]) if k != "selected_units"
                             else 0 for k in F.ACTION_HEADS}
        self._last_action["selected_units"] = 0
        selected = np.asarray(a["selected_units"])
        if getattr(self, "_capped_end", None) is not None:
            # uncapped frames rely on the env dropping index == n_tags; with
            # the obs capped below the real count the end token would alias
            # tags[capped_end], so remap it to the real out-of-range index
            selected = np.where(
                selected == self._capped_end, np.iinfo(np.int64).max, selected
            )
        return {
            "action_type": np.asarray(a["action_type"]),
            "delay": np.asarray(a["delay"]),
            "queued": np.asarray(a["queued"]),
            "selected_units": selected,
            "target_unit": np.asarray(a["target_unit"]),
            "target_location": np.asarray(a["target_location"]),
        }

    # --------------------------------------------------------- pseudo-rewards
    def update_fake_reward(self, next_obs: dict) -> Dict[str, float]:
        action_type = int(self._last_action["action_type"])
        location = int(self._last_action["target_location"])
        bo_reward, cum_reward = 0.0, 0.0

        battle_score = float(next_obs.get("battle_score", 0.0))
        opp_score = float(next_obs.get("opponent_battle_score", 0.0))
        battle_reward = (
            (battle_score - self._battle_score) - (opp_score - self._opponent_battle_score)
        ) / BATTLE_NORM
        self._battle_score = battle_score
        self._opponent_battle_score = opp_score

        success = bool(next_obs.get("action_result", [1])[0] == 1)
        if not self._exceed_flag:
            return {"build_order": bo_reward, "built_unit": cum_reward, "battle": battle_reward}

        if action_type in ACT.BEGINNING_ORDER_ACTIONS and success:
            # zergling spam guard (reference :632-635)
            if action_type == 322:
                self._bo_zergling_count += 1
                if self._bo_zergling_count > 8:
                    return {
                        "build_order": bo_reward, "built_unit": cum_reward, "battle": battle_reward,
                    }
            order_index = ACT.BEGINNING_ORDER_ACTIONS.index(action_type)
            if len(self._behaviour_building_order) < len(self._target_building_order):
                self._behaviour_building_order.append(order_index)
                self._behaviour_bo_location.append(
                    location if ACT.ACTIONS[action_type]["target_location"] else 0
                )
                if self.use_bo_reward:
                    if self._clip_bo:
                        tz = self._target_building_order[: len(self._behaviour_building_order)]
                        tz_lo = self._target_bo_location[: len(self._behaviour_building_order)]
                    else:
                        tz, tz_lo = self._target_building_order, self._target_bo_location
                    new_bo = (
                        -levenshtein_distance(
                            np.asarray(self._behaviour_building_order),
                            np.asarray(tz),
                            np.asarray(self._behaviour_bo_location),
                            np.asarray(tz_lo),
                            partial(l2_distance, spatial_x=F.SPATIAL_SIZE[1]),
                        )
                        / self._bo_norm
                    )
                    bo_reward = new_bo - self._old_bo_reward
                    self._old_bo_reward = new_bo

        cum_flag = False
        # cancelled builds lose their cumulative-stat credit (reference
        # agent.py:682-697, cum_type 'action'): resolve the cancelled order
        # from the selected unit's order fields and decrement its slot
        if ACT.ACTIONS[action_type]["name"] in ("Cancel_quick", "Cancel_Last_quick"):
            cancelled = self._resolve_cancelled_action()
            # 0 = unresolved (and the no-op slot of CUMULATIVE_STAT_ACTIONS)
            if cancelled > 0 and cancelled in ACT.CUMULATIVE_STAT_ACTIONS:
                cum_flag = True
                ci = ACT.CUMULATIVE_STAT_ACTIONS.index(cancelled)
                self._behaviour_cumulative_stat[ci] = max(
                    0, self._behaviour_cumulative_stat[ci] - 1
                )
        if action_type in ACT.CUMULATIVE_STAT_ACTIONS:
            cum_flag = True
            self._behaviour_cumulative_stat[
                ACT.CUMULATIVE_STAT_ACTIONS.index(action_type)
            ] += 1
        # stat updates above are unconditional, the reward recompute gates on
        # the action having succeeded (reference agent.py:699-705)
        if self.use_cum_reward and cum_flag and success:
            # hamming_distance binarizes internally (reference casts to bool)
            new_cum = (
                -hamming_distance(self._behaviour_cumulative_stat, self._target_cumulative_stat)
                / self._cum_norm
            )
            cum_reward = (new_cum - self._old_cum_reward) * time_decay_factor(self._game_step)
            self._old_cum_reward = new_cum
        self._total_bo_reward += bo_reward
        self._total_cum_reward += cum_reward
        self._total_battle_reward += battle_reward
        return {"build_order": bo_reward, "built_unit": cum_reward, "battle": battle_reward}

    def _resolve_cancelled_action(self) -> int:
        """Which action a Cancel_quick/Cancel_Last_quick undoes: the selected
        unit's current order (order_id_0, a mix-ability index) when it has one
        order, else the LAST queued order (order_id_{n-1}, a queue-action id)
        (reference agent.py:682-692)."""
        if self._output is None or self._observation is None:
            return 0
        su = np.asarray(self._output["action_info"]["selected_units"]).reshape(-1)
        if su.size == 0:
            return 0
        unit_index = int(su[0])
        # su[0] may be the end-of-selection token (== entity_num): no unit
        if unit_index >= int(np.asarray(self._observation["entity_num"]).reshape(-1)[0]):
            return 0
        ent = self._observation["entity_info"]
        order_len = int(np.asarray(ent["order_length"]).reshape(-1)[unit_index])
        if order_len == 1:
            ability = int(np.asarray(ent["order_id_0"]).reshape(-1)[unit_index])
            return ACT.UNIT_ABILITY_TO_ACTION.get(ability, 0)
        if order_len > 1:
            key = f"order_id_{min(order_len - 1, 3)}"
            q = int(np.asarray(ent[key]).reshape(-1)[unit_index])
            if 1 <= q <= len(ACT.QUEUE_ACTIONS):
                return ACT.QUEUE_ACTIONS[q - 1]
        return 0

    def episode_stats(self) -> dict:
        """Per-episode summary for league stat meters (reference result_info:
        distances + reward totals + behaviour cum stats)."""
        from ..ops.metric import hamming_distance as _hd, levenshtein_distance as _ld

        return {
            "bo_distance": _ld(
                np.asarray(self._behaviour_building_order),
                np.asarray(self._target_building_order),
            ),
            "cum_distance": _hd(
                self._behaviour_cumulative_stat, self._target_cumulative_stat
            ),
            "bo_reward_total": self._total_bo_reward,
            "cum_reward_total": self._total_cum_reward,
            "battle_reward_total": self._total_battle_reward,
            "cumulative_stat": (self._behaviour_cumulative_stat > 0).astype(int).tolist(),
            "unit_num": self._stat.unit_num,
        }

    def get_behavior_z(self) -> dict:
        pad = F.BEGINNING_ORDER_LENGTH - len(self._behaviour_building_order)
        return {
            "beginning_order": np.asarray(self._behaviour_building_order + [0] * pad, np.int64),
            "bo_location": np.asarray(self._behaviour_bo_location + [0] * pad, np.int64),
            "cumulative_stat": (self._behaviour_cumulative_stat > 0).astype(np.int64),
        }

    # ------------------------------------------------------------ trajectory
    def collect_data(
        self,
        next_obs: Optional[dict],
        reward: float,
        done: bool,
        teacher_logit: dict,
        hidden_state_backup,
    ) -> Optional[list]:
        """Assemble one trajectory step; returns a completed trajectory
        (list of step dicts + bootstrap step) every traj_len steps or at
        episode end (reference collect_data :475-607)."""
        pseudo = self.update_fake_reward(next_obs or {})
        a = self._output["action_info"]
        action_type = int(np.asarray(a["action_type"]).reshape(-1)[0])
        self._stat.update(
            action_type,
            1 if (next_obs or {}).get("action_result", [1])[0] == 1 else 0,
            self._observation,
            self._game_step,
        )
        spec = ACT.ACTIONS[action_type]
        if not self.collect_trajectories:
            # eval agents keep the stat/pseudo-reward updates above but
            # skip the per-step trajectory assembly entirely
            return None
        mask = {
            "actions_mask": {
                "action_type": 1.0,
                "delay": 1.0,
                "queued": float(spec["queued"]),
                "selected_units": float(spec["selected_units"]),
                "target_unit": float(spec["target_unit"]),
                "target_location": float(spec["target_location"]),
            },
            "cum_action_mask": 1.0,
            "build_order_mask": float(self.use_bo_reward),
            "built_unit_mask": float(self.use_cum_reward),
            "effect_mask": 1.0,
            "step_mask": 1.0,
        }
        step_data = {
            "spatial_info": self._observation["spatial_info"],
            "entity_info": self._observation["entity_info"],
            "scalar_info": self._observation["scalar_info"],
            "entity_num": self._observation["entity_num"],
            "selected_units_num": np.asarray(self._output["selected_units_num"]).reshape(()),
            "hidden_state": hidden_state_backup,
            "action_info": {k: np.asarray(v) for k, v in a.items()},
            "behaviour_logp": {k: np.asarray(v) for k, v in self._output["action_logp"].items()},
            "teacher_logit": {k: np.asarray(v) for k, v in teacher_logit.items()},
            "reward": {
                "winloss": float(reward),
                "build_order": pseudo["build_order"],
                "built_unit": pseudo["built_unit"],
                "effect": 0.0,
                "upgrade": 0.0,
                "battle": pseudo["battle"],
            },
            "step": float(self._game_step),
            "mask": mask,
            "done": float(done),
            "model_last_iter": float(self.model_last_iter),
        }
        if self._value_feature is not None:
            step_data["value_feature"] = self._value_feature
        self._data_buffer.append(step_data)
        if len(self._data_buffer) >= self._traj_len or done:
            # fixed-shape contract: an episode ending mid-window pads the
            # trajectory to traj_len by repeating the final step with masks,
            # rewards, and logps zeroed — padded steps contribute nothing to
            # any loss term but keep T static for XLA. step_mask=0 + done=1
            # let the loss zero post-terminal values and mask the always-on
            # heads on pads (the terminal +-1 reward stays at its real step).
            while done and len(self._data_buffer) < self._traj_len:
                pad = copy.deepcopy(self._data_buffer[-1])
                pad["mask"] = {
                    "actions_mask": {k: 0.0 for k in pad["mask"]["actions_mask"]},
                    "cum_action_mask": 0.0,
                    "build_order_mask": 0.0,
                    "built_unit_mask": 0.0,
                    "effect_mask": 0.0,
                    "step_mask": 0.0,
                }
                pad["done"] = 1.0
                pad["reward"] = {k: 0.0 for k in pad["reward"]}
                pad["behaviour_logp"] = {
                    k: np.zeros_like(v) for k, v in pad["behaviour_logp"].items()
                }
                self._data_buffer.append(pad)
            # bootstrap step: the NEXT observation when the episode goes on
            # (value bootstraps from it); on done the learner ignores it, so
            # the current obs stands in (reference :572-598)
            bootstrap_src = self._observation if done else self.pre_process(next_obs)
            last_step = {
                "spatial_info": bootstrap_src["spatial_info"],
                "entity_info": bootstrap_src["entity_info"],
                "scalar_info": bootstrap_src["scalar_info"],
                "entity_num": bootstrap_src["entity_num"],
            }
            if self._value_feature is not None:
                last_step["value_feature"] = self._value_feature
            traj = list(self._data_buffer) + [last_step]
            self._data_buffer.clear()
            return traj
        return None
