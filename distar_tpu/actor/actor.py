"""Actor: job lifecycle + lockstep env fleet with batched device inference.

Role parity with the reference Actor (reference: distar/actor/actor.py:23-353
and actor_comm.py): ask the league for a job, drive env<->agent loops, ship
trajectories to the learner over the Adapter, pull fresh weights
periodically, report results.

TPU-first divergence (documented design choice): the reference forks one
process per env and funnels inference through shared-memory slots
(actor.py:301-319, agent.py:715-739). Here the env fleet steps in lockstep
inside one process and every slot's observation joins ONE fixed-shape jitted
batch — the natural shape for a TPU host, where a single batched forward
amortises dispatch and the MXU. WHERE that batch runs is the rollout
plane's choice (rollout_plane.PolicyClient, the Sebulba split): a private
per-actor BatchedInference (``inline``, default), this host's shared
gateway+engine (``local``), or a remote bin/serve gateway (``remote``).
SC2-process concurrency (the real env's slow step) belongs to the env
layer's own worker pool behind the same interface.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from ..comm import Adapter
from ..envs import BaseEnv, MockEnv
from ..league import League
from ..model import Model, default_model_config
from ..obs import get_registry, start_trace
from ..utils import Config, deep_merge_dicts
from .agent import Agent, sample_fake_z
from .rollout_plane import RolloutPlane

ACTOR_DEFAULTS = Config(
    {
        "actor": {
            "env_num": 2,
            "traj_len": 16,
            "episodes_per_job": 1,
            "model_update_interval_s": 10.0,
            "seed": 0,
            # directories searched for the job's z_path libraries
            "z_dirs": ["", "data/z_libraries"],
            "fake_reward_prob": 1.0,
            # pad-to-bucket entity cap for inference obs (agents slice in
            # pre_process; matches the learner-side learner.max_entities)
            "max_entities": None,
            # replay-store push target (config-switched; default off so the
            # legacy point-to-point shuttle path is untouched). ``addr`` is
            # "host:port" of a ReplayServer, a comma-separated shard list
            # (trajectories route by consistent hash — docs/data_plane.md
            # sharding), or "inproc" for the zero-copy colocated store;
            # ``mirror`` additionally keeps the shuttle push alive
            # (migration/dual-write drills); ``priority`` seeds the table
            # priority for fresh trajectories; ``compress`` is this side's
            # wire-compression preference (negotiated per connection);
            # ``transport`` picks the data-plane leg (auto negotiates shm
            # rings with colocated stores, tcp forces the socket).
            "replay": {
                "enabled": False,
                "addr": "",
                "mirror": False,
                "priority": 1.0,
                "timeout_s": 60.0,
                "compress": True,
                "transport": "auto",
            },
            # rollout inference plane (docs/serving.md, Sebulba split):
            # ``inline`` keeps today's per-actor BatchedInference; ``local``
            # shares ONE in-process gateway+engine per player across every
            # job on this host; ``remote`` rides the framed-TCP data plane
            # of a bin/serve.py gateway at ``addr``. ``slots`` sizes the
            # shared local engine (0 = this job's env_num).
            "plane": {
                "backend": "inline",
                "addr": "",
                "slots": 0,
                "max_delay_s": 0.005,
                "timeout_s": 30.0,
                # remote-backend transport: auto negotiates shm rings per
                # gateway connection when colocated (docs/data_plane.md)
                "transport": "auto",
            },
        }
    }
)


class Actor:
    def __init__(
        self,
        cfg: Optional[dict] = None,
        league: Optional[League] = None,
        adapter: Optional[Adapter] = None,
        model_cfg: Optional[dict] = None,
        env_fn: Optional[Callable[[], BaseEnv]] = None,
        init_params: Optional[dict] = None,
        player_params: Optional[Dict[str, dict]] = None,
    ):
        whole = deep_merge_dicts(ACTOR_DEFAULTS, cfg or {})
        self.cfg = whole.actor
        self.league = league
        self.adapter = adapter
        self.model_cfg = deep_merge_dicts(default_model_config(), model_cfg or {})
        self.model_cfg.use_value_network = False
        self.model = Model(self.model_cfg)
        self._env_fn = env_fn or (lambda: MockEnv(seed=self.cfg.seed))
        self._init_params = init_params
        self._player_params = dict(player_params or {})
        self._rng = np.random.default_rng(self.cfg.seed)
        # ONE plane per actor, surviving across jobs: shared engines (and
        # their compilations) persist; inline stays per-job by construction.
        # Unsized shared engines default to BOTH sides of a job reserving
        # env_num sessions each (a self-play job puts two clients of the
        # SAME player on one gateway; exact-capacity admission would
        # otherwise fail the second side's reserve at job start)
        pcfg = dict(self.cfg.get("plane", {}) or {})
        if not pcfg.get("slots"):
            pcfg["slots"] = 2 * self.cfg.env_num
        self.plane = RolloutPlane(model=self.model, **pcfg)
        self._replay_client = None  # lazily dialed from cfg.actor.replay
        rcfg = self.cfg.get("replay", {}) or {}
        if rcfg.get("enabled", False) and rcfg.get("addr", ""):
            # fail fast on a malformed address here, at config time — not
            # mid-episode at the first push (docs/data_plane.md store path)
            self._replay_target()
        self.results: List[dict] = []
        # highest learner iteration ever received per player — survives
        # across jobs (the per-job _model_iters resets), for freshness
        # monitoring/telemetry
        self.model_iter_highwater: Dict[str, int] = {}

    # ---------------------------------------------------------------- params
    def _initial_params(self):
        if self._init_params is not None:
            return self._init_params
        from ..lib import features as F
        import jax.numpy as jnp

        obs = F.batch_tree([F.fake_step_data(train=False, rng=self._rng)])
        obs = jax.tree.map(jnp.asarray, obs)
        H = self.model_cfg.encoder.core_lstm.hidden_size
        hidden = tuple(
            (jnp.zeros((1, H)), jnp.zeros((1, H)))
            for _ in range(self.model_cfg.encoder.core_lstm.num_layers)
        )

        def init_fn(rng, o, h, k):
            return self.model.init(
                rng, o["spatial_info"], o["entity_info"], o["scalar_info"], o["entity_num"],
                h, k, method=self.model.sample_action,
            )

        self._init_params = jax.jit(init_fn)(
            jax.random.PRNGKey(self.cfg.seed), obs, hidden, jax.random.PRNGKey(1)
        )
        return self._init_params

    def _load_player_params(self, player_id: str):
        """Fresh weights from the learner when published, else initial."""
        if player_id in self._player_params:
            return self._player_params[player_id]
        if self.adapter is not None:
            data = self._pull_latest_model(player_id)
            if data is not None:
                self._note_model_iter(player_id, data.get("iter", 0))
                return jax.tree.map(np.asarray, data["params"])
        return self._initial_params()

    def _note_model_iter(self, player_id: str, it: int) -> None:
        self._model_iters[player_id] = it
        self.model_iter_highwater[player_id] = max(
            self.model_iter_highwater.get(player_id, 0), it
        )
        get_registry().gauge(
            "distar_actor_model_iter", "freshest learner iteration received",
            player=player_id,
        ).set(self.model_iter_highwater[player_id])

    def _sample_z(
        self,
        side: int,
        job: dict,
        born_location: Optional[int] = None,
        map_name: Optional[str] = None,
    ) -> dict:
        """Target strategy for one side: the job's z_path library keyed by
        map/matchup/born-location (reference agent.py:176-243), synthetic
        fallback when no library resolves (e.g. before gen_z has produced
        one). With a ``born_location`` (known once the episode's first obs
        arrives) the exact library key is used; otherwise a random one."""
        z_paths = job.get("z_path", [])
        path = z_paths[side] if side < len(z_paths) else ""
        lib = None
        if path and path != "none":
            if not hasattr(self, "_z_libs"):
                self._z_libs = {}
            if path not in self._z_libs:
                from ..lib.z_library import ZLibrary

                resolved = None
                pkg_z_dir = os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "data", "z_libraries",
                )
                for d in list(self.cfg.get("z_dirs", [""])) + [pkg_z_dir]:
                    cand = os.path.join(d, path) if d else path
                    if os.path.exists(cand):
                        resolved = cand
                        break
                try:
                    self._z_libs[path] = ZLibrary(resolved) if resolved else None
                except Exception as e:
                    logging.warning(f"actor: failed to load z library {path}: {e!r}")
                    self._z_libs[path] = None
            lib = self._z_libs[path]
        if lib is not None:
            from ..league.player import FRAC_ID

            frac_ids = job.get("frac_ids", [1, 1])

            def race_of(s):
                frac = frac_ids[s] if s < len(frac_ids) else 1
                return FRAC_ID.get(frac, ["zerg"])[0]

            race, opp_race = race_of(side), race_of(1 - side)
            # library keys follow the decoder's matchup convention: own race
            # for mirrors, race+opponent otherwise (gen_z, decode_z)
            mix_race = race if race == opp_race else race + opp_race
            fr_prob = float(self.cfg.get("fake_reward_prob", 1.0))
            resolved_map = map_name or job.get("env_info", {}).get("map_name", "")
            if born_location is not None:
                try:
                    return lib.sample(resolved_map, mix_race, int(born_location), fr_prob)
                except (KeyError, TypeError, IndexError):
                    pass  # library has no entries for this exact spawn
            target = lib.sample_any(
                resolved_map, mix_race=mix_race, fake_reward_prob=fr_prob,
            )
            if target is not None:
                return target
        return sample_fake_z(self._rng)

    def _load_teacher_params(self, side: int, job: dict, own_params):
        """Frozen teacher weights for the human-prior KL (reference
        actor_comm.py:114-118: teacher = separate SL checkpoint with value
        nets stripped). Falls back to a frozen snapshot of the player's own
        initial weights — logged loudly, since a self-teacher makes the
        kl/action_type_kl terms near-vacuous."""
        tids = job.get("teacher_player_ids", [])
        tpaths = job.get("teacher_checkpoint_paths", [])
        tid = tids[side] if side < len(tids) else "none"
        tpath = str(tpaths[side]) if side < len(tpaths) else "none"
        if tid != "none" and tpath not in ("none", "") and os.path.exists(tpath):
            try:
                from ..utils.checkpoint import load_checkpoint

                state = load_checkpoint(tpath, target={"params": own_params})["state"]
                return state["params"]
            except Exception as e:
                logging.warning(
                    f"actor: failed to load teacher checkpoint {tpath} for side {side}: {e!r}"
                )
        logging.warning(
            f"actor: no teacher checkpoint for side {side} "
            f"(teacher_id={tid!r}, path={tpath!r}); freezing the player's initial "
            "weights as teacher — KL terms will be weak until a real SL teacher is wired"
        )
        return own_params

    def _pull_latest_model(self, player_id: str):
        """Drain the FIFO plane to the freshest publication (non-blocking).
        reset_flag ORs across everything drained — exactly one publication
        carries it and it must not be lost to a newer one."""
        if self.adapter is None:  # adapterless actor (play/eval/tests): no
            return None           # publication plane to drain
        latest, reset_seen = None, False
        while True:
            data = self.adapter.pull(f"{player_id}model", block=False)
            if data is None:
                if latest is not None and reset_seen:
                    latest = dict(latest, reset_flag=True)
                return latest
            reset_seen = reset_seen or bool(data.get("reset_flag", False))
            if latest is None or data.get("iter", 0) >= latest.get("iter", 0):
                latest = data

    def _refresh_models(self, job, player_ids, clients, params) -> bool:
        """Periodic weight hot-reload for update_players (the
        freshness-critical path, reference actor_comm.py:172-216: actors pull
        every ~10s; a learner-sent reset_flag additionally restarts
        episodes). On the gateway backends the refresh is ONE registry
        hot-swap per (player, iteration) on the plane — deduped, applied at
        a flush boundary, shared by every client — instead of per-actor
        param installs. Returns True when a reset was requested."""
        reset = False
        for side in list(clients):
            player = player_ids[side]
            if player not in job.get("update_players", []):
                continue
            data = self._pull_latest_model(player)
            if data is not None and data.get("iter", -1) > self._model_iters.get(player, -1):
                new_params = jax.tree.map(np.asarray, data["params"])
                params[player] = new_params
                clients[side].refresh(new_params, data.get("iter", 0))
                self._note_model_iter(player, data.get("iter", 0))
                reset = reset or bool(data.get("reset_flag", False))
        return reset

    # ------------------------------------------------------------------- run
    def run_job(
        self, episodes: Optional[int] = None, job: Optional[dict] = None
    ) -> List[dict]:
        """Ask for one job and play it out; returns per-episode results.

        An explicit ``job`` dict overrides asking the league — play/eval use
        this to pin matchups (reference job_type eval_test, play.py)."""
        episodes = episodes or self.cfg.episodes_per_job
        if job is None:
            job = (
                self.league.actor_ask_for_job({"job_type": "train"})
                if self.league is not None
                else {
                    "player_ids": ["MP0", "HP0"],
                    "send_data_players": ["MP0"],
                    "update_players": ["MP0"],
                    "teacher_player_ids": ["T", "none"],
                    "branch": "standalone",
                    "env_info": {"map_name": "mock"},
                }
            )
        self._model_iters: Dict[str, int] = {}
        player_ids = job["player_ids"][:2]
        n_env = self.cfg.env_num
        # each env steps in its own worker thread (real SC2 steps are slow
        # and high-variance); inference batches over the ready set
        from .env_pool import RESET, EnvWorkerPool
        from .. import plugins

        pool = EnvWorkerPool([self._env_fn] * n_env)

        # model-free sides act without the batched inference: no slot, no
        # teacher, no trajectories. That's scripted built-ins
        # ('scripted.random') AND custom plugin pipelines, which own their
        # inference (plugins.py; role of the reference's importable agent
        # pipelines, distar/agent/import_helper.py + pysc2/agents/)
        pipelines = job.get("pipelines", [])

        def _pipeline(side: int) -> str:
            return pipelines[side] if side < len(pipelines) else "default"

        modelfree_sides = {
            side for side in range(len(player_ids))
            if plugins.is_model_free(_pipeline(side))
        }

        # slots: (env, side); one PolicyClient per model-driven side. The
        # plane decides where the model actually lives: a private
        # BatchedInference (inline), this host's shared gateway (local), or
        # a remote bin/serve gateway (remote) — LSTM carries, teacher state
        # and weight refresh all follow the backend (docs/serving.md)
        params = {
            pid: self._load_player_params(pid)
            for side, pid in enumerate(player_ids)
            if side not in modelfree_sides
        }
        teacher_params = {
            side: self._load_teacher_params(side, job, params[pid])
            for side, pid in enumerate(player_ids)
            if side not in modelfree_sides
        }
        clients = {
            side: self.plane.client_for(
                pid, num_slots=n_env, params=params[pid],
                teacher_params=teacher_params[side], seed=side,
            )
            for side, pid in enumerate(player_ids)
            if side not in modelfree_sides
        }
        from ..league.player import FRAC_ID as _FRAC_ID

        _frac_ids = job.get("frac_ids", [])

        def _side_race(side: int) -> str:
            frac = _frac_ids[side] if side < len(_frac_ids) else 1
            return _FRAC_ID.get(frac, ["zerg"])[0]

        agents = {
            (e, side): (
                plugins.build_agent(
                    _pipeline(side), pid,
                    seed=self.cfg.seed + e * 2 + side, race=_side_race(side),
                )
                if side in modelfree_sides
                else Agent(
                    pid,
                    z=self._sample_z(side, job),
                    traj_len=self.cfg.traj_len,
                    seed=self.cfg.seed + e * 2 + side,
                    max_entities=self.cfg.get("max_entities"),
                )
            )
            for e in range(n_env)
            for side, pid in enumerate(player_ids)
        }
        for (e, side), ag in agents.items():
            ag.model_last_iter = self._model_iters.get(ag.player_id, 0)
            ag.collect_trajectories = (
                side not in modelfree_sides
                and ag.player_id in job.get("send_data_players", [])
            )
        sides = list(range(len(player_ids)))
        hidden_backup = {
            (e, side): clients[side].hidden_for_slot(e)
            for e in range(n_env)
            for side in sides
            if side in clients
        }

        def reset_slot(e: int) -> None:
            """Restart env slot e: fresh episode, fresh Z, zeroed policy and
            teacher LSTM carries (shared by episode-end and league-reset).
            On the gateway backends the zeroing happens server-side — a
            session reset. The fresh obs arrives asynchronously via the
            pool."""
            for side in sides:
                if side in modelfree_sides:
                    agents[(e, side)].reset()
                    continue
                agents[(e, side)].reset(z=self._sample_z(side, job))
                clients[side].reset_slot(e)
                hidden_backup[(e, side)] = clients[side].hidden_for_slot(e)
            pool.reset(e)

        def handle_episode_end(e: int, next_obs, rewards, info) -> None:
            """Close out every side's pending action with the terminal
            reward, report the result, restart the slot."""
            nonlocal episodes_done
            for side in sides:
                ag = agents[(e, side)]
                if ag._output is not None and (e, side) in pending_teacher:
                    traj = ag.collect_data(
                        next_obs.get(side), rewards[side], True,
                        pending_teacher.pop((e, side)),
                        hidden_backup[(e, side)],
                    )
                    self._maybe_push(job, ag, traj, clients, hidden_backup, e, side)
            episodes_done += 1
            result = {
                "game_steps": info.get("game_loop", 0),
                "game_iters": 0,
                "game_duration": 0.0,
            }
            from ..league.player import FRAC_ID

            frac_ids = job.get("frac_ids", [1, 1])
            for side in sides:
                ag = agents[(e, side)]
                frac = frac_ids[side] if side < len(frac_ids) else 1
                opponent = (
                    player_ids[1 - side] if 1 - side < len(player_ids) else
                    job.get("opponent_id", "bot")
                )
                result[str(side)] = {
                    "player_id": player_ids[side],
                    "opponent_id": opponent,
                    "winloss": int(rewards[side]),
                    "race": FRAC_ID.get(frac, ["zerg"])[0],
                    **ag.episode_stats(),
                }
            results.append(result)
            if self.league is not None:
                from ..resilience import CommError

                try:
                    self.league.actor_send_result(result)
                except CommError as e:
                    # result delivery already retried inside RemoteLeague;
                    # losing one matchmaking sample must not kill the job
                    # loop mid-episode — log and keep rolling
                    logging.warning(f"actor: result delivery dropped: {e}")
                    get_registry().counter(
                        "distar_actor_result_send_failures_total",
                        "league result deliveries dropped after retries",
                    ).inc()
            reset_slot(e)

        for e in range(n_env):
            pool.reset(e)
        # neutral schema-complete filler for slots that haven't produced an
        # observation yet (inactive batch positions are never consumed)
        from ..lib import features as F

        filler = F.fake_step_data(train=False, rng=self._rng)
        cap = self.cfg.get("max_entities")
        if cap:
            # capped lanes batch at the bucket shape: the filler must match
            filler["entity_info"] = {
                k: v[:cap] for k, v in filler["entity_info"].items()
            }
            filler["entity_num"] = np.minimum(np.asarray(filler["entity_num"]), cap)
        obs: Dict[int, dict] = {}
        episodes_done, results = 0, []
        last_model_refresh = time.time()
        pending_teacher: Dict = {}
        last_prepared: Dict = {}
        try:
            while episodes_done < episodes:
                if time.time() - last_model_refresh > self.cfg.model_update_interval_s:
                    last_model_refresh = time.time()
                    refreshed = self._refresh_models(job, player_ids, clients, params)
                    for ag in agents.values():
                        ag.model_last_iter = self._model_iters.get(ag.player_id, 0)
                    if refreshed:
                        # league-triggered reset: restart every episode with
                        # the fresh checkpoint (reference actor.py:321-323);
                        # in-flight steps are dropped by the epoch tags
                        pending_teacher.clear()
                        obs.clear()
                        for e in range(n_env):
                            reset_slot(e)
                # collect whatever envs finished stepping (>=1, with a cap so
                # the model-refresh clock keeps ticking)
                for e, kind, payload in pool.ready(timeout=1.0):
                    if kind == RESET:
                        obs[e] = payload
                        # the first obs reveals the spawn: re-key Z to the
                        # exact map/matchup/born-location library entry
                        # (reference agent.reset, agent.py:176-243)
                        for side in sides:
                            gi = (payload.get(side) or {}).get("game_info", {})
                            born = gi.get("born_location")
                            if born is not None:
                                agents[(e, side)].reset(z=self._sample_z(
                                    side, job, born_location=born,
                                    map_name=gi.get("map_name"),
                                ))
                    else:
                        next_obs, rewards, done, info = payload
                        if done:
                            handle_episode_end(e, next_obs, rewards, info)
                        else:
                            obs[e] = next_obs
                if not obs:
                    continue

                # obs[e] holds only the sides DUE this cycle (variable
                # per-agent delays, SC2Env contract); a fresh obs first
                # closes out that agent's previous action (collect-on-
                # receipt, the reference's per-env loop order), then the
                # agent acts on it. Non-ready slots ride the batch as
                # inactive filler (hidden state preserved).
                env_actions: Dict[int, dict] = {e: {} for e in range(n_env)}
                for side, pid in enumerate(player_ids):
                    if side in modelfree_sides:
                        for e in range(n_env):
                            if e in obs and side in obs[e]:
                                env_actions[e][side] = agents[(e, side)].step(obs[e][side])
                        continue
                    prepared, active = [], []
                    for e in range(n_env):
                        if e in obs and side in obs[e]:
                            ag = agents[(e, side)]
                            if ag._output is not None and (e, side) in pending_teacher:
                                traj = ag.collect_data(
                                    obs[e][side], 0.0, False,
                                    pending_teacher.pop((e, side)),
                                    hidden_backup[(e, side)],
                                )
                                self._maybe_push(job, ag, traj, clients, hidden_backup, e, side)
                            prepared.append(ag.pre_process(obs[e][side]))
                            last_prepared[(e, side)] = prepared[-1]
                            active.append(True)
                        else:
                            prepared.append(last_prepared.get((e, side), filler))
                            active.append(False)
                    if not any(active):
                        # no lane of this side is due: skip both forwards
                        # (hidden state untouched for inactive lanes anyway)
                        continue
                    outs = clients[side].sample(prepared, active)
                    # teacher logits at act time with the FROZEN teacher
                    # weights, stored until the next obs arrives (on the
                    # gateway backends these rode the SAME flush as the
                    # policy forward — no second round-trip)
                    t_logits = clients[side].teacher_logits(prepared, outs, active)
                    for e in range(n_env):
                        if active[e]:
                            act = agents[(e, side)].post_process(outs[e])
                            act["selected_units_num"] = outs[e]["selected_units_num"]
                            env_actions[e][side] = act
                            pending_teacher[(e, side)] = t_logits[e]

                # hand the acted-on envs back to their workers
                for e in list(obs.keys()):
                    if env_actions[e]:
                        pool.submit(e, env_actions[e])
                        del obs[e]
        finally:
            pool.close()
            for c in clients.values():
                c.close()  # frees the job's sessions on shared gateways
        self.results.extend(results)
        return results

    # ----------------------------------------------------------- replay push
    def _replay_cfg(self):
        return self.cfg.get("replay", {}) or {}

    def _replay_target(self):
        """Validated target spec from ``cfg.actor.replay.addr``: the string
        ``"inproc"`` (colocated store), or a list of ``(host, port)`` pairs
        (one = single store, several = consistent-hash shard fleet). Raises
        a clear config error instead of a bare ``int()`` ValueError."""
        from ..replay import is_inproc_addr

        addr = str(self._replay_cfg().get("addr", ""))
        if is_inproc_addr(addr):
            return addr
        targets = []
        for part in addr.split(","):
            part = part.strip()
            if not part:
                continue
            host, _, port = part.rpartition(":")
            try:
                targets.append((host or "127.0.0.1", int(port)))
            except ValueError:
                raise ValueError(
                    f"actor.replay.addr must be 'host:port' (optionally "
                    f"comma-separated for a shard fleet) or 'inproc', "
                    f"got {addr!r}"
                ) from None
        if not targets:
            raise ValueError(
                f"actor.replay.addr must name at least one 'host:port', "
                f"got {addr!r}"
            )
        return targets

    def _get_replay_client(self):
        """Dial the replay plane once per actor (clients reconnect + retry
        internally; docs/data_plane.md store path): the in-process store
        handle for ``inproc`` (zero serialization), one ``InsertClient``
        for a single store, or a ``ShardedInsertClient`` routing across
        the fleet by consistent hash."""
        if self._replay_client is None:
            target = self._replay_target()
            compress = bool(self._replay_cfg().get("compress", True))
            transport = str(self._replay_cfg().get("transport", "auto"))
            if isinstance(target, str):  # inproc fast path
                from ..replay import LocalReplayClient

                self._replay_client = LocalReplayClient()
            elif len(target) == 1:
                from ..replay import InsertClient

                self._replay_client = InsertClient(*target[0], compress=compress,
                                                   transport=transport)
            else:
                from ..replay import ShardMap, ShardedInsertClient

                self._replay_client = ShardedInsertClient(
                    ShardMap([f"{h}:{p}" for h, p in target]), compress=compress,
                    transport=transport)
        return self._replay_client

    def push_trajectory(self, player_id: str, traj) -> None:
        """Ship one finished trajectory to the configured data plane(s):
        the replay store when ``actor.replay.enabled``, the legacy shuttle
        path otherwise (or additionally, with ``replay.mirror``)."""
        rcfg = self._replay_cfg()
        use_replay = bool(rcfg.get("enabled", False)) and rcfg.get("addr", "")
        if use_replay:
            try:
                # inside the try: client construction failing (config rot
                # after init) must count as a dropped push, not kill the
                # job loop mid-episode
                client = self._get_replay_client()
                client.insert(
                    player_id, traj,
                    priority=float(rcfg.get("priority", 1.0)),
                    timeout_s=float(rcfg.get("timeout_s", 60.0)),
                )
                get_registry().counter(
                    "distar_actor_replay_pushed_total",
                    "trajectories acked by the replay store", player=player_id,
                ).inc()
            except Exception as err:
                # the client already retried under its policy/breaker; a
                # store outage past that budget must not kill the job loop
                # mid-episode (the trajectory is lost, counted, and the
                # episode keeps rolling — exactly the legacy drop semantics)
                logging.warning(f"actor: replay push dropped: {err!r}")
                get_registry().counter(
                    "distar_actor_replay_push_failures_total",
                    "replay-store inserts dropped after retries",
                    player=player_id,
                ).inc()
            if not rcfg.get("mirror", False):
                return
        if self.adapter is not None:
            # mint the pipeline span here, where the trajectory is born: the
            # context rides the payload through shuttle/adapter into the
            # learner, and the span id is ALSO stamped into the trajectory
            # itself so the learner can attribute per-trajectory staleness
            trace = start_trace("trajectory", player=player_id)
            traj[0]["trace"] = trace
            get_registry().counter(
                "distar_actor_traj_pushed_total", "trajectories shipped to the learner",
                player=player_id,
            ).inc()
            self.adapter.push(
                f"{player_id}traj", traj, timeout_ms=120_000, trace=trace
            )

    def _maybe_push(self, job, ag, traj, clients, hidden_backup, e, side) -> None:
        if traj is None:
            return
        # next trajectory starts from the CURRENT carry (before this cycle's
        # forward) — read back from wherever the plane keeps it
        hidden_backup[(e, side)] = clients[side].hidden_for_slot(e)
        if ag.player_id in job["send_data_players"]:
            self.push_trajectory(ag.player_id, traj)
