from .actor import Actor, ACTOR_DEFAULTS
from .agent import Agent, sample_fake_z, time_decay_factor
from .inference import BatchedInference, decollate
from .rollout_plane import (
    GatewayPolicyClient,
    InlinePolicyClient,
    PolicyClient,
    RolloutPlane,
)

__all__ = [
    "Actor",
    "ACTOR_DEFAULTS",
    "Agent",
    "sample_fake_z",
    "time_decay_factor",
    "BatchedInference",
    "decollate",
    "GatewayPolicyClient",
    "InlinePolicyClient",
    "PolicyClient",
    "RolloutPlane",
]
