from .actor import Actor, ACTOR_DEFAULTS
from .agent import Agent, sample_fake_z, time_decay_factor
from .inference import BatchedInference, decollate

__all__ = [
    "Actor",
    "ACTOR_DEFAULTS",
    "Agent",
    "sample_fake_z",
    "time_decay_factor",
    "BatchedInference",
    "decollate",
]
