"""Rollout inference plane: one batched policy server for the actor fleet.

The Sebulba split (PAPERS.md [Podracer]): dedicate inference to a single
batched server and make actors thin env drivers, instead of every Actor
process instantiating its own ``BatchedInference`` — N model replicas, N
compilations, N per-step Python dispatch loops (the DI-star reference's
``gpu_batch_inference`` centralisation, re-decentralised by our per-actor
port until this module). Everything here composes serve-plane machinery
that already exists: the deadline-aware ``MicroBatcher``, ``SessionTable``
sticky LSTM carries, the hot-swap ``ModelRegistry`` and the framed-TCP
frontend.

``PolicyClient`` is the surface the actor's job loop speaks — batched
``sample`` + ``teacher_logits`` over its env slots, per-slot carry
reset/readback, weight ``refresh`` — with three backends behind
``RolloutPlane.client_for``:

  * ``inline`` — today's per-actor ``BatchedInference`` engine, private to
    the client (default; zero behaviour change).
  * ``local``  — ONE shared in-process ``InferenceGateway`` per player on
    this host: every actor thread/job's slots become sticky sessions whose
    LSTM carries live in the shared engine, and all their cycles coalesce
    in the micro-batcher into one fixed-shape flush. One engine, one
    compilation, one registry to hot-swap.
  * ``remote`` — framed-TCP ``ServeClient`` against a ``bin/serve.py``
    gateway, riding the resilience retry/reconnect policies through
    gateway restarts (a restart re-materializes carries from zero —
    counted in ``distar_actor_carry_resets_total``).

Episode resets map to session resets (carry zeroing, server-side), teacher
logits piggyback on the same flush (``want_teacher``), and model refresh is
a single registry hot-swap per host instead of per-actor polling.
Session-per-slot admission is EXACT capacity: clients ``reserve`` every
slot's session at creation and fail fast with a typed ``CapacityError``
instead of shedding mid-episode.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional

from ..obs import get_registry
from ..serve.errors import ServeError, ShedError

_CLIENT_SEQ = itertools.count()

PLANE_BACKENDS = ("inline", "local", "remote")


def _default_engine_factory(player_id: str, num_slots: int, params,
                            teacher_params, model, seed: int):
    """Real-model engine: the actor fleet's compiled ``sample_action``."""
    from ..serve.engine import BatchedInferenceEngine
    from .inference import BatchedInference

    if model is None:
        raise ValueError("rollout plane: a Model is required to build the "
                         "default engine (pass model= or engine_factory=)")
    return BatchedInferenceEngine(BatchedInference(
        model, params, num_slots, seed=seed, teacher_params=teacher_params,
    ))


class PolicyClient:
    """One job-side handle onto the plane: ``num_slots`` env lanes of one
    player's policy (+ optional frozen teacher). Lifetime = one job."""

    num_slots: int
    backend: str

    def sample(self, prepared: List[dict], active: Optional[List[bool]] = None
               ) -> List[Optional[dict]]:
        """One fleet cycle: per-slot outputs for active lanes (inactive
        entries are unspecified and must not be consumed)."""
        raise NotImplementedError

    def teacher_logits(self, prepared: List[dict], outputs: List[dict],
                       active: Optional[List[bool]] = None
                       ) -> List[Optional[dict]]:
        """Teacher-forced logits for the cycle just sampled (same
        ``active`` mask). ``None`` entries when no teacher is installed."""
        raise NotImplementedError

    def reset_slot(self, idx: int) -> None:
        raise NotImplementedError

    def hidden_for_slot(self, idx: int):
        raise NotImplementedError

    def refresh(self, params, iteration: int = 0) -> None:
        """Install freshly published weights (hot swap, never a recompile)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class InlinePolicyClient(PolicyClient):
    """Private engine per client — the legacy per-actor replica, kept as
    the default backend (and the baseline the bench compares against)."""

    backend = "inline"

    def __init__(self, engine, player_id: str = ""):
        self.engine = engine
        self.player_id = player_id
        self.num_slots = engine.num_slots
        reg = get_registry()
        self._c_samples = reg.counter(
            "distar_rollout_samples_total", "slot-steps sampled through the plane",
            backend=self.backend,
        )
        self._h_cycle = reg.histogram(
            "distar_rollout_sample_seconds", "plane round-trip per fleet cycle",
            backend=self.backend,
        )

    def sample(self, prepared, active=None):
        t0 = time.perf_counter()
        outs = self.engine.forward(
            prepared, [True] * self.num_slots if active is None else active
        )
        self._h_cycle.observe(time.perf_counter() - t0)
        self._c_samples.inc(
            self.num_slots if active is None else sum(bool(a) for a in active)
        )
        return outs

    def teacher_logits(self, prepared, outputs, active=None):
        if not getattr(self.engine, "has_teacher", False):
            return [None] * self.num_slots
        return self.engine.teacher_forward(
            prepared, outputs, [True] * self.num_slots if active is None else active
        )

    def reset_slot(self, idx: int) -> None:
        self.engine.reset_slot(idx)

    def hidden_for_slot(self, idx: int):
        return self.engine.hidden_for_slot(idx)

    def refresh(self, params, iteration: int = 0) -> None:
        self.engine.set_params(params)


class _LocalTarget:
    """In-process adapter giving ``GatewayPolicyClient`` the same surface
    ``ServeClient`` speaks, minus the wire."""

    def __init__(self, gateway):
        self._gw = gateway

    def act_many(self, requests, timeout_s=None):
        return self._gw.act_many(requests, timeout_s)

    def reserve(self, session_ids):
        return self._gw.reserve_sessions(session_ids)

    def hidden(self, session_id):
        return self._gw.session_hidden(session_id)

    def set_teacher(self, params):
        return self._gw.set_teacher(params)

    def reset(self, session_id):
        return self._gw.reset_session(session_id)

    def end(self, session_id):
        return self._gw.end_session(session_id)

    def load(self, version, source=None, params=None, activate=False):
        return self._gw.load_version(version, source=source, params=params,
                                     activate=activate)

    def close(self):
        pass


class GatewayPolicyClient(PolicyClient):
    """Slots-as-sessions client over a gateway target (in-process or TCP).

    Each env slot pins one sticky session whose LSTM carry — policy and
    teacher — lives server-side in the shared engine. A cycle is ONE
    ``act_many`` call (teacher logits piggyback via ``want_teacher``);
    per-lane sheds are retried individually within the cycle's timeout so a
    transient queue-full never re-executes lanes that already advanced
    their carry. ``session_step`` answers are monotonic per episode; when
    the counter runs backwards the server-side carry was re-materialized
    from zero (gateway restart, eviction) — counted per player in
    ``distar_actor_carry_resets_total`` so re-materialization cost is
    visible, and the episode keeps rolling on the fresh carry."""

    def __init__(self, target, session_ids: List[str], player_id: str = "",
                 backend: str = "local", want_teacher: bool = False,
                 timeout_s: float = 30.0, reserve: bool = True):
        self.target = target
        self.session_ids = list(session_ids)
        self.player_id = player_id
        self.backend = backend
        self.num_slots = len(session_ids)
        self.want_teacher = want_teacher
        self.timeout_s = timeout_s
        self._expected_step = [0] * self.num_slots
        self._last_teacher: List[Optional[dict]] = [None] * self.num_slots
        self._refresh_cb = None  # plane-level registry swap, set by client_for
        reg = get_registry()
        self._c_samples = reg.counter(
            "distar_rollout_samples_total", "slot-steps sampled through the plane",
            backend=backend,
        )
        self._h_cycle = reg.histogram(
            "distar_rollout_sample_seconds", "plane round-trip per fleet cycle",
            backend=backend,
        )
        self._c_shed = reg.counter(
            "distar_rollout_shed_total", "plane sheds seen by actors (retried client-side)",
            backend=backend,
        )
        self._c_carry_resets = reg.counter(
            "distar_actor_carry_resets_total",
            "server-side LSTM carries re-materialized from zero",
            player=player_id or "?",
        )
        if reserve:
            # exact-capacity admission: every slot's session exists (and its
            # carry is zeroed) before the first env step, or we fail HERE
            # with a typed CapacityError — never a shed mid-episode
            self.target.reserve(self.session_ids)

    # ------------------------------------------------------------------ steps
    def _note_result(self, idx: int, out: dict) -> None:
        st = out.get("session_step")
        if st is None:
            return
        if st <= self._expected_step[idx]:
            # the server's episode-step counter ran backwards: our session
            # was re-created (restart/eviction) and the carry restarted
            # from zero mid-episode
            self._c_carry_resets.inc()
        self._expected_step[idx] = int(st)

    def sample(self, prepared, active=None):
        active = [True] * self.num_slots if active is None else active
        lanes = [i for i in range(self.num_slots) if active[i]]
        outs: List[Optional[dict]] = [None] * self.num_slots
        self._last_teacher = [None] * self.num_slots
        t0 = time.perf_counter()
        deadline = t0 + self.timeout_s
        while lanes:
            results = self.target.act_many(
                [{"session_id": self.session_ids[i], "obs": prepared[i],
                  "want_teacher": self.want_teacher} for i in lanes],
                timeout_s=self.timeout_s,
            )
            retry = []
            for i, res in zip(lanes, results):
                if isinstance(res, ShedError):
                    self._c_shed.inc()
                    if time.perf_counter() < deadline:
                        retry.append(i)  # only the shed lane re-executes
                        continue
                    raise res
                if isinstance(res, ServeError):
                    raise res
                outs[i] = res
                self._note_result(i, res)
                if self.want_teacher:
                    tl = res.get("teacher_logit")
                    if tl is None:
                        raise RuntimeError(
                            "rollout plane: teacher logits requested but the "
                            "gateway engine serves none (set_teacher failed?)"
                        )
                    self._last_teacher[i] = tl
            lanes = retry
            if lanes:
                time.sleep(0.02)
        self._h_cycle.observe(time.perf_counter() - t0)
        self._c_samples.inc(sum(bool(a) for a in active))
        return outs

    def teacher_logits(self, prepared, outputs, active=None):
        """Served from the cycle's own flush (``want_teacher`` piggyback) —
        no second round-trip."""
        if not self.want_teacher:
            return [None] * self.num_slots
        return list(self._last_teacher)

    def reset_slot(self, idx: int) -> None:
        self._expected_step[idx] = 0
        try:
            self.target.reset(self.session_ids[idx])
        except ServeError:
            pass  # unknown session (fresh gateway): next act allocs zeroed

    def hidden_for_slot(self, idx: int):
        return self.target.hidden(self.session_ids[idx])

    def refresh(self, params, iteration: int = 0) -> None:
        if self._refresh_cb is not None:
            self._refresh_cb(params, iteration)

    def close(self) -> None:
        for sid in self.session_ids:
            try:
                self.target.end(sid)
            except (ServeError, ConnectionError, OSError):
                pass
        self.target.close()


class RolloutPlane:
    """Per-host factory/owner of the rollout inference plane.

    One instance per Actor (created once, surviving across jobs — the
    shared engines and their compilations persist). ``client_for`` hands
    each job side a ``PolicyClient`` on the configured backend; ``local``
    gateways are lazily built per player and shared by every subsequent
    client; weight refresh dedupes by learner iteration so N clients cost
    one registry hot-swap."""

    def __init__(self, backend: str = "inline", addr: str = "",
                 slots: int = 0, max_delay_s: float = 0.005,
                 timeout_s: float = 30.0, queue_capacity: int = 1024,
                 idle_ttl_s: float = 300.0, model=None, engine_factory=None,
                 coordinator_addr: str = "", transport: str = "auto"):
        #: remote-backend transport preference (shm rings for colocated
        #: gateways — the Sebulba "never touch a socket on-host" leg)
        self.transport = str(transport or "auto")
        if backend not in PLANE_BACKENDS:
            raise ValueError(
                f"actor.plane.backend must be one of {PLANE_BACKENDS}, got {backend!r}"
            )
        self.backend = backend
        self.addr = str(addr)
        self.coordinator_addr = str(coordinator_addr)
        if backend == "remote" and not self._is_fleet_addr():
            self._remote_addr()  # fail fast on a malformed address
        if backend == "remote" and self.addr == "discover" and not self.coordinator_addr:
            raise ValueError(
                "actor.plane.addr 'discover' needs actor.plane.coordinator_addr "
                "(CLI: --plane-addr discover requires --coordinator-addr)")
        self.slots = int(slots)
        self.max_delay_s = max_delay_s
        self.timeout_s = timeout_s
        self.queue_capacity = queue_capacity
        self.idle_ttl_s = idle_ttl_s
        self._model = model
        self._engine_factory = engine_factory or _default_engine_factory
        self._gateways: Dict[str, object] = {}
        self._refresh_iters: Dict[str, int] = {}
        self._lock = threading.Lock()
        reg = get_registry()
        reg.gauge(
            "distar_rollout_plane_backend", "active rollout-plane backend (1 = active)",
            backend=backend,
        ).set(1)
        self._c_swaps = reg.counter(
            "distar_rollout_swaps_total", "registry hot-swaps driven by plane refresh",
        )

    # ------------------------------------------------------------------ utils
    def _is_fleet_addr(self) -> bool:
        """``discover`` (coordinator-discovered gateway fleet) and multi-
        address lists ride the session-affinity router (serve.fleet) instead
        of a single ``ServeClient`` — same surface, fleet semantics."""
        return self.addr == "discover" or "," in self.addr

    def _remote_addr(self):
        host, _, port = self.addr.rpartition(":")
        try:
            return host or "127.0.0.1", int(port)
        except ValueError:
            raise ValueError(
                f"actor.plane.addr must be 'host:port', a 'h1:p1,h2:p2' fleet "
                f"list, or 'discover' — got {self.addr!r}"
            ) from None

    def _remote_target(self, player_id: str):
        """The remote data-plane client for one job client: a plain
        ``ServeClient`` for a single gateway address, or a ``FleetClient``
        (consistent-hash session affinity, failover re-route, canary split)
        for ``discover``/multi-address fleets. Both are player-stamped so a
        multiplexed gateway (``GatewayMux``) serves several players over
        one address; single-model gateways ignore the field."""
        from ..resilience import RetryPolicy

        if self._is_fleet_addr():
            from ..serve.fleet import FleetClient, GatewayMap

            if self.addr == "discover":
                host, _, port = self.coordinator_addr.rpartition(":")
                return FleetClient(
                    coordinator_addr=(host or "127.0.0.1", int(port)),
                    timeout_s=self.timeout_s, player=player_id or None,
                    transport=self.transport)
            return FleetClient(gateway_map=GatewayMap.parse(self.addr),
                               timeout_s=self.timeout_s,
                               player=player_id or None,
                               transport=self.transport)
        from ..serve.tcp_frontend import ServeClient

        host, port = self._remote_addr()
        # patient reconnect budget: a gateway kill+restart (seconds of
        # dead port) must stay invisible to the job loop — the episode
        # rides through on re-materialized carries
        return ServeClient(
            host, port, timeout_s=self.timeout_s,
            player=player_id or None, transport=self.transport,
            retry_policy=RetryPolicy(
                max_attempts=10, backoff_base_s=0.2, backoff_max_s=2.0,
                deadline_s=max(4 * self.timeout_s, 30.0),
            ),
        )

    def _session_ids(self, player_id: str, num_slots: int) -> List[str]:
        uid = f"{os.getpid():x}-{next(_CLIENT_SEQ)}"
        return [f"{player_id}/{uid}/{j}" for j in range(num_slots)]

    # ---------------------------------------------------------------- clients
    def client_for(self, player_id: str, *, num_slots: int, params=None,
                   teacher_params=None, seed: int = 0, model=None) -> PolicyClient:
        model = model if model is not None else self._model
        if self.backend == "inline":
            engine = self._engine_factory(
                player_id=player_id, num_slots=num_slots, params=params,
                teacher_params=teacher_params, model=model, seed=seed,
            )
            return InlinePolicyClient(engine, player_id)
        if self.backend == "local":
            gw = self._local_gateway(player_id, num_slots, params, model, seed)
            target = _LocalTarget(gw)
        else:  # remote: single gateway, static fleet list, or discover
            target = self._remote_target(player_id)
        if teacher_params is not None:
            target.set_teacher(teacher_params)
        client = GatewayPolicyClient(
            target, self._session_ids(player_id, num_slots),
            player_id=player_id, backend=self.backend,
            want_teacher=teacher_params is not None, timeout_s=self.timeout_s,
        )
        client._refresh_cb = lambda p, it: self._install(player_id, target, p, it)
        return client

    def _local_gateway(self, player_id: str, num_slots: int, params, model,
                       seed: int):
        from ..serve.gateway import InferenceGateway

        with self._lock:
            gw = self._gateways.get(player_id)
            if gw is None:
                slots = self.slots or num_slots
                engine = self._engine_factory(
                    player_id=player_id, num_slots=slots, params=params,
                    teacher_params=None, model=model, seed=seed,
                )
                gw = InferenceGateway(
                    engine,
                    max_batch=slots,
                    max_delay_s=self.max_delay_s,
                    queue_capacity=self.queue_capacity,
                    idle_ttl_s=self.idle_ttl_s,
                    default_timeout_s=self.timeout_s,
                ).start()
                if params is not None:
                    gw.load_version(f"{player_id}@boot", params=params,
                                    activate=True)
                self._gateways[player_id] = gw
            return gw

    # ---------------------------------------------------------------- refresh
    def _install(self, player_id: str, target, params, iteration: int) -> None:
        """One registry hot-swap per (player, learner iteration) on this
        plane — N clients refreshing the same publication dedupe here."""
        with self._lock:
            if iteration <= self._refresh_iters.get(player_id, -1):
                return
            self._refresh_iters[player_id] = iteration
        target.load(f"{player_id}@{iteration}", params=params, activate=True)
        self._c_swaps.inc()

    # --------------------------------------------------------------- lifecycle
    def shutdown(self, timeout_s: float = 10.0) -> None:
        """Drain and stop every shared local gateway (tests/bench teardown;
        actors normally keep the plane alive for the process lifetime)."""
        with self._lock:
            gateways, self._gateways = dict(self._gateways), {}
        for gw in gateways.values():
            gw.drain_and_stop(timeout_s)
