"""Framed-TCP data plane for actor-grade serve callers.

Wire format = ``comm.serializer``: 8-byte big-endian length prefix around a
pickled (+compressed) payload — the same stack the actor fleet's shuttle
speaks, so obs trees with real numpy arrays round-trip losslessly and fast
(no JSON float inflation). One request/response pair per frame; a
connection is a session's natural home but nothing enforces it — the
``session_id`` field is authoritative, so a pool of connections can front
many sessions.

Requests are ``{"op": ..., ...}`` dicts:
  hello    {transports?, host?}            -> {code: 0, transport, shm_*?}
  act      {session_id, obs, timeout_s?, want_teacher?} -> {code: 0, outputs}
  act_many {requests: [{session_id, obs, want_teacher?}], timeout_s?}
                                           -> {code: 0, results: [entry]}
                                              entry = {ok: outputs} | wire error
  reserve  {session_ids: [...]}            -> {code: 0, slots: {sid: slot}}
  hidden   {session_id}                    -> {code: 0, hidden}
  set_teacher {params}                     -> {code: 0, ok: True}
  reset    {session_id}                    -> {code: 0, reset: bool}
  end      {session_id}                    -> {code: 0, ended: bool}
  load     {version, source|params, activate?} -> {code: 0, info}
  swap     {version}                       -> {code: 0, generation}
  status   {}                              -> {code: 0, status}
  drain    {}                              -> {code: 0, draining, resident}
  ping     {}                              -> {code: 0, pong: True}

``act_many`` is the rollout-plane cycle op: one frame carries a whole env
fleet's step, per-lane results (including per-lane typed sheds) come back
in one frame, and different actors' cycles coalesce in the server's
micro-batcher.

``hello`` is the transport negotiation (``comm.shm_ring``): a client
advertising ``transports: [shm, tcp]`` from the same host gets a
shared-memory ring pair minted and its data frames — whole ``act_many``
cycles included — move over the rings with the socket as control channel
and fallback leg (the Podracer/Sebulba colocation recipe: actors and
inference on one host never touch a socket). Garbage preference lists are
NACK'd with the typed ``bad_hello`` code; legacy clients never say hello
and keep the pre-shm wire exactly.

Every request may carry an optional ``player`` field: multiplexed servers
(``serve.mux.GatewayMux`` — one address, several player models) resolve it
to the right model; single-model servers ignore it; absent means the
server's default player — so legacy single-model clients keep working
unchanged against both server generations.

Serve errors answer ``{code: <wire code>, error, shed}`` (errors.to_wire);
the client rehydrates them into the typed exceptions.
"""
from __future__ import annotations

import socket
import threading
from typing import Optional

from ..comm import shm_ring
from ..comm.serializer import recv_msg, send_msg
from ..obs import (
    finish_trace,
    get_registry,
    is_trace,
    set_active_trace,
    start_trace,
    tracing_enabled,
    wire_ctx,
)
from ..resilience import RetryPolicy, retry_call
from .errors import BadFrameError, BadRequestError, ServeError, error_from_wire


class ServeTCPServer:
    def __init__(self, gateway, host: str = "127.0.0.1", port: int = 0,
                 transport: str = "auto",
                 ring_bytes: int = shm_ring.DEFAULT_RING_BYTES):
        self.gateway = gateway
        if transport not in ("auto", "shm", "tcp"):
            raise ValueError(f"transport must be auto|shm|tcp, got {transport!r}")
        self.transport = transport
        self.ring_bytes = int(ring_bytes)
        self._transports = {"tcp": 0, "shm": 0}
        self._transports_lock = threading.Lock()
        # let gateway.status() (the opsctl serving digest's feed) report
        # the live per-connection transport split without a frontend import
        try:
            gateway._tcp_transports = self.transport_counts
        except AttributeError:
            pass
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._ring_services: set = set()
        self._conns_lock = threading.Lock()
        reg = get_registry()
        self._g_conns = reg.gauge(
            "distar_serve_tcp_connections", "open data-plane connections"
        )
        self._c_frames = reg.counter(
            "distar_serve_tcp_frames_total", "request frames handled"
        )

    def start(self) -> "ServeTCPServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="serve-tcp-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: closing the fd from this thread does not
            # wake an accept() blocked in another — the kernel socket (and
            # the port) would live until a final connection arrived
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        # close live connections too: their handler threads otherwise sit in
        # recv until every peer goes away, pinning the port past stop()
        with self._conns_lock:
            conns = list(self._conns)
            rings = list(self._ring_services)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        # sever the shm leg SYNCHRONOUSLY: a closed socket does not stop a
        # ring pump, and a stopped gateway must not keep answering data
        # frames out of shared memory (the in-process kill-drill contract)
        for svc in rings:
            svc.stop()
        t = self._accept_thread
        if t is not None:
            t.join(5.0)
            self._accept_thread = None

    # ------------------------------------------------------------------ loop
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:  # listener closed by stop()
                return
            # REUSEADDR on accepted sockets too: after stop(), lingering
            # FIN_WAIT conns must not block a restarted gateway from
            # rebinding the same port (the crash-restart path)
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            threading.Thread(
                target=self._serve_conn, args=(conn,), name="serve-tcp-conn", daemon=True
            ).start()

    def _count_transport(self, kind: str, delta: int) -> None:
        with self._transports_lock:
            self._transports[kind] = max(0, self._transports[kind] + delta)

    def transport_counts(self) -> dict:
        with self._transports_lock:
            return dict(self._transports)

    def _handle_hello(self, req: dict, have_rings: bool) -> "tuple[dict, object]":
        """Negotiate one connection's transport. Returns (reply, peer) —
        ``peer`` is the server ring endpoint when shm was agreed."""
        nack = shm_ring.hello_nack(req)
        if nack:
            return {"code": "bad_hello", "error": nack, "shed": False}, None
        reply = {"code": 0, "transport": "tcp"}
        if have_rings:  # one ring pair per connection, ever
            return reply, None
        extra, peer = shm_ring.negotiate_server(
            req, self.transport, self.ring_bytes, op="serve")
        reply.update(extra)
        return reply, peer

    def _serve_conn(self, conn: socket.socket) -> None:
        self._g_conns.inc()
        with self._conns_lock:
            self._conns.add(conn)
        ring_svc = None
        self._count_transport("tcp", +1)
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        req = recv_msg(conn)
                    except (ConnectionError, OSError):
                        return  # peer closed (possibly mid-frame)
                    except ValueError as e:
                        # garbage frame header/codec: answer typed, then
                        # close — the stream can no longer be trusted
                        send_msg(conn, BadFrameError(repr(e)).to_wire())
                        return
                    self._c_frames.inc()
                    if isinstance(req, dict) and req.get("op") == "hello":
                        reply, peer = self._handle_hello(req, ring_svc is not None)
                        if peer is not None:
                            ring_svc = shm_ring.RingService(
                                peer, self._dispatch, name="serve-shm-ring").start()
                            with self._conns_lock:
                                self._ring_services.add(ring_svc)
                            self._count_transport("tcp", -1)
                            self._count_transport("shm", +1)
                        try:
                            send_msg(conn, reply)
                        except (ConnectionError, OSError):
                            return
                        if reply.get("code") == "bad_hello":
                            return  # a desynced peer can't be trusted framed
                        continue
                    try:
                        send_msg(conn, self._dispatch(req))
                    except (ConnectionError, OSError):
                        return
        finally:
            if ring_svc is not None:
                ring_svc.stop()
                self._count_transport("shm", -1)
            else:
                self._count_transport("tcp", -1)
            with self._conns_lock:
                self._conns.discard(conn)
                if ring_svc is not None:
                    self._ring_services.discard(ring_svc)
            self._g_conns.dec()

    def _dispatch(self, req) -> dict:
        if not isinstance(req, dict) or "op" not in req:
            return BadRequestError(f"not a request dict: {type(req)}").to_wire()
        op = req["op"]
        gw = self.gateway
        try:
            # multiplexed gateways (serve.mux.GatewayMux, fleet router
            # adapter) resolve the optional wire ``player`` field to the
            # right model; a plain single-model gateway ignores it — legacy
            # clients never send it and keep working unchanged
            if hasattr(gw, "resolve"):
                gw = gw.resolve(req.get("player"))
            if op == "act":
                out = gw.act(req["session_id"], req["obs"], req.get("timeout_s"),
                             want_teacher=bool(req.get("want_teacher", False)),
                             trace=req.get("trace"))
                return {"code": 0, "outputs": out}
            if op == "act_many":
                results = gw.act_many(req["requests"], req.get("timeout_s"))
                return {"code": 0, "results": [
                    r.to_wire() if isinstance(r, ServeError) else {"ok": r}
                    for r in results
                ]}
            if op == "reserve":
                return {"code": 0,
                        "slots": gw.reserve_sessions(req["session_ids"])}
            if op == "hidden":
                return {"code": 0, "hidden": gw.session_hidden(req["session_id"])}
            if op == "set_teacher":
                return {"code": 0, "ok": gw.set_teacher(req["params"])}
            if op == "reset":
                return {"code": 0, "reset": gw.reset_session(req["session_id"])}
            if op == "end":
                return {"code": 0, "ended": gw.end_session(req["session_id"])}
            if op == "load":
                info = gw.load_version(
                    req["version"], source=req.get("source"), params=req.get("params"),
                    activate=bool(req.get("activate", False)),
                )
                return {"code": 0, "info": info}
            if op == "swap":
                return {"code": 0, "generation": gw.activate_version(req["version"])}
            if op == "status":
                return {"code": 0, "status": gw.status()}
            if op == "drain":
                # address-level graceful retirement (never per-player)
                root = self.gateway
                if not hasattr(root, "begin_drain"):
                    return BadRequestError("target has no drain surface").to_wire()
                return {"code": 0, **root.begin_drain()}
            if op == "ping":
                return {"code": 0, "pong": True}
            return BadRequestError(f"unknown op {op!r}").to_wire()
        except ServeError as e:
            return e.to_wire()
        except Exception as e:  # a handler bug must not kill the connection
            return {"code": "serve_error", "error": repr(e), "shed": False}


class ServeClient:
    """Blocking data-plane client: one connection, one request in flight
    (callers wanting pipelining open one client per worker thread).

    Transport faults reconnect-and-retry under ``retry_policy`` (resilience
    fabric: a gateway restart is invisible to callers as long as it comes
    back inside the policy's budget). Typed ``ServeError`` responses — sheds,
    deadlines — are application answers, never retried here: shed/backoff
    decisions belong to the caller. NOTE: a retried ``act`` may execute twice
    on the server (at-least-once); inference is idempotent per (session,
    obs), so replays are safe for every current op.

    ``player`` (ctor default and/or per-call) stamps the wire ``player``
    field so one multiplexed gateway address can serve several player
    models (``serve.mux.GatewayMux``); a single-model server ignores the
    field, so stamped clients interoperate with legacy gateways."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 player: Optional[str] = None, transport: str = "auto"):
        self._addr = (host, port)
        self._timeout_s = timeout_s
        self._player = player
        self._policy = retry_policy or RetryPolicy(
            max_attempts=3, backoff_base_s=0.2, backoff_max_s=2.0,
            deadline_s=4 * timeout_s,
        )
        shm_ring.offer_transports(transport)  # validate the name early
        self._transport = transport
        self._shm: Optional[shm_ring.ShmPeer] = None
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._connect()

    @property
    def transport_active(self) -> str:
        """The leg this connection's data frames currently ride."""
        return "shm" if self._shm is not None else "tcp"

    def _connect(self) -> None:
        self.close()
        self._sock = socket.create_connection(self._addr, timeout=self._timeout_s)
        self._sock.settimeout(self._timeout_s)
        offers = shm_ring.offer_transports(self._transport)
        if "shm" not in offers:
            return  # tcp-only clients keep the pre-shm wire byte-identical
        try:
            send_msg(self._sock, {"op": "hello", "transports": offers,
                                  "host": shm_ring.host_identity()})
            resp = recv_msg(self._sock)
        except (ConnectionError, OSError, ValueError):
            self.close()
            raise
        if isinstance(resp, dict) and resp.get("code") == 0:
            self._shm = shm_ring.maybe_attach(resp, op="serve")
        # a pre-negotiation gateway answers bad_request: stay on TCP

    def _drop_shm(self) -> None:
        shm, self._shm = self._shm, None
        if shm is not None:
            shm.close()

    def _call_once(self, req: dict) -> dict:
        with self._lock:
            if self._sock is None:
                self._connect()
            resp = None
            if self._shm is not None:
                try:
                    resp = self._shm.request(req, timeout_s=self._timeout_s)
                except shm_ring.ShmTimeout:
                    self._drop_shm()
                    self.close()
                    raise
                except shm_ring.ShmError as e:
                    # typed ring fault (peer death mid-frame, oversized
                    # frame, corruption): counted, then THIS call falls
                    # back to the TCP leg on the same connection
                    shm_ring.note_fallback(e.reason)
                    self._drop_shm()
            if resp is None:
                try:
                    send_msg(self._sock, req)
                    resp = recv_msg(self._sock)
                except (ConnectionError, OSError, ValueError):
                    # the stream is no longer trustworthy (peer died
                    # mid-frame / garbage header): drop it so the retry
                    # dials fresh
                    self.close()
                    raise
        if resp.get("code") != 0:
            raise error_from_wire(resp)
        return resp

    def _call(self, req: dict) -> dict:
        return retry_call(
            self._call_once, req, op=f"serve:{req.get('op', '?')}",
            policy=self._policy,
        )

    def _stamp(self, req: dict, player: Optional[str]) -> dict:
        p = self._player if player is None else player
        if p is not None:
            req["player"] = p
        return req

    def act(self, session_id: str, obs, timeout_s: Optional[float] = None,
            want_teacher: bool = False, player: Optional[str] = None,
            trace: Optional[dict] = None) -> dict:
        """One agent step. A client-side span rides the wire as the compact
        ``trace`` field, so the gateway's server span joins under the same
        trace_id (client -> gateway in one waterfall). ``trace`` lets the
        caller supply its own context (it then owns finishing it — the
        FleetClient/loadgen contract); otherwise one is minted and finished
        here. Typed errors gain ``.trace_id`` either way."""
        owned = None
        ctx = trace
        if ctx is None and tracing_enabled():
            ctx = owned = start_trace("serve_client", session=session_id)
        req = {"op": "act", "session_id": session_id, "obs": obs}
        if timeout_s is not None:
            req["timeout_s"] = timeout_s
        if want_teacher:
            req["want_teacher"] = True
        if is_trace(ctx):
            req["trace"] = wire_ctx(ctx)
        # active trace: transport-level blocking (shm ring-full) attributes
        # its wait to this request's span — only the shm leg consumes it
        on_shm = self._shm is not None
        prev = set_active_trace(ctx) if on_shm else None
        try:
            out = self._call(self._stamp(req, player))["outputs"]
        except BaseException as e:
            if is_trace(ctx) and isinstance(e, ServeError):
                e.trace_id = ctx["trace_id"]
            finish_trace(owned, "client_done",
                         outcome="shed" if getattr(e, "shed", False) else "error")
            raise
        finally:
            if on_shm:
                set_active_trace(prev)
        if is_trace(ctx) and isinstance(out, dict):
            out.setdefault("trace_id", ctx["trace_id"])
        finish_trace(owned, "client_done")
        return out

    def act_many(self, requests, timeout_s: Optional[float] = None,
                 player: Optional[str] = None) -> list:
        """One cycle of requests in one frame; returns a per-request list of
        output dicts or typed ``ServeError`` INSTANCES (per-lane sheds come
        back as values, not raises — partial success keeps its lanes).
        Each request dict may carry a caller-minted full context under
        ``trace_ctx`` (client-side only — never serialized; the caller owns
        finishing it); lanes without one get a span minted and finished
        here. Either way only the compact wire field crosses the socket.
        NOTE: a transport retry re-executes the WHOLE cycle server-side
        (at-least-once), which advances succeeded lanes' carries once more —
        acceptable on the restart path, where carries re-materialize from
        zero anyway."""
        requests = list(requests)
        if not tracing_enabled() and not any("trace_ctx" in r for r in requests):
            # untraced fast path: byte-identical to the pre-tracing wire,
            # zero per-lane copies
            req = {"op": "act_many", "requests": requests}
            if timeout_s is not None:
                req["timeout_s"] = timeout_s
            entries = self._call(self._stamp(req, player))["results"]
            return [e["ok"] if isinstance(e, dict) and "ok" in e
                    else error_from_wire(e) for e in entries]
        ctxs, owned, wire_reqs = [], [], []
        for r in requests:
            ctx = r.get("trace_ctx")
            mine = None
            if ctx is None and tracing_enabled():
                ctx = mine = start_trace("serve_client",
                                         session=r.get("session_id", "?"))
            wr = {k: v for k, v in r.items() if k != "trace_ctx"}
            if is_trace(ctx):
                wr["trace"] = wire_ctx(ctx)
            else:
                ctx = None
            ctxs.append(ctx)
            owned.append(mine)
            wire_reqs.append(wr)
        req = {"op": "act_many", "requests": wire_reqs}
        if timeout_s is not None:
            req["timeout_s"] = timeout_s
        on_shm = self._shm is not None
        prev = set_active_trace(
            next((c for c in ctxs if c is not None), None)) if on_shm else None
        try:
            entries = self._call(self._stamp(req, player))["results"]
        except BaseException:
            for mine in owned:
                finish_trace(mine, "client_done", outcome="error")
            raise
        finally:
            if on_shm:
                set_active_trace(prev)
        out = []
        for ctx, mine, e in zip(ctxs, owned, entries):
            if isinstance(e, dict) and "ok" in e:
                val = e["ok"]
                if ctx is not None and isinstance(val, dict):
                    val.setdefault("trace_id", ctx["trace_id"])
                finish_trace(mine, "client_done")
                out.append(val)
            else:
                err = error_from_wire(e)
                if ctx is not None:
                    err.trace_id = ctx["trace_id"]
                finish_trace(mine, "client_done",
                             outcome="shed" if err.shed else "error")
                out.append(err)
        return out

    def reserve(self, session_ids, player: Optional[str] = None) -> dict:
        """Bulk session pre-allocation; typed ``CapacityError`` on shortfall
        (exact-capacity admission — nothing sheds mid-episode)."""
        return self._call(self._stamp(
            {"op": "reserve", "session_ids": list(session_ids)}, player))["slots"]

    def hidden(self, session_id: str, player: Optional[str] = None):
        return self._call(self._stamp(
            {"op": "hidden", "session_id": session_id}, player))["hidden"]

    def set_teacher(self, params, player: Optional[str] = None) -> bool:
        return self._call(self._stamp(
            {"op": "set_teacher", "params": params}, player))["ok"]

    def reset(self, session_id: str, player: Optional[str] = None) -> bool:
        return self._call(self._stamp(
            {"op": "reset", "session_id": session_id}, player))["reset"]

    def end(self, session_id: str, player: Optional[str] = None) -> bool:
        return self._call(self._stamp(
            {"op": "end", "session_id": session_id}, player))["ended"]

    def load(self, version: str, source: Optional[str] = None, params=None,
             activate: bool = False, player: Optional[str] = None) -> dict:
        return self._call(self._stamp(
            {"op": "load", "version": version, "source": source, "params": params,
             "activate": activate}, player)
        )["info"]

    def swap(self, version: str, player: Optional[str] = None) -> int:
        return self._call(self._stamp(
            {"op": "swap", "version": version}, player))["generation"]

    def status(self) -> dict:
        return self._call({"op": "status"})["status"]

    def drain(self) -> dict:
        """Ask the gateway to begin graceful retirement (idempotent);
        returns ``{"draining": True, "resident": N}``."""
        resp = self._call({"op": "drain"})
        return {"draining": bool(resp.get("draining")),
                "resident": int(resp.get("resident", 0))}

    def ping(self) -> bool:
        return self._call({"op": "ping"})["pong"]

    def close(self) -> None:
        self._drop_shm()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
