"""Framed-TCP data plane for actor-grade serve callers.

Wire format = ``comm.serializer``: 8-byte big-endian length prefix around a
pickled (+compressed) payload — the same stack the actor fleet's shuttle
speaks, so obs trees with real numpy arrays round-trip losslessly and fast
(no JSON float inflation). One request/response pair per frame; a
connection is a session's natural home but nothing enforces it — the
``session_id`` field is authoritative, so a pool of connections can front
many sessions.

Requests are ``{"op": ..., ...}`` dicts:
  act      {session_id, obs, timeout_s?, want_teacher?} -> {code: 0, outputs}
  act_many {requests: [{session_id, obs, want_teacher?}], timeout_s?}
                                           -> {code: 0, results: [entry]}
                                              entry = {ok: outputs} | wire error
  reserve  {session_ids: [...]}            -> {code: 0, slots: {sid: slot}}
  hidden   {session_id}                    -> {code: 0, hidden}
  set_teacher {params}                     -> {code: 0, ok: True}
  reset    {session_id}                    -> {code: 0, reset: bool}
  end      {session_id}                    -> {code: 0, ended: bool}
  load     {version, source|params, activate?} -> {code: 0, info}
  swap     {version}                       -> {code: 0, generation}
  status   {}                              -> {code: 0, status}
  ping     {}                              -> {code: 0, pong: True}

``act_many`` is the rollout-plane cycle op: one frame carries a whole env
fleet's step, per-lane results (including per-lane typed sheds) come back
in one frame, and different actors' cycles coalesce in the server's
micro-batcher.

Every request may carry an optional ``player`` field: multiplexed servers
(``serve.mux.GatewayMux`` — one address, several player models) resolve it
to the right model; single-model servers ignore it; absent means the
server's default player — so legacy single-model clients keep working
unchanged against both server generations.

Serve errors answer ``{code: <wire code>, error, shed}`` (errors.to_wire);
the client rehydrates them into the typed exceptions.
"""
from __future__ import annotations

import socket
import threading
from typing import Optional

from ..comm.serializer import recv_msg, send_msg
from ..obs import get_registry
from ..resilience import RetryPolicy, retry_call
from .errors import ServeError, error_from_wire


class ServeTCPServer:
    def __init__(self, gateway, host: str = "127.0.0.1", port: int = 0):
        self.gateway = gateway
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        reg = get_registry()
        self._g_conns = reg.gauge(
            "distar_serve_tcp_connections", "open data-plane connections"
        )
        self._c_frames = reg.counter(
            "distar_serve_tcp_frames_total", "request frames handled"
        )

    def start(self) -> "ServeTCPServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="serve-tcp-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: closing the fd from this thread does not
            # wake an accept() blocked in another — the kernel socket (and
            # the port) would live until a final connection arrived
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        # close live connections too: their handler threads otherwise sit in
        # recv until every peer goes away, pinning the port past stop()
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        t = self._accept_thread
        if t is not None:
            t.join(5.0)
            self._accept_thread = None

    # ------------------------------------------------------------------ loop
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:  # listener closed by stop()
                return
            # REUSEADDR on accepted sockets too: after stop(), lingering
            # FIN_WAIT conns must not block a restarted gateway from
            # rebinding the same port (the crash-restart path)
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            threading.Thread(
                target=self._serve_conn, args=(conn,), name="serve-tcp-conn", daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        self._g_conns.inc()
        with self._conns_lock:
            self._conns.add(conn)
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        req = recv_msg(conn)
                    except (ConnectionError, OSError):
                        return  # peer closed (possibly mid-frame)
                    except ValueError as e:
                        # garbage frame header/codec: answer typed, then
                        # close — the stream can no longer be trusted
                        send_msg(conn, {"code": "bad_frame", "error": repr(e), "shed": False})
                        return
                    self._c_frames.inc()
                    try:
                        send_msg(conn, self._dispatch(req))
                    except (ConnectionError, OSError):
                        return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            self._g_conns.dec()

    def _dispatch(self, req) -> dict:
        if not isinstance(req, dict) or "op" not in req:
            return {"code": "bad_request", "error": f"not a request dict: {type(req)}",
                    "shed": False}
        op = req["op"]
        gw = self.gateway
        try:
            # multiplexed gateways (serve.mux.GatewayMux, fleet router
            # adapter) resolve the optional wire ``player`` field to the
            # right model; a plain single-model gateway ignores it — legacy
            # clients never send it and keep working unchanged
            if hasattr(gw, "resolve"):
                gw = gw.resolve(req.get("player"))
            if op == "act":
                out = gw.act(req["session_id"], req["obs"], req.get("timeout_s"),
                             want_teacher=bool(req.get("want_teacher", False)))
                return {"code": 0, "outputs": out}
            if op == "act_many":
                results = gw.act_many(req["requests"], req.get("timeout_s"))
                return {"code": 0, "results": [
                    r.to_wire() if isinstance(r, ServeError) else {"ok": r}
                    for r in results
                ]}
            if op == "reserve":
                return {"code": 0,
                        "slots": gw.reserve_sessions(req["session_ids"])}
            if op == "hidden":
                return {"code": 0, "hidden": gw.session_hidden(req["session_id"])}
            if op == "set_teacher":
                return {"code": 0, "ok": gw.set_teacher(req["params"])}
            if op == "reset":
                return {"code": 0, "reset": gw.reset_session(req["session_id"])}
            if op == "end":
                return {"code": 0, "ended": gw.end_session(req["session_id"])}
            if op == "load":
                info = gw.load_version(
                    req["version"], source=req.get("source"), params=req.get("params"),
                    activate=bool(req.get("activate", False)),
                )
                return {"code": 0, "info": info}
            if op == "swap":
                return {"code": 0, "generation": gw.activate_version(req["version"])}
            if op == "status":
                return {"code": 0, "status": gw.status()}
            if op == "ping":
                return {"code": 0, "pong": True}
            return {"code": "bad_request", "error": f"unknown op {op!r}", "shed": False}
        except ServeError as e:
            return e.to_wire()
        except Exception as e:  # a handler bug must not kill the connection
            return {"code": "serve_error", "error": repr(e), "shed": False}


class ServeClient:
    """Blocking data-plane client: one connection, one request in flight
    (callers wanting pipelining open one client per worker thread).

    Transport faults reconnect-and-retry under ``retry_policy`` (resilience
    fabric: a gateway restart is invisible to callers as long as it comes
    back inside the policy's budget). Typed ``ServeError`` responses — sheds,
    deadlines — are application answers, never retried here: shed/backoff
    decisions belong to the caller. NOTE: a retried ``act`` may execute twice
    on the server (at-least-once); inference is idempotent per (session,
    obs), so replays are safe for every current op.

    ``player`` (ctor default and/or per-call) stamps the wire ``player``
    field so one multiplexed gateway address can serve several player
    models (``serve.mux.GatewayMux``); a single-model server ignores the
    field, so stamped clients interoperate with legacy gateways."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 player: Optional[str] = None):
        self._addr = (host, port)
        self._timeout_s = timeout_s
        self._player = player
        self._policy = retry_policy or RetryPolicy(
            max_attempts=3, backoff_base_s=0.2, backoff_max_s=2.0,
            deadline_s=4 * timeout_s,
        )
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._connect()

    def _connect(self) -> None:
        self.close()
        self._sock = socket.create_connection(self._addr, timeout=self._timeout_s)
        self._sock.settimeout(self._timeout_s)

    def _call_once(self, req: dict) -> dict:
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                send_msg(self._sock, req)
                resp = recv_msg(self._sock)
            except (ConnectionError, OSError, ValueError):
                # the stream is no longer trustworthy (peer died mid-frame /
                # garbage header): drop it so the retry dials fresh
                self.close()
                raise
        if resp.get("code") != 0:
            raise error_from_wire(resp)
        return resp

    def _call(self, req: dict) -> dict:
        return retry_call(
            self._call_once, req, op=f"serve:{req.get('op', '?')}",
            policy=self._policy,
        )

    def _stamp(self, req: dict, player: Optional[str]) -> dict:
        p = self._player if player is None else player
        if p is not None:
            req["player"] = p
        return req

    def act(self, session_id: str, obs, timeout_s: Optional[float] = None,
            want_teacher: bool = False, player: Optional[str] = None) -> dict:
        req = {"op": "act", "session_id": session_id, "obs": obs}
        if timeout_s is not None:
            req["timeout_s"] = timeout_s
        if want_teacher:
            req["want_teacher"] = True
        return self._call(self._stamp(req, player))["outputs"]

    def act_many(self, requests, timeout_s: Optional[float] = None,
                 player: Optional[str] = None) -> list:
        """One cycle of requests in one frame; returns a per-request list of
        output dicts or typed ``ServeError`` INSTANCES (per-lane sheds come
        back as values, not raises — partial success keeps its lanes).
        NOTE: a transport retry re-executes the WHOLE cycle server-side
        (at-least-once), which advances succeeded lanes' carries once more —
        acceptable on the restart path, where carries re-materialize from
        zero anyway."""
        req = {"op": "act_many", "requests": list(requests)}
        if timeout_s is not None:
            req["timeout_s"] = timeout_s
        entries = self._call(self._stamp(req, player))["results"]
        return [e["ok"] if isinstance(e, dict) and "ok" in e else error_from_wire(e)
                for e in entries]

    def reserve(self, session_ids, player: Optional[str] = None) -> dict:
        """Bulk session pre-allocation; typed ``CapacityError`` on shortfall
        (exact-capacity admission — nothing sheds mid-episode)."""
        return self._call(self._stamp(
            {"op": "reserve", "session_ids": list(session_ids)}, player))["slots"]

    def hidden(self, session_id: str, player: Optional[str] = None):
        return self._call(self._stamp(
            {"op": "hidden", "session_id": session_id}, player))["hidden"]

    def set_teacher(self, params, player: Optional[str] = None) -> bool:
        return self._call(self._stamp(
            {"op": "set_teacher", "params": params}, player))["ok"]

    def reset(self, session_id: str, player: Optional[str] = None) -> bool:
        return self._call(self._stamp(
            {"op": "reset", "session_id": session_id}, player))["reset"]

    def end(self, session_id: str, player: Optional[str] = None) -> bool:
        return self._call(self._stamp(
            {"op": "end", "session_id": session_id}, player))["ended"]

    def load(self, version: str, source: Optional[str] = None, params=None,
             activate: bool = False, player: Optional[str] = None) -> dict:
        return self._call(self._stamp(
            {"op": "load", "version": version, "source": source, "params": params,
             "activate": activate}, player)
        )["info"]

    def swap(self, version: str, player: Optional[str] = None) -> int:
        return self._call(self._stamp(
            {"op": "swap", "version": version}, player))["generation"]

    def status(self) -> dict:
        return self._call({"op": "status"})["status"]

    def ping(self) -> bool:
        return self._call({"op": "ping"})["pong"]

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
