"""Player multiplexing: several models behind ONE gateway address.

The rollout plane's "one gateway, one model" contract (PR 8) forced one
serving process per player. ``GatewayMux`` lifts it: one TCP + one HTTP
address fronting a ``{player: InferenceGateway}`` table, with requests
routed by the optional wire ``player`` field both frontends now carry.
Each player keeps its OWN engine, session table, micro-batcher and
versioned registry — sessions are therefore keyed by ``(player, session)``
by construction (the same session id under two players lands in two
independent tables), and a hot-swap of MP0 cannot disturb MP1's flushes.

Compatibility: requests without a ``player`` field resolve to the
``default_player`` (the first configured), so legacy single-model clients
keep working unchanged; a request naming an unserved player answers the
typed ``unknown_player`` wire error.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .errors import UnknownPlayerError
from .gateway import InferenceGateway

#: Model-tiering player ids (docs/serving.md, model tiering): the wire
#: ``player`` field IS the QoS class — teacher-tier traffic (eval, ladder,
#: showmatches: quality-critical, low volume) names the full-size policy,
#: student-tier traffic (bulk rollouts: volume-critical) names the
#: distilled student. One mux, one address, zero new wire surface.
TEACHER_TIER = "teacher"
STUDENT_TIER = "student"

_TIER_BY_TRAFFIC = {
    "eval": TEACHER_TIER,
    "ladder": TEACHER_TIER,
    "showmatch": TEACHER_TIER,
    "rollout": STUDENT_TIER,
    "bulk": STUDENT_TIER,
}


def tier_player(traffic: str, default: str = STUDENT_TIER) -> str:
    """The serving-tier player id for a traffic class: quality-critical
    classes (eval/ladder/showmatch) ride the teacher, everything bulk
    rides the student. Unknown classes get ``default`` — bulk-by-default
    keeps the expensive tier reserved for traffic that NAMED it."""
    return _TIER_BY_TRAFFIC.get(str(traffic).lower(), default)


class GatewayMux:
    """The gateway surface over a per-player gateway table.

    Frontends call ``resolve(player)`` first (both do, whenever the target
    has a ``resolve`` attribute) and dispatch the op against the result:
    the player's ``InferenceGateway``, or this mux itself for ``player is
    None`` — the mux delegates session/admin ops to the default player's
    gateway and aggregates ``status`` across all of them."""

    def __init__(self, gateways: Dict[str, InferenceGateway],
                 default_player: Optional[str] = None):
        if not gateways:
            raise ValueError("GatewayMux needs at least one player gateway")
        self.gateways = dict(gateways)
        self.default_player = default_player or next(iter(self.gateways))
        if self.default_player not in self.gateways:
            raise ValueError(
                f"default player {self.default_player!r} not in "
                f"{sorted(self.gateways)}")
        #: one registration per ADDRESS: the mux owns the coordinator lease
        #: (player gateways behind it must not deregister independently)
        self.deregister = None
        self._deregistered = False

    # ---------------------------------------------------------------- routing
    def resolve(self, player: Optional[str]):
        """The dispatch target for a request: the named player's gateway, or
        the mux itself (default-player delegation + aggregate status) when
        the request carries no player field."""
        if player is None:
            return self
        gw = self.gateways.get(player)
        if gw is None:
            raise UnknownPlayerError(
                f"player {player!r} not served here (players: "
                f"{sorted(self.gateways)})")
        return gw

    def players(self) -> List[str]:
        return sorted(self.gateways)

    @property
    def _default(self) -> InferenceGateway:
        return self.gateways[self.default_player]

    # -------------------------------------------- default-player delegation
    def act(self, session_id, obs, timeout_s=None, want_teacher=False,
            trace=None):
        return self._default.act(session_id, obs, timeout_s,
                                 want_teacher=want_teacher, trace=trace)

    def act_many(self, requests, timeout_s=None):
        return self._default.act_many(requests, timeout_s=timeout_s)

    def reserve_sessions(self, session_ids):
        return self._default.reserve_sessions(session_ids)

    def session_hidden(self, session_id):
        return self._default.session_hidden(session_id)

    def set_teacher(self, params):
        return self._default.set_teacher(params)

    def reset_session(self, session_id):
        return self._default.reset_session(session_id)

    def end_session(self, session_id):
        return self._default.end_session(session_id)

    def load_version(self, version, source=None, params=None, activate=False):
        return self._default.load_version(version, source=source, params=params,
                                          activate=activate)

    def activate_version(self, version):
        return self._default.activate_version(version)

    # ----------------------------------------------------------------- fleet
    def status(self) -> dict:
        """Aggregate view: per-player gateway status plus the fields fleet
        tooling reads off a single gateway — sessions/requests SUMMED over
        players (the opsctl occupancy digest must see the whole address),
        generation/version from the default player (the one legacy callers
        are talking to)."""
        per_player = {p: gw.status() for p, gw in self.gateways.items()}
        default = per_player[self.default_player]
        sessions = {k: sum(st["sessions"].get(k, 0) for st in per_player.values())
                    for k in ("active", "free_slots", "num_slots", "inflight")}
        requests = {}
        for st in per_player.values():
            for k, v in (st.get("requests") or {}).items():
                requests[k] = requests.get(k, 0.0) + v
        total = sum(requests.values())
        # the TCP frontend stamps its per-connection transport split on the
        # object it fronts — for a mux that is the mux itself, not a player
        transports = getattr(self, "_tcp_transports", None)
        return {
            **default,
            **({"transports": transports()} if callable(transports) else {}),
            "sessions": sessions,
            "requests": requests,
            "shed_rate": round(requests.get("shed", 0.0) / total, 6) if total else 0.0,
            "queue_depth": sum(st.get("queue_depth", 0) for st in per_player.values()),
            "players": per_player,
            "default_player": self.default_player,
        }

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "GatewayMux":
        for gw in self.gateways.values():
            gw.start()
        return self

    def _deregister_once(self) -> None:
        fn, self.deregister = self.deregister, None
        if fn is not None and not self._deregistered:
            self._deregistered = True
            try:
                fn()
            except Exception:  # noqa: BLE001 - best-effort; the lease still lapses
                pass

    def begin_drain(self) -> dict:
        """Graceful retirement of the whole address: deregister the ONE
        coordinator lease first (the regression this fixes: a draining mux
        used to keep heartbeating, so routers kept pinning new sessions to
        it until the lease died), then put every player gateway into
        shed-new/finish-in-flight draining. Idempotent."""
        self._deregister_once()
        for gw in self.gateways.values():
            gw.begin_drain()
        return {"draining": True, "resident": self.resident_sessions()}

    def resident_sessions(self) -> int:
        return sum(gw.resident_sessions() for gw in self.gateways.values())

    @property
    def draining(self) -> bool:
        return any(gw._draining for gw in self.gateways.values())

    def drain_and_stop(self, timeout: Optional[float] = 30.0) -> None:
        self.begin_drain()
        for gw in self.gateways.values():
            gw.drain_and_stop(timeout)
