"""Sticky sessions: server-side recurrent state in a bounded slot map.

Each client session pins one engine slot so its LSTM carry lives on the
server between requests (the serve-plane analogue of the actor's per-env
slot in ``BatchedInference``; episode reset = ``reset_slot`` = slot zero).
Slots are a hard capacity — the batch dimension of the compiled forward —
so allocation is admission control: a new session gets a free slot, else
the least-recently-used *idle-expired* session is evicted, else the request
is shed with ``CapacityError``. Sessions with requests in flight are never
evicted (their slot's hidden state is being advanced by the batcher).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs import get_registry
from .errors import CapacityError


class _Session:
    __slots__ = ("session_id", "slot", "last_seen", "inflight", "created", "steps")

    def __init__(self, session_id: str, slot: int, now: float):
        self.session_id = session_id
        self.slot = slot
        self.last_seen = now
        self.inflight = 0
        self.created = now
        self.steps = 0  # forwards served this episode (zeroed on reset)


class SessionTable:
    def __init__(
        self,
        num_slots: int,
        idle_ttl_s: float = 300.0,
        on_alloc: Optional[Callable[[int], None]] = None,
    ):
        """``on_alloc(slot)`` runs under the table lock whenever a slot is
        (re)assigned — the gateway zeroes the engine's hidden state there so
        a recycled slot never leaks the previous session's carry."""
        assert num_slots > 0
        self.num_slots = num_slots
        self.idle_ttl_s = idle_ttl_s
        self._on_alloc = on_alloc
        self._sessions: Dict[str, _Session] = {}
        self._free = list(range(num_slots - 1, -1, -1))  # pop() -> slot 0 first
        self._lock = threading.Lock()
        reg = get_registry()
        self._g_active = reg.gauge(
            "distar_serve_sessions_active", "sessions currently holding a slot"
        )
        self._c_evict = reg.counter(
            "distar_serve_session_evictions_total", "idle sessions evicted for capacity"
        )

    # ------------------------------------------------------------- lifecycle
    def acquire(self, session_id: str) -> int:
        """Return the session's slot, allocating (and possibly evicting an
        idle-expired session) on first contact; bumps last_seen and the
        in-flight count. Pair every acquire with ``release``."""
        now = time.time()
        with self._lock:
            s = self._sessions.get(session_id)
            if s is None:
                slot = self._alloc_locked(now)
                s = _Session(session_id, slot, now)
                self._sessions[session_id] = s
                self._g_active.set(len(self._sessions))
                if self._on_alloc is not None:
                    self._on_alloc(slot)
            s.last_seen = now
            s.inflight += 1
            return s.slot

    def reserve(self, session_ids: List[str]) -> Dict[str, int]:
        """All-or-nothing bulk allocation (the rollout plane's exact-
        capacity admission: actors pre-allocate every env slot's session at
        job start so nothing sheds mid-episode). Either every id gets a
        slot — already-known ids keep theirs — or the table is untouched
        and a typed ``CapacityError`` reports the shortfall up front.
        Eviction of idle-expired sessions is allowed, exactly as in the
        single-session path; in-flight sessions are never victims."""
        now = time.time()
        with self._lock:
            need = [sid for sid in dict.fromkeys(session_ids)
                    if sid not in self._sessions]
            evictable = sum(
                1 for s in self._sessions.values()
                if s.inflight == 0 and now - s.last_seen >= self.idle_ttl_s
            )
            if len(need) > len(self._free) + evictable:
                raise CapacityError(
                    f"reserve of {len(need)} new sessions exceeds capacity: "
                    f"{len(self._free)} free + {evictable} evictable of "
                    f"{self.num_slots} slots"
                )
            out: Dict[str, int] = {}
            for sid in need:
                slot = self._alloc_locked(now)  # cannot fail: counted above
                self._sessions[sid] = _Session(sid, slot, now)
                if self._on_alloc is not None:
                    self._on_alloc(slot)
            self._g_active.set(len(self._sessions))
            for sid in dict.fromkeys(session_ids):
                s = self._sessions[sid]
                s.last_seen = now
                out[sid] = s.slot
            return out

    def note_step(self, session_id: str) -> int:
        """One forward served for this session; returns the episode-local
        step count (clients detect a server-side carry reset — restart,
        eviction — when this counter runs backwards)."""
        with self._lock:
            s = self._sessions.get(session_id)
            if s is None:
                return 0
            s.steps += 1
            return s.steps

    def reset_steps(self, session_id: str) -> None:
        """Episode boundary: the step counter restarts with the carry."""
        with self._lock:
            s = self._sessions.get(session_id)
            if s is not None:
                s.steps = 0

    def release(self, session_id: str) -> None:
        """A request for this session finished (delivered, shed or timed
        out) — the session becomes evictable again once idle-expired."""
        with self._lock:
            s = self._sessions.get(session_id)
            if s is not None:
                s.inflight = max(0, s.inflight - 1)
                s.last_seen = time.time()

    def _alloc_locked(self, now: float) -> int:
        if self._free:
            return self._free.pop()
        # LRU idle-expired victim with nothing in flight
        victim = None
        for s in self._sessions.values():
            if s.inflight > 0 or now - s.last_seen < self.idle_ttl_s:
                continue
            if victim is None or s.last_seen < victim.last_seen:
                victim = s
        if victim is None:
            raise CapacityError(
                f"all {self.num_slots} session slots busy and none idle past "
                f"{self.idle_ttl_s}s"
            )
        del self._sessions[victim.session_id]
        self._c_evict.inc()
        self._g_active.set(len(self._sessions))
        return victim.slot

    def slot_of(self, session_id: str) -> Optional[int]:
        with self._lock:
            s = self._sessions.get(session_id)
            return None if s is None else s.slot

    def end(self, session_id: str) -> bool:
        """Explicitly release the session's slot (client said goodbye)."""
        with self._lock:
            s = self._sessions.pop(session_id, None)
            if s is None:
                return False
            self._free.append(s.slot)
            self._g_active.set(len(self._sessions))
            return True

    def stats(self) -> dict:
        with self._lock:
            return {
                "active": len(self._sessions),
                "free_slots": len(self._free),
                "num_slots": self.num_slots,
                "inflight": sum(s.inflight for s in self._sessions.values()),
            }
