"""Versioned model registry with zero-downtime hot swap.

RLAX-style weight management (arxiv 2512.06392: a central inference service
whose weights advance by versioned swaps, never in place): every checkpoint
loads under an explicit version name via ``utils.checkpoint`` — so sources
are ``utils.storage`` URLs (plain paths, ``mem://``, registered pod
backends) — is warmed up with one compiled forward *off the serving path*,
and only then becomes swappable. ``activate`` is an atomic pointer bump
guarded by a generation counter; the gateway's batcher applies the new
params at its next flush boundary, so a forward already executing finishes
on the old params and no in-flight request is dropped or served by
half-installed weights.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..obs import get_registry
from .errors import UnknownVersionError


def default_load_fn(source: str):
    """``utils.checkpoint.load_params`` over storage URLs: checkpoint state
    -> bare inference params (opt_state stripped)."""
    from ..utils.checkpoint import load_params

    return load_params(source)


class _Version:
    __slots__ = ("name", "params", "source", "loaded_at", "load_s", "warmup_s")

    def __init__(self, name, params, source, load_s, warmup_s):
        self.name = name
        self.params = params
        self.source = source
        self.loaded_at = time.time()
        self.load_s = load_s
        self.warmup_s = warmup_s


class ModelRegistry:
    def __init__(
        self,
        load_fn: Optional[Callable[[str], dict]] = None,
        warmup_fn: Optional[Callable[[dict], None]] = None,
    ):
        """``load_fn(source) -> params`` (default: ``utils.checkpoint`` via
        storage URLs); ``warmup_fn(params)`` runs one forward on the freshly
        loaded params before they are swappable (the gateway wires the
        engine's scratch-state warmup here)."""
        self._load_fn = load_fn or default_load_fn
        self._warmup_fn = warmup_fn
        self._versions: Dict[str, _Version] = {}
        self._current: Optional[str] = None
        self._generation = 0
        self._activated_at = 0.0
        self._lock = threading.RLock()
        reg = get_registry()
        self._h_load = reg.histogram(
            "distar_serve_model_load_seconds", "checkpoint load + warmup wall time"
        )
        self._h_swap = reg.histogram(
            "distar_serve_swap_duration_seconds",
            "activate() to first flush on the new params",
        )
        self._c_swap = reg.counter("distar_serve_swaps_total", "version activations")
        self._g_gen = reg.gauge(
            "distar_serve_model_generation", "monotonic active-params generation"
        )
        self._g_versions = reg.gauge(
            "distar_serve_model_versions", "versions resident in the registry"
        )

    # ------------------------------------------------------------------ load
    def load(self, version: str, source: Optional[str] = None, params=None,
             activate: bool = False) -> dict:
        """Load ``version`` from a storage URL (or take ``params`` directly,
        e.g. pushed over the wire by a learner) and warm it up. Loading
        happens outside the registry lock — the serving path never waits on
        checkpoint IO or warm-up compilation."""
        assert (source is None) != (params is None), "exactly one of source/params"
        t0 = time.perf_counter()
        if params is None:
            params = self._load_fn(source)
        load_s = time.perf_counter() - t0
        warmup_s = 0.0
        if self._warmup_fn is not None:
            t1 = time.perf_counter()
            self._warmup_fn(params)
            warmup_s = time.perf_counter() - t1
        self._h_load.observe(load_s + warmup_s)
        with self._lock:
            self._versions[version] = _Version(version, params, source, load_s, warmup_s)
            self._g_versions.set(len(self._versions))
        if activate:
            self.activate(version)
        return {"version": version, "load_s": load_s, "warmup_s": warmup_s}

    # ------------------------------------------------------------------ swap
    def activate(self, version: str) -> int:
        """Atomically make ``version`` current; returns the new generation."""
        with self._lock:
            if version not in self._versions:
                raise UnknownVersionError(f"version {version!r} not loaded")
            self._current = version
            self._generation += 1
            self._activated_at = time.perf_counter()
            self._c_swap.inc()
            self._g_gen.set(self._generation)
            return self._generation

    def current(self) -> Tuple[int, Optional[str], Optional[dict]]:
        """(generation, version, params) under one lock acquisition — the
        batcher reads this at every flush and applies on generation change."""
        with self._lock:
            if self._current is None:
                return self._generation, None, None
            return self._generation, self._current, self._versions[self._current].params

    def swap_applied(self, generation: int) -> None:
        """The batcher installed generation ``generation`` on the engine —
        close the swap-duration measurement (activate -> first flush that
        serves the new params)."""
        with self._lock:
            if generation == self._generation and self._activated_at:
                self._h_swap.observe(time.perf_counter() - self._activated_at)
                self._activated_at = 0.0

    # ----------------------------------------------------------------- admin
    def unload(self, version: str) -> bool:
        """Drop a non-current version (old params are only reclaimable once
        nothing can flush on them)."""
        with self._lock:
            if version == self._current:
                raise UnknownVersionError(f"version {version!r} is current; swap first")
            dropped = self._versions.pop(version, None) is not None
            self._g_versions.set(len(self._versions))
            return dropped

    def status(self) -> dict:
        with self._lock:
            return {
                "current": self._current,
                "generation": self._generation,
                "versions": {
                    v.name: {
                        "source": v.source,
                        "loaded_at": v.loaded_at,
                        "load_s": round(v.load_s, 6),
                        "warmup_s": round(v.warmup_s, 6),
                    }
                    for v in self._versions.values()
                },
            }
