"""Request queue + deadline-aware micro-batcher.

Concurrent ``act`` calls land in one bounded queue; a single batcher thread
coalesces them into fixed-shape flushes (the Sebulba inference-server loop,
arxiv 2104.06272). A flush fires when

  * **full**     — the queue holds requests for ``max_batch`` distinct slots
                   (one request per slot per flush: a session's steps are
                   sequential because its LSTM carry advances per forward);
  * **deadline** — the oldest admitted request has waited ``max_delay_s``
                   (tail-latency bound under light load);
  * **drain**    — shutdown flushes whatever is queued, then stops.

Admission control is synchronous in ``submit``: a full queue sheds with
``QueueFullError`` instead of blocking the caller, and requests whose own
deadline lapsed while queued are shed with ``DeadlineExceededError`` before
ever reaching the engine. The flush itself is a callback — the gateway owns
batch assembly, params versioning and delivery; the batcher owns only
queueing, timing and shedding, so it is testable with a list-appending stub.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..obs import get_registry
from .errors import DeadlineExceededError, DrainingError, QueueFullError, ServeError


class PendingRequest:
    """One queued ``act`` request: observation + slot + timing + the rendezvous
    the submitting thread blocks on. Completion is once-only (``complete``
    returns False if the request was already completed or abandoned)."""

    __slots__ = (
        "session_id", "slot", "obs", "enqueue_ts", "deadline_ts", "ctx",
        "want_teacher", "result", "error", "service_s", "queue_s", "_event",
        "_state", "_lock",
    )

    def __init__(self, session_id: str, slot: int, obs, deadline_ts: Optional[float],
                 ctx: Optional[dict] = None, want_teacher: bool = False):
        self.session_id = session_id
        self.slot = slot
        self.obs = obs
        self.enqueue_ts = time.time()
        self.deadline_ts = deadline_ts
        self.ctx = ctx  # obs.trace context riding the request
        self.want_teacher = want_teacher  # piggyback teacher logits on the flush
        self.service_s = 0.0  # the flush's engine-forward share (trace attribution)
        self.queue_s = 0.0  # admission-to-flush residency (trace attribution)
        self.result = None
        self.error: Optional[ServeError] = None
        self._event = threading.Event()
        self._state = "pending"
        self._lock = threading.Lock()

    def complete(self, result=None, error: Optional[ServeError] = None) -> bool:
        with self._lock:
            if self._state != "pending":
                return False
            self._state = "done"
        self.result = result
        self.error = error
        self._event.set()
        return True

    def abandon(self) -> bool:
        """The submitter stopped waiting (its timeout fired). The batcher may
        still run the forward for this slot — the hidden state advances — but
        the output is discarded."""
        with self._lock:
            if self._state != "pending":
                return False
            self._state = "abandoned"
            return True

    def wait(self, timeout: Optional[float]) -> bool:
        return self._event.wait(timeout)

    @property
    def done(self) -> bool:
        return self._state == "done"


class MicroBatcher:
    def __init__(
        self,
        flush_fn: Callable[[List[PendingRequest], str], None],
        max_batch: int,
        max_delay_s: float = 0.005,
        capacity: int = 256,
    ):
        assert max_batch > 0 and capacity > 0
        self._flush_fn = flush_fn
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.capacity = capacity
        self._queue: List[PendingRequest] = []
        self._cond = threading.Condition()
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        reg = get_registry()
        self._g_depth = reg.gauge(
            "distar_serve_queue_depth", "admitted requests waiting for a flush"
        )
        self._h_occupancy = reg.histogram(
            "distar_serve_batch_occupancy", "requests per flushed batch"
        )
        self._h_wait = reg.histogram(
            "distar_serve_queue_wait_seconds", "admission-to-flush queue wait"
        )
        self._c_flush = {
            reason: reg.counter(
                "distar_serve_flush_total", "batch flushes by trigger", reason=reason
            )
            for reason in ("full", "deadline", "drain")
        }
        self._c_shed = {
            code: reg.counter(
                "distar_serve_shed_total", "requests shed by admission/deadline control",
                reason=code,
            )
            for code in ("shed_queue_full", "shed_deadline", "draining", "shed_capacity")
        }

    # ------------------------------------------------------------- admission
    def submit(self, req: PendingRequest) -> None:
        """Admit a request or shed it (typed, never blocking)."""
        with self._cond:
            if self._draining or self._stopped:
                self._c_shed["draining"].inc()
                raise DrainingError("gateway is draining; not accepting requests")
            if len(self._queue) >= self.capacity:
                self._c_shed["shed_queue_full"].inc()
                raise QueueFullError(
                    f"request queue at capacity ({self.capacity}); retry with backoff"
                )
            self._queue.append(req)
            self._g_depth.set(len(self._queue))
            self._cond.notify()

    def shed_count(self, reason: str) -> float:
        """Convenience for admission-control callers (gateway status)."""
        return self._c_shed[reason].value if reason in self._c_shed else 0.0

    # ----------------------------------------------------------------- loop
    def start(self) -> None:
        assert self._thread is None, "batcher already started"
        self._thread = threading.Thread(target=self._run, name="serve-batcher", daemon=True)
        self._thread.start()

    def drain_and_stop(self, timeout: Optional[float] = 30.0) -> None:
        """Stop admissions, flush everything already admitted, stop the
        thread. Idempotent."""
        with self._cond:
            self._draining = True
            self._cond.notify()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def _run(self) -> None:
        while True:
            batch, reason = self._next_batch()
            if batch is None:
                break
            if not batch:
                continue
            now = time.time()
            for r in batch:
                wait = max(0.0, now - r.enqueue_ts)
                self._h_wait.observe(wait)
                # queue-wait attribution: stashed for the waiter's thread to
                # annotate at completion (the waterfall separates "sat in
                # the micro-batcher" from "ran the engine"; this loop is the
                # serial flush path, so it only stamps the number)
                r.queue_s = wait
            self._h_occupancy.observe(len(batch))
            self._c_flush[reason].inc()
            try:
                self._flush_fn(batch, reason)
            except Exception as e:  # flush must never kill the loop
                err = ServeError(f"flush failed: {e!r}")
                for r in batch:
                    r.complete(error=err)

    def _next_batch(self):
        """Block until a flush should happen; returns (requests, reason) or
        (None, ...) when drained-and-empty. Runs entirely under the lock
        except the final timed waits."""
        with self._cond:
            while True:
                now = time.time()
                self._shed_expired_locked(now)
                if self._queue:
                    slots = set()
                    for r in self._queue:
                        slots.add(r.slot)
                        if len(slots) >= self.max_batch:
                            return self._take_locked(), "full"
                    if self._draining:
                        return self._take_locked(), "drain"
                    flush_at = self._queue[0].enqueue_ts + self.max_delay_s
                    if now >= flush_at:
                        return self._take_locked(), "deadline"
                    self._cond.wait(min(flush_at - now, 0.05))
                    continue
                if self._draining or self._stopped:
                    self._stopped = True
                    return None, "stopped"
                self._cond.wait(0.05)

    def _take_locked(self) -> List[PendingRequest]:
        """Pop up to ``max_batch`` requests with distinct slots, preserving
        arrival order; a second request for a slot already in the batch
        stays queued for the next flush (its session's carry must see the
        first step's update before the second runs)."""
        taken, rest, slots = [], [], set()
        for r in self._queue:
            if len(taken) < self.max_batch and r.slot not in slots:
                taken.append(r)
                slots.add(r.slot)
            else:
                rest.append(r)
        self._queue = rest
        self._g_depth.set(len(self._queue))
        return taken

    def _shed_expired_locked(self, now: float) -> None:
        alive = []
        for r in self._queue:
            if r.deadline_ts is not None and now >= r.deadline_ts:
                self._c_shed["shed_deadline"].inc()
                r.complete(
                    error=DeadlineExceededError(
                        f"deadline passed after {now - r.enqueue_ts:.3f}s in queue"
                    )
                )
            else:
                alive.append(r)
        if len(alive) != len(self._queue):
            self._queue = alive
            self._g_depth.set(len(self._queue))

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._queue)
