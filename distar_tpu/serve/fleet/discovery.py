"""Gateway fleet discovery: ``serve_gateway`` registrations + the fleet map.

Every serving gateway (``bin/serve.py --coordinator-addr``, or the jax-free
``fleet.gateway_proc`` drill twin) registers its framed-TCP data-plane
address with the coordinator under the ``serve_gateway`` token, carrying a
meta block the rest of the fleet plans against:

  players    list of player ids this gateway serves (one entry for a
             single-model gateway, several behind a ``GatewayMux``)
  slots      engine batch lanes = max live sessions
  http_port  the HTTP/JSON frontend (opsctl digests hit ``/serve/status``)
  version    boot model version name (live generation comes from status)

The TCP address is the gateway's *identity*: a restarted gateway on the
same address keeps its ring segment (so routing looks for sessions exactly
where they were pinned), mirroring the replay shard fleet's contract.
Liveness is the PR 4 lease/heartbeat: a gateway that stops heartbeating is
evicted broker-side and drops out of freshly discovered maps.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

#: coordinator token serving gateways register under
GATEWAY_TOKEN = "serve_gateway"


def register_gateway(coordinator_addr: Tuple[str, int], host: str, port: int,
                     meta: Optional[dict] = None, lease_s: Optional[float] = None,
                     heartbeat_interval_s: Optional[float] = None,
                     stop_event: Optional[threading.Event] = None) -> threading.Thread:
    """Register one gateway's TCP data-plane endpoint under
    ``GATEWAY_TOKEN`` and keep its lease alive (``comm.discovery`` idiom).
    Returns the heartbeat thread; its ``stop_event`` ends the keep-alive."""
    from ...comm.discovery import register_endpoint

    return register_endpoint(
        coordinator_addr, GATEWAY_TOKEN, host, port, meta=meta, lease_s=lease_s,
        heartbeat_interval_s=heartbeat_interval_s, stop_event=stop_event,
    )


class GatewayMap:
    """Ordered gateway address list + per-gateway meta.

    Same role as the replay fleet's ``ShardMap``: the stable membership a
    router hashes over. Addresses are data-plane ``host:port`` identities;
    ``meta`` keeps whatever each gateway advertised at registration (empty
    for maps built from a plain address list)."""

    def __init__(self, addrs: Sequence[str], meta: Optional[Dict[str, dict]] = None):
        self.addrs = list(dict.fromkeys(a.strip() for a in addrs if a.strip()))
        if not self.addrs:
            raise ValueError("gateway map needs at least one 'host:port' address")
        self.meta: Dict[str, dict] = {a: dict((meta or {}).get(a) or {})
                                      for a in self.addrs}

    def __len__(self) -> int:
        return len(self.addrs)

    def __contains__(self, addr: str) -> bool:
        return addr in self.meta

    @classmethod
    def parse(cls, spec: str) -> "GatewayMap":
        """``"h1:p1,h2:p2,..."`` -> map (a single address is a 1-gateway map)."""
        return cls(str(spec).split(","))

    @classmethod
    def discover(cls, coordinator_addr: Tuple[str, int],
                 token: str = GATEWAY_TOKEN) -> "GatewayMap":
        """Build the map from the coordinator's live ``serve_gateway``
        registrations (lease-evicted gateways never appear). Raises
        ``ValueError`` when no gateway has registered yet."""
        from ...comm.discovery import discover_endpoints

        records = discover_endpoints(coordinator_addr, token)
        meta: Dict[str, dict] = {}
        for r in records:
            meta[f"{r['ip']}:{r['port']}"] = dict(r.get("meta") or {})
        if not meta:
            host, port = coordinator_addr
            raise ValueError(
                f"no {token!r} registrations at coordinator {host}:{port} "
                "(are the gateways up, and started with --coordinator-addr?)"
            )
        addrs = sorted(meta)
        return cls(addrs, meta=meta)

    def players(self) -> List[str]:
        """Every player id any gateway in the map advertises."""
        out: List[str] = []
        for addr in self.addrs:
            for p in self.meta.get(addr, {}).get("players") or []:
                if p not in out:
                    out.append(p)
        return out

    def http_addr(self, addr: str) -> Optional[str]:
        """The gateway's HTTP/JSON surface (``host:http_port``) when its
        registration advertised one — the opsctl/status side-channel."""
        http_port = self.meta.get(addr, {}).get("http_port")
        if not http_port:
            return None
        host = addr.rpartition(":")[0] or "127.0.0.1"
        return f"{host}:{http_port}"
